"""Generate the per-command CLI reference (docs/commands.md) from the live
argparse tree, so the docs can never drift from the code: every command's
section IS its ``--help`` output, and a unit test regenerates the file and
fails when the checked-in copy is stale.

The reference ships a 69-file Sphinx user guide with hand-written
per-command pages (`/root/reference/docs/src/user/`); generating ours from
the parser keeps the same surface at zero maintenance cost.

Run as ``python -m orion_tpu.cli.docgen [output-path]``.
"""

import argparse


def _subparsers_of(parser):
    """name -> subparser mapping, or {} when the parser has none."""
    for action in parser._actions:
        if isinstance(action, argparse._SubParsersAction):
            # `choices` maps aliases to the same object; keep first name only.
            seen, out = set(), {}
            for name, sub in action.choices.items():
                if id(sub) not in seen:
                    seen.add(id(sub))
                    out[name] = sub
            return out
    return {}


def _command_section(name, parser, depth):
    title = "#" * depth + f" `{name}`"
    help_text = parser.format_help().rstrip()
    lines = [title, "", "```text", help_text, "```", ""]
    for sub_name, sub in sorted(_subparsers_of(parser).items()):
        lines.append(_command_section(f"{name} {sub_name}", sub, depth + 1))
    return "\n".join(lines)


def generate_markdown():
    import os

    from orion_tpu.cli import build_parser

    # argparse wraps help to the terminal width; pin it so the generated
    # file is identical no matter where it is regenerated.
    prev = os.environ.get("COLUMNS")
    os.environ["COLUMNS"] = "80"
    try:
        parser = build_parser()
        return _render(parser)
    finally:
        if prev is None:
            os.environ.pop("COLUMNS", None)
        else:
            os.environ["COLUMNS"] = prev


def _render(parser):
    parts = [
        "# Command reference",
        "",
        "Generated from the live argparse tree by `python -m"
        " orion_tpu.cli.docgen` — do not edit by hand"
        " (`tests/unit/test_cli_reference.py` fails when this file is"
        " stale).",
        "",
        "```text",
        parser.format_help().rstrip(),
        "```",
        "",
    ]
    for name, sub in sorted(_subparsers_of(parser).items()):
        parts.append(_command_section(name, sub, 2))
    return "\n".join(parts).rstrip() + "\n"


def main(argv=None):
    import sys

    argv = list(argv if argv is not None else sys.argv[1:])
    out_path = argv[0] if argv else "docs/commands.md"
    text = generate_markdown()
    with open(out_path, "w") as handle:
        handle.write(text)
    print(f"wrote {out_path} ({len(text.splitlines())} lines)")


if __name__ == "__main__":
    main()
