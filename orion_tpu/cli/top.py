"""`orion-tpu top`: live per-worker optimization-health dashboard.

No reference counterpart — part of the TPU build's optimization-health
subsystem (orion_tpu.health).  Polls the storage telemetry + health
channels and renders, per worker: producer round rate, heartbeat lag,
storage p99 latency, retries/reconnects, and the latest health record
(incumbent, GP marginal likelihood, trust-region length); plus a merged
regret-curve sparkline across the fleet.  ``--json`` is the one-shot
scripting mode: print one JSON snapshot and exit.
"""

import json
import sys
import time

from orion_tpu.cli.base import (
    add_experiment_args,
    build_all_experiments,
    build_from_args,
)

from orion_tpu.core.producer import Producer

SPARK_CHARS = "▁▂▃▄▅▆▇█"

#: A worker whose last metrics/health flush is older than this is marked
#: STALE: 3× the producer's snapshot-upsert interval — MAX-merged gauges
#: keep a quiet worker's last numbers alive, so the AGE (not the values)
#: is the liveness signal.
STALE_AFTER = 3.0 * Producer.METRICS_FLUSH_INTERVAL


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "top", help="live per-worker optimization-health dashboard"
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--json",
        action="store_true",
        help="print ONE machine-readable snapshot and exit (scripting mode)",
    )
    parser.add_argument(
        "-i",
        "--interval",
        type=float,
        default=2.0,
        metavar="seconds",
        help="refresh interval in live mode (default: 2s)",
    )
    parser.add_argument(
        "--iterations",
        type=int,
        default=0,
        metavar="N",
        help="render N frames then exit (default 0 = until interrupted)",
    )
    parser.add_argument(
        "--all",
        action="store_true",
        help="dashboard every experiment in the store (a serve gateway "
        "hosts many tenants), not just -n NAME",
    )
    parser.set_defaults(func=main)
    return parser


def sparkline(values, width=40):
    """Unicode sparkline of ``values`` downsampled to ``width`` columns."""
    values = [float(v) for v in values if v is not None]
    if not values:
        return ""
    if len(values) > width:
        # Keep the last point exact (the current incumbent) and stride the
        # rest — a regret curve's tail is the part being watched.
        stride = len(values) / float(width)
        values = [values[int(i * stride)] for i in range(width - 1)] + [values[-1]]
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(
        SPARK_CHARS[int((v - lo) / span * (len(SPARK_CHARS) - 1))] for v in values
    )


def _merged_percentile(histograms, prefix, p):
    """Worst p-th percentile (ms) over every histogram named under
    ``prefix`` — the per-worker "storage p99" number."""
    from orion_tpu.telemetry import histogram_percentile

    worst = None
    for name, hist in (histograms or {}).items():
        if not name.startswith(prefix) or not hist.get("count"):
            continue
        value = histogram_percentile(hist, p) * 1e3
        worst = value if worst is None else max(worst, value)
    return worst


def _host_device_ratio(histograms):
    """Mean ``producer.round`` / mean ``device.dispatch`` for one worker —
    the live wall-vs-device number next to the mem column.  The round
    CONTAINS the device window, so a healthy worker sits near 1.0 and the
    budget bar is ``1 + host_budget_factor()`` (orion_tpu.hostbudget —
    the SAME knob the bench gate and doctor DX004 read).  None when either
    histogram has no samples yet."""
    ratio = None
    round_hist = (histograms or {}).get("producer.round") or {}
    device_hist = (histograms or {}).get("device.dispatch") or {}
    round_count = int(round_hist.get("count", 0))
    device_count = int(device_hist.get("count", 0))
    if round_count > 0 and device_count > 0:
        round_mean = float(round_hist.get("sum", 0.0)) / round_count
        device_mean = float(device_hist.get("sum", 0.0)) / device_count
        if device_mean > 0:
            ratio = round(round_mean / device_mean, 2)
    return ratio


def _counter_sum(counters, *needles):
    """Sum every counter whose name contains one of ``needles`` (the
    reconnects counter is per-backend-prefixed: ``storage.network
    .reconnects``)."""
    total = 0
    found = False
    for name, value in (counters or {}).items():
        if any(needle in name for needle in needles):
            total += int(value)
            found = True
    return total if found else None


def snapshot_top(experiment, now=None):
    """One dashboard snapshot dict from the storage channels.

    ``workers`` merges the metrics-snapshot docs (rates, lags, p99s,
    retries) with each worker's LATEST health record (incumbent, GP fit,
    trust region); ``incumbent``/``regret_curve`` aggregate health records
    across the fleet in time order.  Round rate is derived from each
    worker's health-record timestamps (rounds per second over the window
    the records span), so a one-shot ``--json`` call needs no second poll.
    """
    now = time.time() if now is None else now
    storage = experiment.storage
    metrics_docs = storage.fetch_metrics(experiment)
    health_docs = storage.fetch_health(experiment)

    workers = {}
    for doc in metrics_docs:
        worker = str(doc.get("worker") or "?")
        counters = doc.get("counters") or {}
        gauges = doc.get("gauges") or {}
        histograms = doc.get("histograms") or {}
        rounds_hist = histograms.get("producer.round") or {}
        mem_bytes = gauges.get("memory.device_live_bytes")
        if mem_bytes is None:
            mem_bytes = gauges.get("memory.history_device_bytes")
        workers[worker] = {
            "rounds": int(rounds_hist.get("count", 0)),
            "round_rate": None,
            "heartbeat_lag_s": gauges.get("pacemaker.heartbeat_lag_s"),
            "storage_p99_ms": _merged_percentile(histograms, "storage.", 99),
            "retries": int(counters.get("storage.retries", 0)),
            "gave_up": int(counters.get("storage.gave_up", 0)),
            "reconnects": _counter_sum(counters, ".reconnects") or 0,
            "retraces": int(counters.get("jax.retraces", 0)),
            # Compiler plane (orion_tpu.compiler_plane): every XLA compile
            # this worker paid — retraces, prewarms, and append-jit forks.
            "compiles": int(counters.get("jax.compiles", 0)),
            # Device-memory accounting (orion_tpu.devmem): live device
            # buffer MB, falling back to the resident-history gauge when
            # live_arrays introspection was unavailable on the worker.
            "mem_mb": (
                round(float(mem_bytes) / 1e6, 3) if mem_bytes is not None else None
            ),
            # Live wall-vs-device: mean producer round over mean device
            # window — the per-worker view of the bench's host-budget gate.
            "host_device_ratio": _host_device_ratio(histograms),
            "last_seen_s": round(now - float(doc.get("time") or now), 3),
            # Age of the last metrics flush specifically (last_seen_s is
            # min-merged with health below): the staleness signal.
            "metrics_age_s": round(now - float(doc.get("time") or now), 3),
            "health_age_s": None,
            "stale": None,
            "health": None,
        }

    by_worker = {}
    for doc in health_docs:
        by_worker.setdefault(str(doc.get("worker") or "?"), []).append(doc)
    curve = []
    best = None
    best_doc = None
    for doc in health_docs:  # already time-ordered
        y = doc.get("best_y")
        if y is None:
            continue
        best = y if best is None else min(best, y)
        best_doc = doc if best == y else best_doc
        curve.append(best)
    for worker, docs in by_worker.items():
        entry = workers.setdefault(
            worker,
            {
                "rounds": len(docs),
                "round_rate": None,
                "heartbeat_lag_s": None,
                "storage_p99_ms": None,
                "retries": 0,
                "gave_up": 0,
                "reconnects": 0,
                "retraces": 0,
                "compiles": 0,
                "mem_mb": None,
                "host_device_ratio": None,
                "last_seen_s": None,
                "metrics_age_s": None,
                "health_age_s": None,
                "stale": None,
                "health": None,
            },
        )
        latest = docs[-1]
        entry["health"] = {
            key: latest.get(key)
            for key in (
                "round",
                "n_obs",
                "best_y",
                "gp_mll",
                "gp_ls_mean",
                "gp_noise",
                "acq_ei_max",
                "q_unique_frac",
                "tr_length",
                "tr_succ",
                "tr_fail",
                "rung_occupancy",
                "model_tier",
                "algo",
                # Serve-gateway fields (orion_tpu.serve): rounds produced
                # through a gateway report their coalesce width and the
                # gateway queue depth alongside the algorithm health.
                "serve_width",
                "serve_queue_depth",
                "serve_tenants",
            )
            if latest.get(key) is not None
        }
        entry["health_age_s"] = round(now - float(latest.get("time") or now), 3)
        entry["last_seen_s"] = round(
            now - float(latest.get("time") or now), 3
        )
        times = [float(d.get("time") or 0.0) for d in docs]
        window = max(times) - min(times)
        if len(docs) >= 2 and window > 0:
            entry["round_rate"] = round((len(docs) - 1) / window, 4)

    # Staleness: the freshest of the two flush channels is the worker's
    # liveness age; past 3× METRICS_FLUSH_INTERVAL the worker stopped
    # flushing (crash, hang, partition) and its MAX-merged gauges are
    # fossils — the marker says WHICH worker went quiet.
    for entry in workers.values():
        ages = [
            a for a in (entry["metrics_age_s"], entry["health_age_s"])
            if a is not None
        ]
        entry["flush_age_s"] = min(ages) if ages else None
        entry["stale"] = (
            entry["flush_age_s"] > STALE_AFTER
            if entry["flush_age_s"] is not None
            else None
        )

    # Doctor badge (orion_tpu.diagnosis): the same joined channels this
    # snapshot already fetched, run through the diagnosis rule catalog —
    # the dashboard leads with the verdict, not just the raw numbers.
    doctor = _doctor_block(experiment, metrics_docs, health_docs, now)

    # Compiler-plane gauges, MAX-merged across workers (the headroom line
    # cares about the worst plan anywhere in the fleet).
    compiler = {}
    for doc in metrics_docs:
        for key, value in (doc.get("gauges") or {}).items():
            if key.startswith("compiler."):
                compiler[key] = max(compiler.get(key, 0.0), float(value))

    return {
        "experiment": experiment.name,
        "version": experiment.version,
        "time": now,
        "workers": workers,
        "incumbent": {
            "best_y": best,
            "round": best_doc.get("round") if best_doc else None,
            "worker": best_doc.get("worker") if best_doc else None,
        },
        "regret_curve": curve,
        "health_records": len(health_docs),
        "doctor": doctor,
        "compiler": compiler,
    }


def _doctor_block(experiment, metrics_docs, health_docs, now):
    """Evaluate the doctor rules over the docs the snapshot already
    fetched (no second storage pass per frame); degrades to None rather
    than ever failing a dashboard frame."""
    try:
        from orion_tpu.diagnosis import Snapshot, run_rules
        from orion_tpu.diagnosis.snapshot import probe_replication
        from orion_tpu.telemetry import merge_snapshots

        snapshot = Snapshot(
            metrics=merge_snapshots(metrics_docs),
            per_worker=metrics_docs,
            health=health_docs,
            replication=probe_replication(experiment.storage),
            heartbeat=getattr(experiment, "heartbeat", None),
            stale_after=STALE_AFTER,
            now=now,
        )
        report = run_rules(snapshot)
        return {
            **report.summary(),
            "findings": [
                {
                    "rule": f.rule_id,
                    "severity": f.severity,
                    "message": f.message,
                }
                for f in report.findings
            ],
        }
    except Exception:  # pragma: no cover - a frame must render regardless
        return None


def doctor_badge(doctor):
    """One-line doctor verdict for the top/info headers."""
    if not doctor:
        return None
    if doctor["status"] == "ok":
        return "doctor: OK"
    rules = ", ".join(
        sorted({f["rule"] for f in doctor.get("findings") or ()})
    )
    return (
        f"doctor: {doctor['status'].upper()} "
        f"(critical: {doctor['critical']}, warn: {doctor['warn']}, "
        f"info: {doctor['info']}) [{rules}] — see `orion-tpu doctor`"
    )


def render_top(snap):
    """Human frame for one snapshot."""
    lines = [
        f"orion-tpu top — {snap['experiment']} v{snap['version']}   "
        f"workers: {len(snap['workers'])}   "
        f"health records: {snap['health_records']}"
    ]
    badge = doctor_badge(snap.get("doctor"))
    if badge:
        lines.append(badge)
    incumbent = snap["incumbent"]
    if incumbent["best_y"] is not None:
        lines.append(
            f"incumbent: {incumbent['best_y']:.6g} "
            f"(worker {incumbent['worker']}, round {incumbent['round']})"
        )
    if snap["regret_curve"]:
        lines.append(f"objective  {sparkline(snap['regret_curve'])}")
    lines.append("")
    from orion_tpu.hostbudget import round_budget_factor

    budget = round_budget_factor()
    header = (
        f"{'worker':<24} {'rounds':>6} {'rate/s':>7} {'age':>7} {'hb lag':>7} "
        f"{'sto p99':>8} {'mem MB':>8} {'h/d':>6} {'cmpl':>5} {'retry':>5} "
        f"{'reconn':>6} {'best_y':>12} {'gp_mll':>8} {'tr_len':>6}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    stale_workers = []
    over_budget = []
    for worker, row in sorted(snap["workers"].items()):
        health = row.get("health") or {}

        def fmt(value, spec):
            return format(value, spec) if value is not None else "-"

        # `!` marks a stale worker: no metrics/health flush for 3× the
        # flush interval — its row is the last thing it said, not news.
        age = row.get("flush_age_s")
        age_cell = (fmt(age, "6.1f") + ("!" if row.get("stale") else " "))[:7]
        if row.get("stale"):
            stale_workers.append(worker)
        # `!` marks a worker whose mean round exceeds the host-budget bar
        # (1 + host_budget_factor(), same knob as the bench gate / DX004).
        ratio = row.get("host_device_ratio")
        breached = ratio is not None and ratio > budget
        ratio_cell = (fmt(ratio, "5.2f") + ("!" if breached else " "))[:6]
        if breached:
            over_budget.append(worker)
        lines.append(
            f"{worker:<24} {row['rounds']:>6} "
            f"{fmt(row['round_rate'], '7.2f'):>7} "
            f"{age_cell:>7} "
            f"{fmt(row['heartbeat_lag_s'], '6.1f'):>7} "
            f"{fmt(row['storage_p99_ms'], '7.1f'):>8} "
            f"{fmt(row.get('mem_mb'), '8.1f'):>8} "
            f"{ratio_cell:>6} "
            f"{row.get('compiles', 0):>5} "
            f"{row['retries']:>5} {row['reconnects']:>6} "
            f"{fmt(health.get('best_y'), '12.5g'):>12} "
            f"{fmt(health.get('gp_mll'), '8.3f'):>8} "
            f"{fmt(health.get('tr_length'), '6.3f'):>6}"
        )
    if stale_workers:
        lines.append(
            f"STALE (no flush for > {STALE_AFTER:g}s): "
            + ", ".join(stale_workers)
        )
    if over_budget:
        lines.append(
            f"HOST-BUDGET BREACH (round > {budget:g}x device window): "
            + ", ".join(over_budget)
        )
    # HBM-headroom line from the MAX-merged compiler.* gauges — the same
    # rendering `orion-tpu profile` leads with (one code path, DX053's bar).
    from orion_tpu.cli.profile import hbm_line

    headroom = hbm_line(snap.get("compiler") or {})
    if headroom:
        lines.append(headroom)
    return "\n".join(lines)


def render_fleet(snaps, serve_config=None):
    """The ``--all`` frame: one row per experiment — the operator's view of
    a gateway hosting many tenants (who is producing, who is stalled, where
    the fleet incumbents sit) without running N ``top`` processes."""
    header = (
        f"{'experiment':<28} {'workers':>7} {'records':>7} {'rounds':>6} "
        f"{'best_y':>12} {'retry':>5} {'reconn':>6}"
    )
    lines = [f"orion-tpu top --all   experiments: {len(snaps)}"]
    from orion_tpu.cli.base import describe_serve_fleet, describe_storage_topology

    # probe=True: the fleet header shows per-shard epoch + replication lag
    # (one tiny seq request per node per frame — the operator's first
    # question when a shard looks wrong is "who is primary and how far
    # behind are the replicas").
    topology = describe_storage_topology(probe=True)
    if topology is not None:
        # The fleet the table shows spans every shard (the router resolved
        # it); the header says so.
        lines.append(topology)
    # The serve plane gets the same treatment: one `fleet` probe per
    # gateway per frame (answered inline by the handler, so it renders
    # even when a member's dispatcher is saturated).
    gateways = describe_serve_fleet(serve_config)
    if gateways is not None:
        lines.append(gateways)
    lines += ["", header, "-" * len(header)]
    for snap in snaps:
        rounds = sum(row["rounds"] for row in snap["workers"].values())
        retries = sum(row["retries"] for row in snap["workers"].values())
        reconnects = sum(
            row["reconnects"] for row in snap["workers"].values()
        )
        best = snap["incumbent"]["best_y"]
        lines.append(
            f"{snap['experiment'] + ' v' + str(snap['version']):<28} "
            f"{len(snap['workers']):>7} {snap['health_records']:>7} "
            f"{rounds:>6} "
            f"{format(best, '12.5g') if best is not None else '-':>12} "
            f"{retries:>5} {reconnects:>6}"
        )
    if not snaps:
        lines.append("(no experiments in storage)")
    return "\n".join(lines)


def main(args):
    if getattr(args, "all", False):
        # Re-resolved EVERY frame: a fleet dashboard watching a gateway
        # must pick up experiments attached after it started.
        snapshot = lambda: [  # noqa: E731
            snapshot_top(e) for e in build_all_experiments(args)
        ]
        from orion_tpu.cli.base import load_cli_config

        serve_config = load_cli_config(args).get("serve")
        render = lambda snaps: render_fleet(  # noqa: E731
            snaps, serve_config=serve_config
        )
        as_json = lambda snaps: {"experiments": snaps}  # noqa: E731
    else:
        experiment, _parser = build_from_args(
            args, need_user_args=False, allow_create=False, view=True
        )
        snapshot = lambda: snapshot_top(experiment)  # noqa: E731
        render = render_top
        as_json = lambda snap: snap  # noqa: E731
    if args.json:
        print(json.dumps(as_json(snapshot())))
        return 0
    frames = 0
    try:
        while True:
            # ANSI clear + home, one frame per interval.
            sys.stdout.write("\x1b[2J\x1b[H" + render(snapshot()) + "\n")
            sys.stdout.flush()
            frames += 1
            if args.iterations and frames >= args.iterations:
                return 0
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0
