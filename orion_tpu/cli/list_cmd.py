"""`orion-tpu list`: print the forest of experiments and their EVC trees.

Capability parity: reference `src/orion/core/cli/list.py` + `utils/pptree.py`
— each root experiment printed as an ASCII tree of its versions/branches.
"""

from orion_tpu.cli.base import add_experiment_args, load_cli_config
from orion_tpu.evc.experiment import ExperimentNode
from orion_tpu.storage.base import setup_storage


def add_subparser(subparsers):
    parser = subparsers.add_parser("list", help="list experiments as EVC trees")
    add_experiment_args(parser, with_user_args=False)
    parser.set_defaults(func=main)
    return parser


def print_tree(node, prefix="", is_last=True, is_root=True, out=print):
    connector = "" if is_root else ("└── " if is_last else "├── ")
    out(f"{prefix}{connector}{node.tree_name()}")
    children = node.children
    child_prefix = prefix if is_root else prefix + ("    " if is_last else "│   ")
    for i, child in enumerate(children):
        print_tree(child, child_prefix, i == len(children) - 1, is_root=False, out=out)


def main(args):
    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    query = {}
    if config.get("name"):
        query["name"] = config["name"]
    experiments = storage.fetch_experiments(query)
    roots = [
        e for e in experiments if not (e.get("refers") or {}).get("parent_id")
    ]
    if not roots and experiments:
        roots = experiments  # orphaned branches: list them flat
    for doc in sorted(roots, key=lambda e: (e["name"], e.get("version", 1))):
        print_tree(ExperimentNode(storage, doc))
    if not experiments:
        print("No experiment found")
    return 0
