"""Shared CLI argument groups and experiment bootstrapping.

Capability parity: reference `src/orion/core/cli/base.py` — the common
``-n/--name``, ``--version``, ``-c/--config``, ``--debug`` group plus the
trailing ``user_args`` remainder, and the helper that turns parsed args into
a built Experiment (storage setup -> prior extraction -> build/branch).
"""

import os

import yaml

from orion_tpu.config import resolve_config
from orion_tpu.core.experiment import build_experiment
from orion_tpu.io.cmdline import CommandLineParser
from orion_tpu.io.versioning import hash_config_file, infer_versioning_metadata
from orion_tpu.storage.base import setup_storage
from orion_tpu.utils.exceptions import NoConfigurationError


def add_experiment_args(parser, with_user_args=True):
    group = parser.add_argument_group("experiment")
    group.add_argument("-n", "--name", help="experiment name")
    group.add_argument("--exp-version", type=int, default=None, help="experiment version")
    group.add_argument(
        "-u",
        "--user",
        default=None,
        help="user namespace (defaults to the system user; experiments are "
        "stored under metadata.user and -u filters lookups to that user)",
    )
    group.add_argument(
        "-c", "--config", metavar="path", help="orion-tpu configuration file (yaml)"
    )
    group.add_argument(
        "--debug", action="store_true", help="use an in-memory non-persistent storage"
    )
    group.add_argument(
        "--storage-path", default=None,
        help="path of the local storage file (.sqlite/.db selects the "
        "SQLite backend, anything else the pickled one)"
    )
    group.add_argument(
        "--manual-resolution",
        action="store_true",
        help="resolve branching conflicts interactively instead of automatically",
    )
    group.add_argument(
        "--branch-to",
        default=None,
        metavar="name",
        help="on a branching event, give the child experiment this name "
        "instead of a version bump under the same name",
    )
    if with_user_args:
        import argparse

        parser.add_argument(
            "user_args",
            nargs=argparse.REMAINDER,
            metavar="command",
            help="user script and its arguments, with priors as name~'expr'",
        )
    return group


def _storage_type_for_path(path):
    """Backend for --storage-path (header-sniffed; see sqlite_path_selected)."""
    from orion_tpu.storage.sqlitedb import sqlite_path_selected

    return "sqlite" if sqlite_path_selected(path) else "pickled"


def load_cli_config(args):
    """Merge config sources: defaults < env < config file < cmdline.
    Sectioned spellings (`experiment:`, `producer:`, `database:`) are
    normalized inside resolve_config — for every file layer, not just -c."""
    file_config = {}
    if getattr(args, "config", None):
        with open(args.config) as handle:
            file_config = yaml.safe_load(handle) or {}
    cmd_config = {
        key: value
        for key, value in {
            "name": getattr(args, "name", None),
            "version": getattr(args, "exp_version", None),
            "user": getattr(args, "user", None),
            "max_trials": getattr(args, "max_trials", None),
            "pool_size": getattr(args, "pool_size", None),
            "working_dir": getattr(args, "working_dir", None),
            "max_broken": getattr(args, "max_broken", None),
            "heartbeat": getattr(args, "heartbeat", None),
            "max_idle_time": getattr(args, "max_idle_time", None),
            "pipeline_depth": getattr(args, "pipeline_depth", None),
        }.items()
        if value is not None
    }
    storage_override = None
    if getattr(args, "debug", False):
        storage_override = {"type": "memory"}
    elif getattr(args, "storage_path", None):
        storage_override = {
            "type": _storage_type_for_path(args.storage_path),
            "path": args.storage_path,
        }
    config = resolve_config(file_config, cmd_config, storage_override)
    # `telemetry:` in any config layer flips the process-wide registry AND
    # the flight recorder (one switch for the whole observability layer); a
    # None (unset) leaves whatever ORION_TPU_TELEMETRY / ORION_TPU_FLIGHT
    # decided at import.
    if config.get("telemetry") is not None:
        from orion_tpu.health import FLIGHT
        from orion_tpu.telemetry import TELEMETRY

        if config["telemetry"]:
            TELEMETRY.enable()
            FLIGHT.enable()
        else:
            TELEMETRY.disable()
            FLIGHT.disable()
    # `metrics_port:` requests the worker-side /metrics + /healthz daemon
    # (orion_tpu.metrics) — same plane `orion-tpu serve --metrics-port`
    # attaches to the gateway.  Resolved to the env spelling here (so
    # `hunt --n-workers` children inherit it too) and STARTED only where a
    # worker loop actually runs (workon) — read-only commands like `info`
    # or `top` must not bind the port just because the config names it.
    if config.get("metrics_port") is not None:
        os.environ.setdefault(
            "ORION_TPU_METRICS_PORT", str(int(config["metrics_port"]))
        )
    # `doctor_interval:` rides the same channel as metrics_port: resolved
    # to the env spelling (so `hunt --n-workers` children inherit it) and
    # STARTED only where a worker loop runs (workon) — a read-only
    # command must not spin a diagnosis thread just because the config
    # names it.
    if config.get("doctor_interval") is not None:
        os.environ.setdefault(
            "ORION_TPU_DOCTOR_INTERVAL", str(float(config["doctor_interval"]))
        )
    return config


def _default_user():
    import getpass

    try:
        return getpass.getuser()
    except Exception:  # pragma: no cover - no passwd entry
        return os.environ.get("USER", "unknown")


def build_all_experiments(args, view=True):
    """``--all`` resolution shared by audit/top/info: the name-less
    config/storage bootstrap, then EVERY experiment in the store built for
    read-only inspection (a gateway hosts many tenants; fleet commands must
    not require ``-n NAME`` per experiment).  Sorted by (name, version)."""
    from orion_tpu.core.experiment import ExperimentView

    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    docs = storage.fetch_experiments({})
    experiments = []
    for doc in sorted(docs, key=lambda d: (d["name"], d.get("version", 1))):
        experiment = build_experiment(
            storage, doc["name"], version=doc.get("version")
        )
        if view:
            experiment = ExperimentView(experiment)
        experiments.append(experiment)
    return experiments


def describe_storage_topology(probe=False):
    """One-line sharded-topology summary of the ACTIVE storage singleton
    (``audit``/``info``/``top`` fleet views print it so an operator can
    tell at a glance WHICH plane answered), or None when the storage is
    not the consistent-hash router.

    ``probe=True`` additionally probes every shard node's replication
    position (one tiny ``seq`` request each) and annotates each shard
    with its current epoch and worst replica lag (``ep2 lag:3``) — the
    first thing an operator needs when a promotion or a lagging replica
    is suspected.  Probing also publishes the ``netdb.replication.lag.*``
    gauges."""
    from orion_tpu.storage.base import _storage_singleton

    db = getattr(_storage_singleton, "db", None)
    describe = getattr(db, "describe_topology", None)
    if describe is None:
        return None
    topology = describe()
    health = {}
    if probe:
        replication_health = getattr(db, "replication_health", None)
        if replication_health is not None:
            try:
                health = {h["index"]: h for h in replication_health()}
            except Exception:  # pragma: no cover - a dead fleet still renders
                health = {}
    parts = []
    for shard in topology["shards"]:
        part = f"s{shard['index']}={shard['address']}"
        if shard["replicas"]:
            part += f"(+{len(shard['replicas'])}r)"
        probed = health.get(shard["index"])
        if probed is not None:
            if probed.get("epoch"):
                part += f" ep{probed['epoch']}"
            if probed.get("max_lag") is not None:
                part += f" lag:{probed['max_lag']}"
            if probed.get("error"):
                part += " DOWN"
            elif probed.get("primary") != shard["address"]:
                # A promoted replica serves this shard now.
                part += f"->{probed['primary']}"
        parts.append(part)
    return (
        f"storage: {len(topology['shards'])} shard(s) [{', '.join(parts)}] "
        f"vnodes={topology['vnodes']} replica_reads="
        f"{'on' if topology['replica_reads'] else 'off'}"
    )


def describe_serve_fleet(serve_config, timeout=2.0):
    """One-line gateway-fleet summary for the ``--all`` fleet views
    (``top``/``info``): each configured gateway probed with a single
    ``fleet`` request per frame — answered inline by the handler thread,
    never queued behind the dispatcher backlog, so the header renders
    even when a member is saturated.  Per member: tenant count, queue
    depth, membership epoch, and any in-flight handoff state
    (``FENCED``/``moved``); a dead member renders ``DOWN`` instead of
    erasing the row.  Works against a pre-fleet single gateway too (it
    answers as a one-member fleet).  Returns None when the config names
    no gateway."""
    if not serve_config:
        return None
    from orion_tpu.serve.client import GatewayClient, parse_address
    from orion_tpu.serve.fleet import parse_serve_addresses
    from orion_tpu.storage.base import resolve_wire_secret
    from orion_tpu.utils.exceptions import DatabaseError

    try:
        addresses = parse_serve_addresses(serve_config)
        secret = resolve_wire_secret(
            serve_config, env_prefix="ORION_SERVE", what="serve gateway"
        )
    except DatabaseError:
        return None

    results = {}

    def _probe(address):
        host, port = parse_address(address)
        client = GatewayClient(
            host=host,
            port=port,
            secret=secret,
            timeout=timeout,
            retry={"max_attempts": 1, "deadline": timeout},
        )
        try:
            results[address] = client.fleet()
        except Exception as exc:
            results[address] = {"error": str(exc)}
        finally:
            client.close()

    import threading

    threads = [
        threading.Thread(target=_probe, args=(address,), daemon=True)
        for address in addresses
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout + 1.0)

    parts = []
    epochs = set()
    for index, address in enumerate(addresses):
        snap = results.get(address) or {"error": "no answer"}
        part = f"g{index}={address}"
        if "error" in snap:
            part += " DOWN"
        else:
            part += f" t:{snap.get('tenants', 0)} q:{snap.get('queue_depth', 0)}"
            epochs.add(int(snap.get("epoch", 0)))
            if snap.get("fenced"):
                part += f" FENCED:{snap['fenced']}"
            if snap.get("moved"):
                part += f" moved:{snap['moved']}"
        parts.append(part)
    epoch = ""
    if epochs:
        # Members disagreeing on the epoch is the membership-drift smell
        # DX007's runbook sends operators here to check.
        epoch = (
            f" epoch={epochs.pop()}"
            if len(epochs) == 1
            else " epoch=SPLIT"
        )
    return f"serve: {len(addresses)} gateway(s) [{', '.join(parts)}]{epoch}"


def build_from_args(args, need_user_args=True, allow_create=True, view=False):
    """CLI args -> (experiment, cmdline_parser), with storage wired up.

    ``allow_create=False`` (lookup commands: info, status, insert) only
    loads existing experiments — a typo'd name must never persist a ghost.
    ``view=True`` additionally wraps the result in a read-only
    :class:`ExperimentView` (info/status paths).
    """
    config = load_cli_config(args)
    if not config.get("name"):
        raise NoConfigurationError("an experiment name is required (-n/--name)")
    storage = setup_storage(config["storage"], force=True)

    parser = CommandLineParser(config_prefix=config.get("user_script_config", "config"))
    user_args = list(getattr(args, "user_args", []) or [])
    priors = parser.parse(user_args)
    existing = []
    if not allow_create or (need_user_args and not user_args):
        # Check BEFORE build_experiment would persist an empty experiment —
        # including the requested version, or a typo'd --exp-version would
        # pass the name check and still create a ghost.
        query = {"name": config["name"]}
        if config.get("version") is not None:
            query["version"] = config["version"]
        if config.get("user"):
            # -u/--user namespacing (reference `cli/base.py:94`): an
            # explicit user restricts the lookup to that user's experiments.
            query["metadata.user"] = config["user"]
        existing = storage.fetch_experiments(query)
        if not existing:
            if not allow_create:
                raise NoConfigurationError(
                    f"no experiment matching {query} found"
                )
            raise NoConfigurationError(
                "a user script command is required for a new experiment"
            )

    if not allow_create:
        # Lookup commands (info/status/insert) must never branch: their
        # user_args are not a command line (insert passes `x=1.2`
        # assignments) and a lookup must not mutate the experiment tree —
        # so pass NO config at all, only the identity.
        latest = max(existing, key=lambda d: d.get("version", 1))
        experiment = build_experiment(
            storage,
            config["name"],
            version=latest.get("version"),
            user=config.get("user"),
        )
        if view:
            from orion_tpu.core.experiment import ExperimentView

            experiment = ExperimentView(experiment)
        return experiment, parser

    metadata = {
        "user_args": user_args,
        "parser_state": parser.state_dict(),
        # Experiments are namespaced per user (reference stores
        # metadata.user on every experiment, `resolve_config.py`).
        "user": config.get("user") or _default_user(),
    }
    script_path = None
    config_file_path = parser.config_file_path
    if user_args:
        script_path = os.path.abspath(user_args[0])
        metadata["user_script"] = script_path
    else:
        # Argless resume (`hunt -n name`): the code identity must still be
        # checked, or edits to the stored script silently contaminate the
        # old version.  Recover the script/config paths from the stored
        # experiment (fetched above when user_args is empty; resume targets
        # the latest version).
        stored_meta = {}
        if existing:
            latest = max(existing, key=lambda d: d.get("version", 1))
            stored_meta = latest.get("metadata") or {}
        script_path = stored_meta.get("user_script")
        stored_parser = stored_meta.get("parser_state") or {}
        config_file_path = config_file_path or stored_parser.get("config_file_path")
    if script_path:
        vcs = infer_versioning_metadata(script_path)
        if vcs is not None:
            metadata["vcs"] = vcs
    if config_file_path:
        config_hash = hash_config_file(config_file_path)
        if config_hash is not None:
            metadata["script_config_hash"] = config_hash
    experiment = build_experiment(
        storage,
        config["name"],
        version=config.get("version"),
        user=config.get("user"),
        priors=priors or None,
        metadata=metadata,
        max_trials=config.get("max_trials"),
        pool_size=config.get("pool_size"),
        working_dir=config.get("working_dir"),
        max_broken=config.get("max_broken"),
        algorithms=config.get("algorithms"),
        strategy=config.get("strategy"),
        branch_config={
            "manual_resolution": getattr(args, "manual_resolution", False),
            "branch_to": getattr(args, "branch_to", None),
        },
    )
    # Worker-level knobs, not part of the experiment's stored identity
    # (reference keeps them in the global worker config, `core/__init__.py:93`):
    # heartbeat governs this worker's lost-trial sweep threshold,
    # max_idle_time its producer stall budget (consumed by workon).
    experiment.heartbeat = float(config.get("heartbeat", experiment.heartbeat))
    experiment.max_idle_time = float(
        config.get("max_idle_time", experiment.max_idle_time)
    )
    # Speculative-pipeline depth rides the same worker-level channel (the
    # Producer resolves None through ORION_TPU_PIPELINE_DEPTH to 1).
    if config.get("pipeline_depth") is not None:
        experiment.pipeline_depth = int(config["pipeline_depth"])
    # Suggest-gateway selection is a worker-level knob too (the same
    # experiment may run served on one box and local on another):
    # instantiate() builds a RemoteAlgorithm when this is set.
    if config.get("serve") is not None:
        experiment.serve_config = config.get("serve")
    # Resuming: rebuild the parser from the stored experiment metadata so the
    # original template (and config file) is used even without user args.
    if not user_args:
        state = experiment.metadata.get("parser_state")
        if state and (state.get("template") or state.get("priors")):
            parser = CommandLineParser.from_state(state)
        elif experiment.metadata.get("user_args"):
            # Reference-Oríon experiments (db load migration) store the raw
            # command instead of parser state — same prior DSL, so reparse
            # it (reference metadata schema: experiment.py:120-155).
            parser = CommandLineParser()
            parser.parse(list(experiment.metadata["user_args"]))
        elif need_user_args:
            raise NoConfigurationError(
                f"experiment {experiment.name!r} has no stored command to resume; "
                "provide the user script on the command line"
            )
    return experiment, parser
