import sys

from orion_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
