"""`orion-tpu insert`: manually register a trial at fixed parameter values.

Capability parity: reference `src/orion/core/cli/insert.py` — values given
as ``name=value`` args, validated against the experiment's space; dimensions
with a default may be omitted.
"""

import re

from orion_tpu.cli.base import add_experiment_args, build_from_args
from orion_tpu.client.manual import insert_trials
from orion_tpu.space.dims import NotSet

ASSIGN_RE = re.compile(r"^(?P<name>[\w\-/\.]+)=(?P<value>.*)$")


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "insert", help="insert a trial with fixed values (name=value ...)"
    )
    add_experiment_args(parser)
    parser.set_defaults(func=main)
    return parser


def parse_assignments(user_args, space):
    params = {}
    for token in user_args:
        match = ASSIGN_RE.match(token)
        if not match:
            raise ValueError(
                f"Bad assignment {token!r}; expected name=value"
            )
        name = match.group("name")
        if not name.startswith("/"):
            name = "/" + name
        if name not in space.keys():
            raise ValueError(
                f"Unknown dimension {name!r}; space has {space.keys()}"
            )
        dim = space[name]
        params[name] = dim.cast(match.group("value"))
    # Fill defaults for unspecified dims (reference `cli/insert.py:57-86`);
    # fidelity dims default to their maximum budget.
    from orion_tpu.space.dims import Fidelity

    for dim in space:
        if dim.name in params:
            continue
        if isinstance(dim, Fidelity):
            params[dim.name] = dim.high
        elif dim.default_value is NotSet:
            raise ValueError(
                f"Dimension {dim.name!r} has no default and was not given"
            )
        else:
            params[dim.name] = dim.default_value
    return params


def main(args):
    experiment, _parser = build_from_args(args, need_user_args=False, allow_create=False)
    if experiment.space is None:
        raise ValueError(f"experiment {experiment.name!r} has no search space")
    params = parse_assignments(args.user_args, experiment.space)
    insert_trials(experiment, [params])
    print(f"Inserted 1 trial into {experiment.name} (v{experiment.version})")
    return 0
