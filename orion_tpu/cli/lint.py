"""`orion-tpu lint`: check the project's own invariants statically.

No reference counterpart — the TPU build's conventions (retrace-free
fused steps, retry-covered storage ops, allocation-free disabled
telemetry, acyclic lock order) are enforceable from the AST, and this
command is how CI and the bench preflight enforce them
(``orion_tpu.analysis``; rule catalog in ``docs/static_analysis.md``).
Exit code 0 = clean, 1 = violations found, 2 = bad path argument.
"""


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "lint", help="statically check orion-tpu invariant conventions"
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=None,
        metavar="path",
        help="files or directories to lint (default: the installed "
        "orion_tpu package)",
    )
    parser.add_argument(
        "--select",
        default=None,
        metavar="RULES",
        help="comma-separated rule-id prefixes to run (e.g. JIT,STO002)",
    )
    parser.add_argument(
        "--ignore",
        default=None,
        metavar="RULES",
        help="comma-separated rule-id prefixes to skip",
    )
    parser.add_argument(
        "--format",
        choices=("human", "json"),
        default="human",
        help="output format (default: human)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    parser.set_defaults(func=main)
    return parser


def _split(value):
    return [part.strip() for part in value.split(",") if part.strip()] if value else None


def main(args):
    import os

    from orion_tpu.analysis import format_human, format_json, rule_catalog, run_lint

    if args.list_rules:
        for rule_id, name, description in rule_catalog():
            print(f"{rule_id}  {name}")
            print(f"    {description}")
        return 0

    paths = args.paths
    if not paths:
        import orion_tpu

        paths = [os.path.dirname(os.path.abspath(orion_tpu.__file__))]
    try:
        diagnostics = run_lint(
            paths, select=_split(args.select), ignore=_split(args.ignore)
        )
    except ValueError as exc:  # typo'd --select/--ignore prefix
        import sys

        print(f"ERROR: {exc}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(format_json(diagnostics))
    else:
        print(format_human(diagnostics))
    # A bad ARGUMENT (missing path, non-Python file, empty directory) is a
    # usage error, not a lint verdict: exit 2, keyed off the engine's own
    # LNT003 findings so path validation lives in ONE place (run_lint) and
    # the tree is walked exactly once per invocation.  An LNT003 on a file
    # merely discovered under a valid directory argument (e.g. permission
    # denied mid-walk) is a lint finding like any other: exit 1.
    from orion_tpu.analysis.engine import UNREADABLE_PATH

    arg_paths = set(paths)
    if any(
        d.rule_id == UNREADABLE_PATH and d.path in arg_paths for d in diagnostics
    ):
        return 2
    return 1 if diagnostics else 0
