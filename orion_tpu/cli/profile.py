"""`orion-tpu profile`: the compiler plane of one experiment.

No reference counterpart — the observability face of
:mod:`orion_tpu.compiler_plane`.  Workers running with telemetry enabled
record every fused-plan/stacked/append compilation as a ``jax.compile``
span (family, kind, wall ms, full static-arg signature in args) and every
attributed retrace as a flight ``jax.retrace`` event (mirrored into the
span channel as ``flight.jax.retrace``, naming the changed statics); both
flush through the storage channel like every other span.  This command
merges them back into:

- the **compile table** — one row per recorded compilation, with family,
  kind (compile / prewarm / retrace), wall ms, and signature;
- the **retrace attribution table** — one row per retrace, naming the
  changed statics (``fit_bucket 64→128``) and whether a completed prewarm
  recorded that exact signature (a yes is a prewarm bug — doctor DX052);
- the **HBM line** — max per-plan footprint vs device capacity and the
  predicted HBM-bound q, from the ``compiler.*`` gauges the workers
  flushed (ROADMAP item 1's open tail as one line);
- the local :data:`~orion_tpu.compiler_plane.COMPILE_REGISTRY` summary,
  when THIS process compiled anything (bench harnesses and tests that
  call ``main`` after running plans in-process).

``--capture DIR`` wraps the local registry's cost/memory analysis pass —
each pending analysis is an AOT ``lower().compile()``, a real XLA compile
— in the shared :func:`~orion_tpu.compiler_plane.profiler_capture`
context, the SAME helper ``hunt --profile`` uses, so both commands print
the identical artifact summary line and the captured trace shows the
compiles themselves (inspect with TensorBoard / xprof).
"""

import json

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "profile", help="show the compiler plane of an experiment"
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--json",
        action="store_true",
        help="machine-readable JSON instead of the tables",
    )
    parser.add_argument(
        "--capture",
        metavar="DIR",
        default=None,
        help="run the local registry's cost/memory analysis pass under a "
        "jax.profiler trace written to DIR (the same capture helper as "
        "`hunt --profile`)",
    )
    parser.set_defaults(func=main)
    return parser


#: The compiler-plane names this command extracts from the span channel.
_COMPILE_SPAN = "jax.compile"
_RETRACE_SPAN = "flight.jax.retrace"


def _compile_rows(spans):
    """Compile-table rows off the flushed ``jax.compile`` spans."""
    rows = []
    for span in spans:
        if span.get("name") != _COMPILE_SPAN:
            continue
        args = span.get("args") or {}
        rows.append(
            {
                "worker": str(span.get("worker") or "?"),
                "family": args.get("family", "?"),
                "kind": args.get("kind", "?"),
                "compile_ms": round(float(span.get("dur") or 0.0) * 1e3, 3),
                "signature": args.get("signature", ""),
            }
        )
    return rows


def _retrace_rows(spans):
    """Attribution rows off the mirrored flight ``jax.retrace`` events."""
    rows = []
    for span in spans:
        if span.get("name") != _RETRACE_SPAN:
            continue
        args = span.get("args") or {}
        rows.append(
            {
                "worker": str(span.get("worker") or "?"),
                "family": args.get("family", "?"),
                "changed": args.get("changed", "?"),
                "covered_by_prewarm": bool(args.get("covered_by_prewarm")),
                "signature": args.get("signature", ""),
            }
        )
    return rows


def _format_table(rows, columns):
    """Fixed-width table; column order and headers from ``columns``."""
    if not rows:
        return []
    widths = {
        key: max(len(key), *(len(str(row.get(key, ""))) for row in rows))
        for key in columns
    }
    lines = ["  ".join(key.ljust(widths[key]) for key in columns)]
    for row in rows:
        lines.append(
            "  ".join(str(row.get(key, "")).ljust(widths[key]) for key in columns)
        )
    return lines


def _gb(value):
    return f"{float(value) / 1e9:.2f}GB"


def hbm_line(gauges):
    """``HBM: plan max 1.25GB of 16.00GB (7.8%) — predicted HBM-bound q
    4096`` from merged ``compiler.*`` gauges, or None without footprints."""
    footprint = gauges.get("compiler.hbm_bytes_max")
    if not footprint:
        return None
    capacity = gauges.get("compiler.hbm_capacity_bytes")
    parts = [f"HBM: plan max {_gb(footprint)}"]
    if capacity:
        parts.append(
            f"of {_gb(capacity)} ({100.0 * footprint / capacity:.1f}%)"
        )
    bound_q = gauges.get("compiler.hbm_bound_q")
    if bound_q:
        parts.append(f"— predicted HBM-bound q {int(bound_q)}")
    return " ".join(parts)


def _merged_metrics(experiment):
    from orion_tpu.telemetry import merge_snapshots

    try:
        docs = experiment.storage.fetch_metrics(experiment)
    except Exception:
        docs = None
    if not docs:
        return {}, {}
    merged = merge_snapshots(docs)
    return merged.get("counters") or {}, merged.get("gauges") or {}


def _local_registry_block(capture_dir):
    """The in-process registry summary — empty unless THIS process
    compiled plans.  With ``capture_dir``, the analysis pass (each one an
    AOT second compile) runs under the shared profiler capture."""
    from orion_tpu.compiler_plane import COMPILE_REGISTRY, profiler_capture

    summary = COMPILE_REGISTRY.summary()
    if not summary["compiles"] and capture_dir is None:
        return None
    if capture_dir is not None:
        with profiler_capture(capture_dir):
            analysis = COMPILE_REGISTRY.analyze_all()
    else:
        analysis = COMPILE_REGISTRY.analyze_all()
    summary = COMPILE_REGISTRY.summary()
    summary["analysis"] = analysis
    return summary


def main(args):
    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    try:
        spans = experiment.storage.fetch_spans(experiment) or []
    except Exception:
        spans = []
    compiles = _compile_rows(spans)
    retraces = _retrace_rows(spans)
    counters, gauges = _merged_metrics(experiment)
    registry = _local_registry_block(args.capture)
    if args.json:
        print(
            json.dumps(
                {
                    "experiment": experiment.name,
                    "counters": {
                        key: counters[key]
                        for key in sorted(counters)
                        if key.startswith(("jax.", "compiler."))
                    },
                    "gauges": {
                        key: gauges[key]
                        for key in sorted(gauges)
                        if key.startswith("compiler.")
                    },
                    "compiles": compiles,
                    "retraces": retraces,
                    "registry": registry,
                },
                default=str,
            )
        )
        return 0
    out = [f"compiler plane — experiment {experiment.name!r}"]
    totals = " ".join(
        f"{key}={counters[key]}"
        for key in (
            "jax.compiles",
            "jax.retraces",
            "jax.retraces.attributed",
            "jax.retraces.prewarm_covered",
        )
        if key in counters
    )
    if totals:
        out.append(totals)
    line = hbm_line(gauges)
    if line:
        out.append(line)
    if compiles:
        out.append("")
        out.append("compiles:")
        out.extend(
            _format_table(
                compiles, ("worker", "family", "kind", "compile_ms", "signature")
            )
        )
    if retraces:
        out.append("")
        out.append("retrace attribution:")
        out.extend(
            _format_table(
                retraces, ("worker", "family", "changed", "covered_by_prewarm")
            )
        )
    if registry:
        out.append("")
        out.append(
            "local registry: "
            f"{registry['compiles']} compiles, "
            f"{registry['retraces_attributed']}/{registry['retraces']} "
            "retraces attributed"
        )
        per_plan = registry.get("per_plan") or []
        out.extend(
            _format_table(
                per_plan,
                ("family", "kind", "compile_ms", "flops", "hbm_bytes", "signature"),
            )
        )
    if not (compiles or retraces or registry):
        out.append(
            "no compiler-plane data — run the hunt with ORION_TPU_TELEMETRY=1 "
            "(or `telemetry: true` in the config) to collect it"
        )
        print("\n".join(out))
        return 1
    print("\n".join(out))
    return 0
