"""`orion-tpu trace`: export an experiment's merged telemetry trace.

No reference counterpart — part of the TPU build's unified telemetry
subsystem (orion_tpu.telemetry).  Workers running with telemetry enabled
flush their span records through the storage channel every producer round;
this command merges every worker's spans into one Chrome trace-event JSON
(load it in Perfetto / chrome://tracing — each worker process appears as
its own track, and the pipelined storage commit shows up as a
``storage.commit`` span running concurrently with the ``device.dispatch``
window) or, with ``--format jsonl``, one span per line for ad-hoc tooling.
"""

import json

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "trace", help="export the merged telemetry trace of an experiment"
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--out",
        default="trace.json",
        metavar="path",
        help="output file (default: trace.json)",
    )
    parser.add_argument(
        "--format",
        choices=("chrome", "jsonl"),
        default="chrome",
        help="chrome = trace-event JSON for Perfetto (default); "
        "jsonl = one span object per line",
    )
    parser.set_defaults(func=main)
    return parser


def main(args):
    from orion_tpu.telemetry import write_chrome_trace

    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    spans = experiment.storage.fetch_spans(experiment)
    if not spans:
        print(
            f"no spans recorded for experiment {experiment.name!r} — run the "
            "hunt with ORION_TPU_TELEMETRY=1 (or `telemetry: true` in the "
            "config) to collect them"
        )
        return 1
    if args.format == "jsonl":
        with open(args.out, "w") as handle:
            for span in spans:
                handle.write(json.dumps(span) + "\n")
    else:
        write_chrome_trace(args.out, spans)
    workers = {s.get("worker") for s in spans if s.get("worker")}
    print(
        f"wrote {len(spans)} spans from {max(len(workers), 1)} worker(s) "
        f"to {args.out}"
    )
    return 0
