"""`orion-tpu trace`: export an experiment's merged telemetry trace.

No reference counterpart — part of the TPU build's unified telemetry
subsystem (orion_tpu.telemetry).  Workers running with telemetry enabled
flush their span records through the storage channel every producer round;
this command merges every worker's spans into one Chrome trace-event JSON
(load it in Perfetto / chrome://tracing — each worker process appears as
its own track, and the pipelined storage commit shows up as a
``storage.commit`` span running concurrently with the ``device.dispatch``
window) or, with ``--format jsonl``, one span per line for ad-hoc tooling.

``--distributed`` additionally joins the SERVER side of the experiment's
traces (the netdb server flushes its adopted-context spans under the
reserved ``__server__`` id; the merge matches them back by trace_id), so
the exported file carries cross-process flow arrows — client commit →
server apply, request → coalesced gateway dispatch.  ``--attribute``
additionally prints the per-trace critical-path table: each sampled
round's wall time bucketed into client-host / wire / server-host /
device (orion_tpu.tracing) — ROADMAP item 2's burn-down as a
measurement.
"""

import json

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "trace", help="export the merged telemetry trace of an experiment"
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--out",
        default="trace.json",
        metavar="path",
        help="output file (default: trace.json)",
    )
    parser.add_argument(
        "--format",
        choices=("chrome", "jsonl"),
        default="chrome",
        help="chrome = trace-event JSON for Perfetto (default); "
        "jsonl = one span object per line",
    )
    parser.add_argument(
        "--distributed",
        action="store_true",
        help="merge server-side spans (netdb __server__ channel) into the "
        "experiment's traces by trace_id — cross-process flow arrows",
    )
    parser.add_argument(
        "--attribute",
        action="store_true",
        help="print the per-trace critical-path attribution table "
        "(client-host / wire / server-host / device) in addition to "
        "writing the trace file",
    )
    parser.set_defaults(func=main)
    return parser


def main(args):
    from orion_tpu.telemetry import write_chrome_trace
    from orion_tpu.tracing import collect_distributed_spans, format_attribution

    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    if args.distributed or args.attribute:
        spans = collect_distributed_spans(experiment.storage, experiment)
    else:
        spans = experiment.storage.fetch_spans(experiment)
    if not spans:
        print(
            f"no spans recorded for experiment {experiment.name!r} — run the "
            "hunt with ORION_TPU_TELEMETRY=1 (or `telemetry: true` in the "
            "config) to collect them"
        )
        return 1
    if args.format == "jsonl":
        with open(args.out, "w") as handle:
            for span in spans:
                handle.write(json.dumps(span) + "\n")
    else:
        write_chrome_trace(args.out, spans)
    workers = {s.get("worker") for s in spans if s.get("worker")}
    print(
        f"wrote {len(spans)} spans from {max(len(workers), 1)} worker(s) "
        f"to {args.out}"
    )
    if args.attribute:
        # Next to the file, never instead of it: a scripted pipeline that
        # passed --out must still find its artifact.
        print(format_attribution(spans))
    return 0
