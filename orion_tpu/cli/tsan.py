"""`orion-tpu tsan`: run a command under the runtime concurrency sanitizer.

No reference counterpart — the TPU build's multithreaded serving/storage
stack (gateway dispatcher, prewarm daemon, netdb driver, pacemaker) needs
its lock discipline *proved at runtime*, not just statically screened
(``orion_tpu.analysis.sanitizer``; the static half is ``orion-tpu lint``'s
``LCK*`` rules).  The child process runs with instrumented lock/event
shims, vector-clock race detection over the annotated shared cells, and
the seeded interleaving explorer; its observed lock graph is then
cross-checked against the static LCK graph (runtime edges the static
resolver missed = ``LCK003``; static cycles confirmed at runtime are
escalated).  Exit code 0 = clean, 1 = violations (data races, lock-order
cycles, or cross-check findings), 2 = usage error / no report produced;
a clean report over a FAILING command propagates the command's exit code
(a CI gate must not read swallowed test failures as success).
"""


def add_subparser(subparsers):
    import argparse

    parser = subparsers.add_parser(
        "tsan",
        help="run a command under the runtime concurrency sanitizer",
    )
    parser.add_argument(
        "--seed",
        type=int,
        default=0,
        help="interleaving-explorer seed (default: 0; same seed = same "
        "forced-switch schedule)",
    )
    parser.add_argument(
        "--switch-rate",
        type=float,
        default=None,
        metavar="RATE",
        help="probability of a forced thread switch at each instrumented "
        "lock acquisition (default: sanitizer default)",
    )
    parser.add_argument(
        "--out",
        default=None,
        metavar="PATH",
        help="also write the merged JSON report to PATH",
    )
    parser.add_argument(
        "--format",
        choices=("human", "json"),
        default="human",
        help="stdout format (default: human)",
    )
    parser.add_argument(
        "--paths",
        nargs="*",
        default=None,
        metavar="PATH",
        help="files/directories for the static cross-check (default: the "
        "installed orion_tpu package)",
    )
    parser.add_argument(
        "--no-cross-check",
        action="store_true",
        help="skip the static LCK-graph cross-check",
    )
    parser.add_argument(
        "cmd",
        nargs=argparse.REMAINDER,
        metavar="-- CMD [ARG...]",
        help="command to run instrumented (everything after --)",
    )
    parser.set_defaults(func=main)
    return parser


def _merged_report(report, args):
    """The child's tsan report + the static cross-check block.

    The LCK003 leg runs through ``run_lint`` so suppressions at the
    acquisition site (`# lint: disable=LCK003 -- reason`) argue an edge
    away exactly like any other lint finding; ``unmodeled_edges`` keeps
    the raw pre-suppression list for the report's audit trail."""
    from orion_tpu.analysis import run_lint
    from orion_tpu.analysis.sanitizer import (
        cross_check_static,
        set_lint_runtime_edges,
    )

    if args.no_cross_check:
        report["cross_check"] = None
        return report
    paths = args.paths
    if not paths:
        import os

        import orion_tpu

        paths = [os.path.dirname(os.path.abspath(orion_tpu.__file__))]
    check = cross_check_static(report.get("edges") or [], paths)
    set_lint_runtime_edges(report.get("edges") or [])
    try:
        check["lck003"] = [
            d.to_dict() for d in run_lint(paths, select=["LCK003"])
        ]
    finally:
        set_lint_runtime_edges(None)
    report["cross_check"] = check
    return report


def _format_human(report):
    lines = []
    for cycle in report.get("lock_order_cycles") or []:
        lines.append(
            "POTENTIAL DEADLOCK: lock-order cycle "
            + " -> ".join(cycle["cycle"])
        )
        for edge in cycle["edges"]:
            lines.append(f"  edge {edge['outer']} -> {edge['inner']}:")
            for label, stack in (
                ("outer", edge.get("outer_stack") or ["?"]),
                ("inner", edge.get("inner_stack") or ["?"]),
            ):
                lines.append(f"    {label} acquired at: {stack[0]}")
    for race in report.get("races") or []:
        lines.append(
            f"DATA RACE ({race['kind']}) on {race['cell']}: "
            f"{race['site_a']} vs {race['site_b']}"
        )
    check = report.get("cross_check")
    if check:
        for finding in check.get("lck003") or []:
            lines.append(
                f"{finding['path']}:{finding['line']}: LCK003 "
                f"{finding['message']}"
            )
        for cycle in check.get("confirmed_static_cycles") or []:
            lines.append(
                "RUNTIME-CONFIRMED static cycle: " + " -> ".join(cycle)
            )
    lines.append(
        f"{len(report.get('races') or [])} race(s), "
        f"{len(report.get('lock_order_cycles') or [])} cycle(s), "
        f"{len(report.get('edges') or [])} observed edge(s), "
        f"{report.get('switches', 0)} forced switch(es)"
    )
    return "\n".join(lines)


def _violations(report):
    check = report.get("cross_check") or {}
    return (
        len(report.get("races") or [])
        + len(report.get("lock_order_cycles") or [])
        # Suppression-aware LCK003 findings count; the raw unmodeled-edge
        # list is audit context (a suppressed edge was argued, not missed).
        + len(check.get("lck003") or [])
        + len(check.get("confirmed_static_cycles") or [])
    )


def main(args):
    import json
    import os
    import subprocess
    import sys
    import tempfile

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print(
            "ERROR: no command given; usage: orion-tpu tsan [options] -- "
            "CMD [ARG...]",
            file=sys.stderr,
        )
        return 2

    handle = tempfile.NamedTemporaryFile(
        prefix="orion-tsan-", suffix=".json", delete=False
    )
    handle.close()
    env = dict(os.environ)
    env["ORION_TPU_TSAN"] = "1"
    env["ORION_TPU_TSAN_SEED"] = str(args.seed)
    env["ORION_TPU_TSAN_REPORT"] = handle.name
    # The child must import THIS orion_tpu (the env hook lives in its
    # __init__), but `python /path/to/script.py` puts the SCRIPT's dir at
    # sys.path[0], not our cwd — from an uninstalled checkout the child
    # would silently run uninstrumented (and write no report).  Prepend
    # the package root to PYTHONPATH so the child resolves the same tree.
    import orion_tpu

    package_root = os.path.dirname(os.path.dirname(os.path.abspath(orion_tpu.__file__)))
    existing = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (
        package_root + os.pathsep + existing if existing else package_root
    )
    if args.switch_rate is not None:
        env["ORION_TPU_TSAN_SWITCH"] = str(args.switch_rate)
    try:
        proc = subprocess.run(cmd, env=env)
        try:
            with open(handle.name) as report_file:
                report = json.load(report_file)
        except (OSError, ValueError):
            print(
                f"ERROR: instrumented command wrote no tsan report "
                f"(exit code {proc.returncode}) — does it import orion_tpu?",
                file=sys.stderr,
            )
            return 2
    finally:
        try:
            os.unlink(handle.name)
        except OSError:  # pragma: no cover
            pass

    report["command"] = cmd
    report["command_returncode"] = proc.returncode
    report = _merged_report(report, args)
    if args.out:
        with open(args.out, "w") as out_file:
            json.dump(report, out_file, indent=2)
    if args.format == "json":
        print(json.dumps(report))
    else:
        print(_format_human(report))
        if proc.returncode:
            print(f"(command exited {proc.returncode})")
    if _violations(report):
        return 1
    if proc.returncode:
        # Signals/exotic codes clamp to 1; 2 is reserved for usage errors
        # of THIS command, but a child's own 2 still must not read clean.
        return proc.returncode if 0 < proc.returncode < 128 else 1
    return 0
