"""`orion-tpu setup` — top-level alias for `db setup`.

Capability parity: reference `src/orion/core/cli/setup.py` keeps the
historical `orion setup` spelling alongside `orion db setup`; both write
the user-level storage configuration.
"""

from orion_tpu.cli.db import add_setup_args, main_setup


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "setup", help="write the user configuration file (alias for `db setup`)"
    )
    add_setup_args(parser)
    parser.set_defaults(func=main_setup)
    return parser
