"""`orion-tpu audit`: check an experiment's storage invariants.

No reference counterpart — part of the TPU build's robustness subsystem
(``orion_tpu.storage.audit``).  Walks the experiment's raw trial documents
and reports every violation of the cross-trial invariants (unique ids, no
duplicated parameter points, status/heartbeat consistency, completed ⇒
objective present, no orphaned reservations past the sweep threshold).
Exit code 0 = clean, 1 = violations found — cron-able as a fleet health
check next to `orion-tpu status`.
"""

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "audit", help="check an experiment's storage invariants"
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--timeout",
        type=float,
        default=None,
        metavar="seconds",
        help="orphaned-reservation threshold (default: the experiment's "
        "heartbeat setting)",
    )
    parser.add_argument(
        "--all",
        action="store_true",
        help="audit every experiment in the storage, not just -n NAME",
    )
    parser.add_argument(
        "--flight-out",
        default=None,
        metavar="path",
        help="where a failed audit dumps its flight-record artifact "
        "(default: flight-audit-<experiment>.jsonl)",
    )
    parser.set_defaults(func=main)
    return parser


def _dump_failure(report, out=None, suffix=False):
    """A failed audit leaves a flight-record JSONL artifact: the recent
    event ring (when this process recorded any) plus every violation as a
    structured event — the post-mortem starts from the artifact, not from
    scrollback.  Violations ride ``extra_events`` so this cold path needs
    no guarded hot-path ``record`` calls.

    Only dumps when the operator asked for observability: the flight
    recorder is enabled, or ``--flight-out`` names a path explicitly — a
    cron audit that never opted in must not scatter artifacts into its
    cwd (same rule as ``FlightRecorder.dump_crash``).  ``suffix=True``
    (the ``--all`` sweep with an explicit path) keys the file by
    experiment so multiple failing experiments don't overwrite each
    other's dumps."""
    import os
    import time

    from orion_tpu.health import FLIGHT

    if out is None and not FLIGHT.enabled:
        print(
            "audit failed; pass --flight-out PATH (or enable the flight "
            "recorder) to dump a flight-record artifact"
        )
        return None
    events = [
        {
            "kind": "audit.violation",
            "ts": time.time(),
            "args": dict(violation),
        }
        for violation in report.violations
    ]
    path = out or f"flight-audit-{report.experiment_id}.jsonl"
    if suffix and out is not None:
        root, ext = os.path.splitext(out)
        path = f"{root}-{report.experiment_id}{ext or '.jsonl'}"
    FLIGHT.dump(path, reason="audit-failure", extra_events=events)
    print(f"audit failed; flight record written to {path}")
    return path


def main(args):
    from orion_tpu.storage.audit import audit_experiment, audit_storage

    if getattr(args, "all", False):
        # Whole-storage sweep needs the raw storage, not one experiment;
        # reuse the name-less config/storage bootstrap path.
        from orion_tpu.cli.base import load_cli_config
        from orion_tpu.storage.base import setup_storage

        config = load_cli_config(args)
        storage = setup_storage(config["storage"], force=True)
        # heartbeat is a worker-level knob, never part of the stored
        # experiment identity (cli/base.py) — resolve the threshold from
        # the same config layers the -n NAME path applies to
        # experiment.heartbeat, so --all and -n agree on what "orphaned"
        # means.
        timeout = args.timeout
        if timeout is None:
            timeout = config.get("heartbeat")
        from orion_tpu.cli.base import describe_storage_topology

        topology = describe_storage_topology()
        if topology is not None:
            # The --all sweep resolved through the sharded router: every
            # shard's experiments are in the report set, and each one is
            # labeled with its ring placement below.
            print(topology)
        reports = audit_storage(storage, lost_timeout=timeout)
        if not reports:
            print("no experiments in storage")
            return 0
        shard_for = getattr(storage.db, "shard_for", None)
        for report in reports:
            if shard_for is not None:
                print(f"[shard {shard_for(report.experiment_id)}]", end=" ")
            print(report.summary())
        failed = [r for r in reports if not r.ok]
        for report in failed:
            # Per-experiment suffixing when several fail: one shared
            # --flight-out path must not have each dump overwrite the last.
            _dump_failure(
                report,
                getattr(args, "flight_out", None),
                suffix=len(failed) > 1,
            )
        return 0 if all(r.ok for r in reports) else 1

    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    report = audit_experiment(
        experiment.storage, experiment, lost_timeout=args.timeout
    )
    print(report.summary())
    if not report.ok:
        _dump_failure(report, getattr(args, "flight_out", None))
    return 0 if report.ok else 1
