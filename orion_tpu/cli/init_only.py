"""`orion-tpu init-only`: register the experiment without running trials.

Capability parity: reference `src/orion/core/cli/init_only.py`.
"""

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "init-only", help="create/branch the experiment without executing trials"
    )
    add_experiment_args(parser)
    parser.add_argument("--max-trials", type=int, default=None)
    parser.set_defaults(func=main)
    return parser


def main(args):
    experiment, _parser = build_from_args(args)
    print(f"Initialized experiment {experiment.name} (v{experiment.version})")
    return 0
