"""`orion-tpu status`: trial counts by status per experiment / EVC tree.

Capability parity: reference `src/orion/core/cli/status.py` — all
experiments by default or one via ``-n``; ``--all`` lists individual trials,
``--collapse`` aggregates an EVC tree into its root, versions shown as an
indented forest.
"""

from orion_tpu.cli.base import add_experiment_args, load_cli_config
from orion_tpu.core.trial import ALL_STATUSES
from orion_tpu.storage.base import setup_storage


def add_subparser(subparsers):
    parser = subparsers.add_parser("status", help="trial counts by status")
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument("-a", "--all", action="store_true", help="list every trial")
    parser.add_argument(
        "-C", "--collapse", action="store_true",
        help="aggregate each EVC tree into its root experiment",
    )
    parser.add_argument(
        "-e", "--expand-versions", action="store_true",
        help="one section per experiment version instead of aggregating a "
        "name's versions together (reference `cli/status.py:41`)",
    )
    parser.set_defaults(func=main)
    return parser


def _status_table(trials):
    counts = {}
    for trial in trials:
        counts[trial.status] = counts.get(trial.status, 0) + 1
    lines = [f"{'status':<14}{'quantity':<10}"]
    lines.append(f"{'-' * 12:<14}{'-' * 8:<10}")
    for status in ALL_STATUSES:
        if status in counts:
            lines.append(f"{status:<14}{counts[status]:<10}")
    if not counts:
        lines.append("(no trials)")
    return lines


def _trial_lines(trials):
    lines = [f"{'id':<34}{'status':<14}{'best objective':<16}"]
    for trial in sorted(trials, key=lambda t: t.submit_time or 0):
        obj = trial.objective.value if trial.objective else ""
        lines.append(f"{trial.id:<34}{trial.status:<14}{obj!s:<16}")
    return lines


def main(args):
    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)

    query = {}
    if config.get("name"):
        query["name"] = config["name"]
    if config.get("user"):
        query["metadata.user"] = config["user"]
    experiments = sorted(
        storage.fetch_experiments(query),
        key=lambda e: (e["name"], e.get("version", 1)),
    )
    if not experiments:
        print("No experiment found")
        return 0

    if getattr(args, "collapse", False):
        # Group by EVC root (refers.root_id), not by name — a branch created
        # under a different name still belongs to its original tree.
        by_id = {e["_id"]: e for e in experiments}
        by_root = {}
        for exp in experiments:
            root_id = (exp.get("refers") or {}).get("root_id") or exp["_id"]
            by_root.setdefault(root_id, []).append(exp)
        for root_id, family in sorted(
            by_root.items(), key=lambda kv: by_id.get(kv[0], kv[1][0])["name"]
        ):
            name = by_id.get(root_id, family[0])["name"]
            print(f"{name}")
            print("=" * len(name))
            trials = []
            for exp in family:
                trials.extend(storage.fetch_trials(uid=exp["_id"]))
            body = _trial_lines(trials) if args.all else _status_table(trials)
            print("\n".join(body) + "\n")
        return 0

    by_name = {}
    for exp in experiments:
        by_name.setdefault(exp["name"], []).append(exp)

    expand = getattr(args, "expand_versions", False)
    for name, versions in sorted(by_name.items()):
        if expand:
            # One section per version (reference --expand-versions).
            for exp in versions:
                title = f"{name}-v{exp.get('version', 1)}"
                print(title)
                print("=" * len(title))
                trials = storage.fetch_trials(uid=exp["_id"])
                body = _trial_lines(trials) if args.all else _status_table(trials)
                print("\n".join(body) + "\n")
        else:
            # Default: a name's versions aggregate into one section
            # (reference shows only the latest/aggregated unless expanded).
            print(name)
            print("=" * len(name))
            trials = []
            for exp in versions:
                trials.extend(storage.fetch_trials(uid=exp["_id"]))
            body = _trial_lines(trials) if args.all else _status_table(trials)
            print("\n".join(body) + "\n")
    return 0
