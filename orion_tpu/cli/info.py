"""`orion-tpu info`: pretty-print one experiment.

Capability parity: reference `src/orion/core/cli/info.py` — sections for
commandline, config, algorithm, space, metadata, refers (EVC lineage), and
stats.
"""

import time

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser("info", help="show experiment details")
    add_experiment_args(parser, with_user_args=False)
    parser.set_defaults(func=main)
    return parser


def _section(title):
    return f"\n{title}\n{'=' * len(title)}"


def format_info(experiment):
    out = [_section("Commandline")]
    out.append(" ".join(experiment.metadata.get("user_args", [])) or "(none)")

    out.append(_section("Config"))
    for key in ("pool_size", "max_trials", "max_broken", "working_dir"):
        out.append(f"{key}: {getattr(experiment, key)}")

    out.append(_section("Algorithm"))
    out.append(repr(experiment.algo_config))
    out.append(f"strategy: {experiment.strategy_config!r}")

    out.append(_section("Space"))
    for name, prior in sorted(experiment.priors.items()):
        out.append(f"{name}: {prior}")

    out.append(_section("Meta-data"))
    out.append(f"name: {experiment.name}")
    out.append(f"version: {experiment.version}")
    ts = experiment.metadata.get("timestamp")
    if ts:
        out.append(f"datetime: {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(ts))}")
    if experiment.metadata.get("user_script"):
        out.append(f"user_script: {experiment.metadata['user_script']}")

    out.append(_section("Parent experiment"))
    refers = experiment.refers or {}
    out.append(f"root: {refers.get('root_id') or experiment.id}")
    out.append(f"parent: {refers.get('parent_id') or '(none)'}")

    out.append(_section("Stats"))
    stats = experiment.stats()
    out.append(f"trials completed: {stats['trials_completed']}")
    if stats.get("best_evaluation") is not None:
        out.append(f"best evaluation: {stats['best_evaluation']}")
        out.append(f"best trial: {stats['best_trials_id']}")
        for key, value in sorted(stats.get("best_params", {}).items()):
            out.append(f"  {key}: {value}")
    if stats.get("start_time"):
        started = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(stats["start_time"]))
        out.append(f"start time: {started}")
    if stats.get("duration") is not None:
        out.append(f"duration: {stats['duration']:.1f}s")

    perf = _perf_section(experiment)
    if perf:
        out.append(_section("Performance"))
        out.extend(perf)

    tele = _telemetry_section(experiment)
    if tele:
        out.append(_section("Telemetry"))
        out.extend(tele)
    return "\n".join(out) + "\n"


def _perf_section(experiment):
    """suggest/observe/register latency percentiles from producer telemetry
    (SURVEY §5: timing hooks are a TPU-build addition; no reference
    counterpart).  ``register`` is the batched storage commit of a produce
    round — the stage the pipelined commit overlaps with device dispatch."""
    lines = []
    for op in ("suggest", "observe", "register"):
        try:
            docs = experiment.storage.fetch_timings(experiment, op=op)
        except Exception:
            return []
        if not docs:
            continue
        durations = sorted(d["duration"] for d in docs)
        n_points = sum(d.get("count", 1) for d in docs)

        def pct(p):
            # Nearest-rank percentile: ceil(p/100 * n) - 1 (0-indexed).
            idx = max(0, -(-int(p * len(durations)) // 100) - 1)
            return durations[min(idx, len(durations) - 1)]

        lines.append(
            f"{op}: {len(durations)} rounds, {n_points} points | "
            f"p50 {pct(50) * 1e3:.1f}ms  p90 {pct(90) * 1e3:.1f}ms  "
            f"p99 {pct(99) * 1e3:.1f}ms  max {durations[-1] * 1e3:.1f}ms"
        )
    return lines


def _telemetry_section(experiment):
    """The unified-telemetry block: per-op latency percentiles from the
    merged cross-worker histogram snapshots (orion_tpu.telemetry), plus
    the counters (jax retraces, storage transactions/wire requests/
    reconnects, lost-trial sweeps) and gauges each worker flushed through
    the storage metrics channel.  Empty unless a hunt ran with
    ``ORION_TPU_TELEMETRY=1`` (or ``telemetry: true``).  The WHOLE section
    is guarded, not just the fetch: a malformed doc (third-party backend,
    corruption) must drop this block, never take down ``info``."""
    from orion_tpu.telemetry import histogram_percentile, merge_snapshots

    try:
        docs = experiment.storage.fetch_metrics(experiment)
        if not docs:
            return []
        merged = merge_snapshots(docs)
        lines = [f"workers reporting: {len(docs)}"]
        for name, hist in sorted(merged["histograms"].items()):
            if not hist.get("count"):
                continue
            p50, p90, p99 = (
                histogram_percentile(hist, p) * 1e3 for p in (50, 90, 99)
            )
            lines.append(
                f"{name}: {hist['count']} samples | "
                f"p50 {p50:.1f}ms  p90 {p90:.1f}ms  p99 {p99:.1f}ms  "
                f"max {hist.get('max', 0.0) * 1e3:.1f}ms"
            )
        for name, value in sorted(merged["counters"].items()):
            lines.append(f"{name}: {value}")
        for name, value in sorted(merged["gauges"].items()):
            lines.append(f"{name}: {value:.4g}")
        return lines
    except Exception:
        return []


def main(args):
    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    print(format_info(experiment))
    return 0
