"""`orion-tpu info`: pretty-print one experiment.

Capability parity: reference `src/orion/core/cli/info.py` — sections for
commandline, config, algorithm, space, metadata, refers (EVC lineage), and
stats.
"""

import time

from orion_tpu.cli.base import (
    add_experiment_args,
    build_all_experiments,
    build_from_args,
)


def add_subparser(subparsers):
    parser = subparsers.add_parser("info", help="show experiment details")
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--per-worker",
        action="store_true",
        help="show each worker's telemetry/health snapshot separately "
        "instead of only the merged view (MAX-merged gauges hide WHICH "
        "worker is lagging)",
    )
    parser.add_argument(
        "--all",
        action="store_true",
        help="show every experiment in the store (a serve gateway hosts "
        "many tenants), not just -n NAME",
    )
    parser.set_defaults(func=main)
    return parser


def _section(title):
    return f"\n{title}\n{'=' * len(title)}"


def format_info(experiment, per_worker=False):
    out = [_section("Commandline")]
    out.append(" ".join(experiment.metadata.get("user_args", [])) or "(none)")

    out.append(_section("Config"))
    for key in ("pool_size", "max_trials", "max_broken", "working_dir"):
        out.append(f"{key}: {getattr(experiment, key)}")

    out.append(_section("Algorithm"))
    out.append(repr(experiment.algo_config))
    out.append(f"strategy: {experiment.strategy_config!r}")

    out.append(_section("Space"))
    for name, prior in sorted(experiment.priors.items()):
        out.append(f"{name}: {prior}")

    out.append(_section("Meta-data"))
    out.append(f"name: {experiment.name}")
    out.append(f"version: {experiment.version}")
    ts = experiment.metadata.get("timestamp")
    if ts:
        out.append(f"datetime: {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(ts))}")
    if experiment.metadata.get("user_script"):
        out.append(f"user_script: {experiment.metadata['user_script']}")

    out.append(_section("Parent experiment"))
    refers = experiment.refers or {}
    out.append(f"root: {refers.get('root_id') or experiment.id}")
    out.append(f"parent: {refers.get('parent_id') or '(none)'}")

    out.append(_section("Stats"))
    stats = experiment.stats()
    out.append(f"trials completed: {stats['trials_completed']}")
    if stats.get("best_evaluation") is not None:
        out.append(f"best evaluation: {stats['best_evaluation']}")
        out.append(f"best trial: {stats['best_trials_id']}")
        for key, value in sorted(stats.get("best_params", {}).items()):
            out.append(f"  {key}: {value}")
    if stats.get("start_time"):
        started = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(stats["start_time"]))
        out.append(f"start time: {started}")
    if stats.get("duration") is not None:
        out.append(f"duration: {stats['duration']:.1f}s")

    perf = _perf_section(experiment)
    if perf:
        out.append(_section("Performance"))
        out.extend(perf)

    # ONE fetch per channel for the three sections below — the telemetry,
    # health, and doctor blocks all read the same two collections, and a
    # sharded store pays a fan-out per fetch.  Each fetch is guarded
    # separately so one sick channel costs only its own sections.
    metrics_docs = _fetch_guarded(experiment, "fetch_metrics")
    health_docs = _fetch_guarded(experiment, "fetch_health")

    tele = _telemetry_section(experiment, per_worker=per_worker, docs=metrics_docs)
    if tele:
        out.append(_section("Telemetry"))
        out.extend(tele)

    compiler = _compiler_section(metrics_docs)
    if compiler:
        out.append(_section("Compiler"))
        out.extend(compiler)

    health = _health_section(experiment, per_worker=per_worker, docs=health_docs)
    if health:
        out.append(_section("Health"))
        out.extend(health)

    doctor = _doctor_line(experiment, metrics_docs, health_docs)
    if doctor:
        out.append(_section("Doctor"))
        out.append(doctor)
    return "\n".join(out) + "\n"


def _fetch_guarded(experiment, op):
    """One storage-channel fetch, degraded to None on any failure (a
    malformed doc or a sick store drops the dependent sections, never
    takes down ``info``)."""
    try:
        return getattr(experiment.storage, op)(experiment)
    except Exception:
        return None


def _doctor_line(experiment, metrics_docs, health_docs):
    """The diagnosis badge (orion_tpu.diagnosis): one line leading with
    the verdict, naming the firing rules — `orion-tpu doctor` is the full
    report.  Reads the docs format_info already fetched (the full
    snapshot_top assembly builds a regret curve and per-worker rows this
    line would throw away).  Guarded like the telemetry/health sections."""
    if metrics_docs is None and health_docs is None:
        # Both fetches failed: no data is not "healthy" — drop the badge
        # rather than print an OK verdict over nothing.
        return None
    try:
        from orion_tpu.cli.top import _doctor_block, doctor_badge

        return doctor_badge(
            _doctor_block(
                experiment, metrics_docs or [], health_docs or [], time.time()
            )
        )
    except Exception:
        return None


def _perf_section(experiment):
    """suggest/observe/register latency percentiles from producer telemetry
    (SURVEY §5: timing hooks are a TPU-build addition; no reference
    counterpart).  ``register`` is the batched storage commit of a produce
    round — the stage the pipelined commit overlaps with device dispatch."""
    lines = []
    for op in ("suggest", "observe", "register"):
        try:
            docs = experiment.storage.fetch_timings(experiment, op=op)
        except Exception:
            return []
        if not docs:
            continue
        durations = sorted(d["duration"] for d in docs)
        n_points = sum(d.get("count", 1) for d in docs)

        def pct(p):
            # Nearest-rank percentile: ceil(p/100 * n) - 1 (0-indexed).
            idx = max(0, -(-int(p * len(durations)) // 100) - 1)
            return durations[min(idx, len(durations) - 1)]

        lines.append(
            f"{op}: {len(durations)} rounds, {n_points} points | "
            f"p50 {pct(50) * 1e3:.1f}ms  p90 {pct(90) * 1e3:.1f}ms  "
            f"p99 {pct(99) * 1e3:.1f}ms  max {durations[-1] * 1e3:.1f}ms"
        )
    return lines


def _compiler_section(docs):
    """The compiler-plane digest (orion_tpu.compiler_plane): total XLA
    compiles and retrace-attribution coverage from the merged counters,
    plus the HBM-headroom line `orion-tpu profile` and `top` also render.
    Empty unless some worker recorded compiles; guarded like the telemetry
    block."""
    if not docs:
        return []
    try:
        from orion_tpu.cli.profile import hbm_line
        from orion_tpu.telemetry import merge_snapshots

        merged = merge_snapshots(docs)
        counters = merged.get("counters") or {}
        gauges = merged.get("gauges") or {}
        compiles = counters.get("jax.compiles")
        if not compiles:
            return []
        lines = [
            f"compiles: {int(compiles)}  "
            f"retraces: {int(counters.get('jax.retraces', 0))} "
            f"({int(counters.get('jax.retraces.attributed', 0))} attributed, "
            f"{int(counters.get('jax.retraces.prewarm_covered', 0))} "
            "prewarm-covered)"
        ]
        ms_total = gauges.get("compiler.compile_ms_total")
        if ms_total:
            lines.append(f"compile time total: {float(ms_total):.1f}ms")
        headroom = hbm_line(gauges)
        if headroom:
            lines.append(headroom)
        lines.append("details: `orion-tpu profile -n NAME`")
        return lines
    except Exception:
        return []


def _snapshot_lines(snapshot):
    """Histogram/counter/gauge lines for one (merged or per-worker)
    metrics snapshot dict."""
    from orion_tpu.telemetry import histogram_percentile

    lines = []
    for name, hist in sorted((snapshot.get("histograms") or {}).items()):
        if not hist.get("count"):
            continue
        p50, p90, p99 = (
            histogram_percentile(hist, p) * 1e3 for p in (50, 90, 99)
        )
        lines.append(
            f"{name}: {hist['count']} samples | "
            f"p50 {p50:.1f}ms  p90 {p90:.1f}ms  p99 {p99:.1f}ms  "
            f"max {hist.get('max', 0.0) * 1e3:.1f}ms"
        )
    for name, value in sorted((snapshot.get("counters") or {}).items()):
        lines.append(f"{name}: {value}")
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        lines.append(f"{name}: {float(value):.4g}")
    return lines


def _telemetry_section(experiment, per_worker=False, docs=None):
    """The unified-telemetry block: per-op latency percentiles from the
    merged cross-worker histogram snapshots (orion_tpu.telemetry), plus
    the counters (jax retraces, storage transactions/wire requests/
    reconnects, lost-trial sweeps) and gauges each worker flushed through
    the storage metrics channel.  Empty unless a hunt ran with
    ``ORION_TPU_TELEMETRY=1`` (or ``telemetry: true``).  ``per_worker``
    keeps each worker's snapshot separate instead of merging — the merged
    view's MAX-combined gauges say only that SOME worker lags, never which
    one.  ``docs`` lets format_info share one fetch across sections.  The
    WHOLE section is guarded, not just the fetch: a malformed doc
    (third-party backend, corruption) must drop this block, never take
    down ``info``."""
    from orion_tpu.telemetry import merge_snapshots

    try:
        if docs is None:
            docs = experiment.storage.fetch_metrics(experiment)
        if not docs:
            return []
        now = time.time()
        if per_worker:
            lines = [f"workers reporting: {len(docs)}"]
            for doc in docs:
                lines.append(
                    f"--- worker {doc.get('worker') or '?'}"
                    + _flush_age_suffix(doc, now)
                )
                lines.extend(_snapshot_lines(doc))
                ratio_line = _ratio_line(doc.get("histograms"))
                if ratio_line:
                    lines.append(ratio_line)
            return lines
        merged = merge_snapshots(docs)
        stale = [
            str(doc.get("worker") or "?")
            for doc in docs
            if _flush_age(doc, now) is not None
            and _flush_age(doc, now) > _stale_after()
        ]
        lines = [f"workers reporting: {len(docs)}"] + _snapshot_lines(merged)
        ratio_line = _ratio_line(merged.get("histograms"))
        if ratio_line:
            lines.append(ratio_line)
        if stale:
            # The merged view MAX-combines gauges, so a quiet worker's
            # numbers survive indefinitely — name who went quiet.
            lines.append(
                f"STALE workers (no flush for > {_stale_after():g}s): "
                + ", ".join(stale)
            )
        return lines
    except Exception:
        return []


def _stale_after():
    from orion_tpu.cli.top import STALE_AFTER

    return STALE_AFTER


def _ratio_line(histograms):
    """``host/device ratio: 1.12 (budget 2.25x)`` from the round vs
    device-window histogram means — the same per-worker number ``orion-tpu
    top`` shows in its ``h/d`` column, against the same
    ``orion_tpu.hostbudget`` bar the bench gate and doctor DX004 use."""
    from orion_tpu.cli.top import _host_device_ratio
    from orion_tpu.hostbudget import round_budget_factor

    ratio = _host_device_ratio(histograms)
    if ratio is None:
        return None
    budget = round_budget_factor()
    marker = "  HOST-BUDGET BREACH" if ratio > budget else ""
    return f"host/device ratio: {ratio:g} (budget {budget:g}x){marker}"


def _flush_age(doc, now):
    ts = doc.get("time")
    return round(now - float(ts), 1) if ts else None


def _flush_age_suffix(doc, now):
    """`` (last flush 3.2s ago)`` — with a STALE marker past 3× the
    metrics flush interval, so the un-merged per-worker blocks carry the
    liveness signal the MAX-merged view hides."""
    age = _flush_age(doc, now)
    if age is None:
        return ""
    marker = " STALE" if age > _stale_after() else ""
    return f" (last flush {age:g}s ago{marker})"


def _health_section(experiment, per_worker=False, docs=None):
    """The optimization-health block (orion_tpu.health): the fleet-wide
    incumbent over the recorded regret trajectory and, per worker, the
    latest per-round health record — GP marginal likelihood, lengthscale
    spread, acquisition level, trust-region box, rung occupancy.
    ``docs`` lets format_info share one fetch across sections.  Guarded
    like the telemetry block; empty when no hunt recorded health."""
    try:
        if docs is None:
            docs = experiment.storage.fetch_health(experiment)
        if not docs:
            return []
        best = None
        for doc in docs:
            y = doc.get("best_y")
            if y is not None and (best is None or y < best):
                best = y
        by_worker = {}
        for doc in docs:  # time-ordered: the last doc per worker wins
            by_worker[str(doc.get("worker") or "?")] = doc
        lines = [f"health records: {len(docs)} from {len(by_worker)} worker(s)"]
        if best is not None:
            lines.append(f"incumbent best_y: {best:.6g}")
        now = time.time()
        for worker, doc in sorted(by_worker.items()):
            fields = []
            age = _flush_age(doc, now)
            if age is not None:
                marker = " STALE" if age > _stale_after() else ""
                fields.append(f"age {age:g}s{marker}")
            for key, spec in (
                ("round", "d"),
                ("n_obs", "d"),
                ("best_y", ".5g"),
                ("gp_mll", ".3f"),
                ("gp_ls_mean", ".3g"),
                ("gp_noise", ".3g"),
                ("acq_ei_max", ".3g"),
                ("q_unique_frac", ".2f"),
                ("tr_length", ".3f"),
                ("model_tier", "d"),
                # Serve-gateway fields (orion_tpu.serve): the coalesce
                # width this worker's rounds rode and the gateway queue.
                ("serve_width", "d"),
                ("serve_queue_depth", "d"),
                ("serve_tenants", "d"),
            ):
                value = doc.get(key)
                if value is not None:
                    if spec == "d":
                        value = int(value)
                    fields.append(f"{key} {format(value, spec)}")
            occupancy = doc.get("rung_occupancy")
            if occupancy:
                # Every bracket, not just the first: the starved rung the
                # signal exists to expose can sit in any ladder.  Per rung:
                # ``resources:occupied(evaluated)`` — occupied counts
                # pending promotion slots too, evaluated only real
                # objectives.
                for index, bracket in enumerate(occupancy):
                    rungs = " ".join(
                        f"{resources}:{occupied}({evaluated})"
                        for resources, occupied, evaluated in bracket
                    )
                    fields.append(f"rungs[b{index}] {rungs}")
            header = f"{worker}: " if per_worker or len(by_worker) > 1 else ""
            lines.append(header + "  ".join(fields))
        return lines
    except Exception:
        return []


def main(args):
    per_worker = getattr(args, "per_worker", False)
    if getattr(args, "all", False):
        experiments = build_all_experiments(args)
        # Fleet view over a sharded control plane: say which topology
        # answered (build_all_experiments resolved through the router, so
        # experiments from EVERY shard are in the list).
        from orion_tpu.cli.base import (
            describe_serve_fleet,
            describe_storage_topology,
            load_cli_config,
        )

        topology = describe_storage_topology(probe=True)
        if topology is not None:
            print(topology)
        # Serve-plane twin of the storage header: one `fleet` probe per
        # configured gateway (per-member tenant counts, queue depth, and
        # the membership epoch — epoch=SPLIT is the drift smell DX007's
        # runbook starts from).
        gateways = describe_serve_fleet(load_cli_config(args).get("serve"))
        if gateways is not None:
            print(gateways)
        if not experiments:
            print("no experiments in storage")
            return 0
        for experiment in experiments:
            print(format_info(experiment, per_worker=per_worker))
        return 0
    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    print(format_info(experiment, per_worker=per_worker))
    return 0
