"""`orion-tpu info`: pretty-print one experiment.

Capability parity: reference `src/orion/core/cli/info.py` — sections for
commandline, config, algorithm, space, metadata, refers (EVC lineage), and
stats.
"""

import time

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser("info", help="show experiment details")
    add_experiment_args(parser, with_user_args=False)
    parser.set_defaults(func=main)
    return parser


def _section(title):
    return f"\n{title}\n{'=' * len(title)}"


def format_info(experiment):
    out = [_section("Commandline")]
    out.append(" ".join(experiment.metadata.get("user_args", [])) or "(none)")

    out.append(_section("Config"))
    for key in ("pool_size", "max_trials", "max_broken", "working_dir"):
        out.append(f"{key}: {getattr(experiment, key)}")

    out.append(_section("Algorithm"))
    out.append(repr(experiment.algo_config))
    out.append(f"strategy: {experiment.strategy_config!r}")

    out.append(_section("Space"))
    for name, prior in sorted(experiment.priors.items()):
        out.append(f"{name}: {prior}")

    out.append(_section("Meta-data"))
    out.append(f"name: {experiment.name}")
    out.append(f"version: {experiment.version}")
    ts = experiment.metadata.get("timestamp")
    if ts:
        out.append(f"datetime: {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(ts))}")
    if experiment.metadata.get("user_script"):
        out.append(f"user_script: {experiment.metadata['user_script']}")

    out.append(_section("Parent experiment"))
    refers = experiment.refers or {}
    out.append(f"root: {refers.get('root_id') or experiment.id}")
    out.append(f"parent: {refers.get('parent_id') or '(none)'}")

    out.append(_section("Stats"))
    stats = experiment.stats()
    out.append(f"trials completed: {stats['trials_completed']}")
    if stats.get("best_evaluation") is not None:
        out.append(f"best evaluation: {stats['best_evaluation']}")
        out.append(f"best trial: {stats['best_trials_id']}")
        for key, value in sorted(stats.get("best_params", {}).items()):
            out.append(f"  {key}: {value}")
    if stats.get("start_time"):
        out.append(f"start time: {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(stats['start_time']))}")
    if stats.get("duration") is not None:
        out.append(f"duration: {stats['duration']:.1f}s")
    return "\n".join(out) + "\n"


def main(args):
    experiment, _parser = build_from_args(args, need_user_args=False, allow_create=False)
    print(format_info(experiment))
    return 0
