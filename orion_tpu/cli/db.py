"""`orion-tpu db {setup,test,upgrade}`: storage administration.

Capability parity: reference `src/orion/core/cli/db/` + `cli/setup.py` +
`cli/checks/` — ``setup`` writes the user-level configuration file,
``test`` runs the three staged check suites (presence / creation /
operations, reference `cli/checks/`), ``upgrade`` migrates stored documents
to the current schema (indexes + config backfill, reference
`cli/db/upgrade.py:96-183`).
"""

import contextlib
import os

import yaml

from orion_tpu.cli.base import load_cli_config
from orion_tpu.config import user_config_path
from orion_tpu.storage.base import setup_storage
from orion_tpu.utils.exceptions import CheckError


def add_subparser(subparsers):
    parser = subparsers.add_parser("db", help="storage administration")
    sub = parser.add_subparsers(dest="db_command", metavar="ACTION")

    setup_p = sub.add_parser("setup", help="write the user configuration file")
    add_setup_args(setup_p)
    setup_p.set_defaults(func=main_setup)

    serve_p = sub.add_parser(
        "serve", help="run the shared network DB server (multi-node storage)"
    )
    serve_p.add_argument("--host", default="0.0.0.0", help="bind address")
    serve_p.add_argument("--port", type=int, default=8765, help="bind port")
    serve_p.add_argument(
        "--persist",
        default=None,
        help="snapshot file so the server can restart without losing state",
    )
    serve_p.add_argument(
        "--secret-file",
        default=None,
        help="file holding the shared secret clients must prove knowledge of "
        "(HMAC handshake; the secret never crosses the wire).  Clients set "
        "ORION_DB_SECRET_FILE or storage.secret_file.",
    )
    serve_p.add_argument(
        "--no-auth",
        action="store_true",
        help="explicitly run WITHOUT authentication (localhost development "
        "only — any peer that can reach the port can read and corrupt "
        "experiments)",
    )
    serve_p.add_argument(
        "--replicate-to",
        action="append",
        default=None,
        metavar="host:port",
        help="push this server's ordered mutation stream to a read replica "
        "(repeatable; replicas serve the hot read path of a sharded "
        "topology — see docs/multi_node.md).  Replication is asynchronous: "
        "writes are acknowledged before they reach any replica.",
    )
    serve_p.add_argument(
        "--replica",
        action="store_true",
        help="mark this server a read replica (stamps its applied "
        "replication sequence on read replies so clients detect lag; also "
        "set automatically when a primary's stream arrives)",
    )
    serve_p.add_argument(
        "--quorum",
        type=int,
        default=0,
        metavar="N",
        help="replication-ack floor for synchronous collections "
        "(experiments/trials/placement): a write is acknowledged only "
        "after N replicas confirm it, so those writes survive kill -9 by "
        "construction.  Needs at least N live replicas to stay writable; "
        "telemetry/health stay async.  0 (default) = all-async "
        "(see docs/multi_node.md).",
    )
    serve_p.set_defaults(func=main_serve)

    ring_p = sub.add_parser(
        "ring",
        help="show the sharded storage topology and per-experiment "
        "ring placement (requires a shards: stanza / ORION_DB_SHARDS)",
    )
    _common(ring_p)
    ring_p.add_argument(
        "--diff",
        action="store_true",
        help="show the rebalance plan instead: which experiments live away "
        "from their ring home (after a topology change) and where "
        "`db rebalance` would move them (~1/N of the keyspace when one "
        "shard was added)",
    )
    ring_p.set_defaults(func=main_ring)

    rebalance_p = sub.add_parser(
        "rebalance",
        help="move experiments to their ring homes after a topology change "
        "(live: copy -> verify byte-identical -> atomic placement flip -> "
        "delete source; crash-resumable — see docs/multi_node.md)",
    )
    _common(rebalance_p)
    rebalance_p.add_argument(
        "--dry-run", action="store_true",
        help="print the plan and exit without moving anything",
    )
    rebalance_p.add_argument(
        "--fence-grace", type=float, default=None, metavar="SECONDS",
        help="how long experiments stay fenced before the flip (default: "
        "the routers' placement-cache TTL, so every router observes the "
        "fence before documents move)",
    )
    rebalance_p.set_defaults(func=main_rebalance)

    drain_p = sub.add_parser(
        "drain",
        help="empty one shard BEFORE removing it from the topology: every "
        "resident experiment migrates to its post-removal ring home "
        "through the same crash-resumable pin/copy/byte-verify/flip "
        "machinery as `db rebalance` — zero lost observations, clean "
        "audit (see docs/multi_node.md, Day-2 operations)",
    )
    _common(drain_p)
    drain_p.add_argument(
        "shard", metavar="SHARD",
        help="the shard to drain: its index (as shown by `db ring` / "
        "`db status`) or its ring identity host:port",
    )
    drain_p.add_argument(
        "--dry-run", action="store_true",
        help="print the plan and exit without moving anything",
    )
    drain_p.add_argument(
        "--fence-grace", type=float, default=None, metavar="SECONDS",
        help="how long experiments stay fenced before the flip (default: "
        "the routers' placement-cache TTL, so every router observes the "
        "fence before documents move)",
    )
    drain_p.set_defaults(func=main_drain)

    status_p = sub.add_parser(
        "status",
        help="one-shot storage fleet status: per-shard role, replica set, "
        "replication lag and quorum floor (probed live)",
    )
    _common(status_p)
    status_p.add_argument(
        "--json", action="store_true",
        help="emit the probed structure as JSON instead of the table",
    )
    status_p.set_defaults(func=main_status)

    migrate_ids_p = sub.add_parser(
        "migrate-ids",
        help="rewrite trial ids to a new identity scheme (default: the "
        "byte-hash cube_hash scheme) experiment by experiment — copy under "
        "new ids -> byte-verify non-id fields -> flip id_scheme -> delete "
        "originals; crash-resumable, works on every backend and across "
        "the sharded router (see docs/multi_node.md)",
    )
    _common(migrate_ids_p)
    migrate_ids_p.add_argument(
        "--scheme", default="cube_hash", choices=["md5", "cube_hash"],
        help="target id scheme (default: cube_hash)",
    )
    migrate_ids_p.add_argument(
        "-n", "--name", default=None, metavar="NAME",
        help="migrate only this experiment (default: every experiment "
        "whose scheme differs)",
    )
    migrate_ids_p.add_argument(
        "--dry-run", action="store_true",
        help="print the plan and exit without rewriting anything",
    )
    migrate_ids_p.set_defaults(func=main_migrate_ids)

    backup_p = sub.add_parser(
        "backup",
        help="stream one consistent seq/epoch-stamped snapshot per shard "
        "into a directory (manifest written last, atomically)",
    )
    _common(backup_p)
    backup_p.add_argument(
        "--out", required=True, metavar="DIR",
        help="backup directory (created if missing)",
    )
    backup_p.set_defaults(func=main_backup)

    restore_p = sub.add_parser(
        "restore",
        help="rebuild a FRESH topology from a `db backup` directory; "
        "documents are routed through the CURRENT ring, so the new "
        "topology may have a different shard count",
    )
    _common(restore_p)
    restore_p.add_argument(
        "--src", required=True, metavar="DIR",
        help="backup directory holding manifest.json",
    )
    restore_p.add_argument(
        "--force", action="store_true",
        help="restore into a NON-empty destination (documents merge by id; "
        "conflicting content is NOT detected — disaster recovery only)",
    )
    restore_p.set_defaults(func=main_restore)

    copy_p = sub.add_parser(
        "copy",
        help="copy every experiment/trial between storage backends "
        "(e.g. migrate a pickled file to sqlite or to a network server)",
    )
    copy_p.add_argument(
        "--src", required=True,
        help="source storage: a DB file path, or host:port of a network server",
    )
    copy_p.add_argument(
        "--dst", required=True,
        help="destination storage: a DB file path, or host:port (created/merged)",
    )
    copy_p.set_defaults(func=main_copy)

    dump_p = sub.add_parser(
        "dump",
        help="export every document as JSON lines (backup / migration)",
    )
    dump_p.add_argument(
        "--src", required=True,
        help="storage to export: a DB file path, or host:port",
    )
    dump_p.add_argument(
        "--out", default="-",
        help="output file (default '-': stdout)",
    )
    dump_p.set_defaults(func=main_dump)

    load_p = sub.add_parser(
        "load",
        help="import documents from a dump file (orion-tpu JSONL, or a "
        "mongoexport --jsonArray file from a reference Oríon deployment)",
    )
    load_p.add_argument(
        "--src", required=True,
        help="dump file: `db dump` JSONL, raw-JSONL, or a JSON array",
    )
    load_p.add_argument(
        "--dst", required=True,
        help="destination storage: a DB file path, or host:port",
    )
    load_p.add_argument(
        "--collection", default=None,
        help="collection for files of raw documents (mongoexport output); "
        "not needed for `db dump` files, which carry the collection per line",
    )
    load_p.set_defaults(func=main_load)

    test_p = sub.add_parser("test", help="run staged storage checks")
    _common(test_p)
    test_p.set_defaults(func=main_test)

    up_p = sub.add_parser("upgrade", help="migrate stored documents to the current schema")
    _common(up_p)
    up_p.set_defaults(func=main_upgrade)

    parser.set_defaults(func=lambda args: parser.print_help() or 1)
    return parser


def _common(parser):
    parser.add_argument("-c", "--config", metavar="path", default=None)
    parser.add_argument("--storage-path", default=None)
    parser.add_argument("--debug", action="store_true")


def add_setup_args(parser):
    """Storage-setup arguments, shared by `db setup` and the top-level
    `setup` alias."""
    parser.add_argument(
        "--storage-type",
        default="pickled",
        choices=["pickled", "sqlite", "memory", "network"],
    )
    parser.add_argument("--path", default=None, help="DB file path (pickled/sqlite)")
    parser.add_argument("--host", default="127.0.0.1", help="network DB host")
    parser.add_argument("--port", type=int, default=8765, help="network DB port")
    parser.add_argument(
        "--secret-file", default=None,
        help="shared-secret file for an authenticated network server",
    )


def _copy_spec_to_config(spec):
    """``host:port`` (no path separators, numeric port) selects the network
    driver; anything else is a DB file path routed by header/extension
    (same routing as --storage-path)."""
    if ":" in spec and os.sep not in spec and not os.path.exists(spec):
        host, _, port = spec.rpartition(":")
        if port.isdigit():
            return {"type": "network", "host": host, "port": int(port)}
    from orion_tpu.cli.base import _storage_type_for_path

    return {"type": _storage_type_for_path(spec), "path": spec}


_COPY_COLLECTIONS = ("experiments", "trials", "lying_trials", "telemetry")


def _same_content(a, b):
    """Content equality across backend representations: canonical JSON
    tolerates numpy values, tuples→lists through sqlite, and non-finite
    floats (NaN != NaN as dicts).  Legacy pickled docs may hold values JSON
    can't express at all (bytes, sets) — fall back to plain equality then."""
    from orion_tpu.storage.documents import dumps_canonical

    try:
        return dumps_canonical(a) == dumps_canonical(b)
    except TypeError:
        try:
            return bool(a == b)
        except Exception:  # numpy arrays make dict.__eq__ ambiguous
            return False


def _unique_key(doc, fields):
    from orion_tpu.storage.documents import _get_path, index_key

    try:
        return index_key(doc, fields)
    except TypeError:  # non-JSON value inside a unique field: rare, legacy
        return repr([_get_path(doc, f)[1] for f in fields])


def _source_storage_or_error(spec):
    """Open a READ source: a nonexistent file path is an error, never a
    freshly-created empty DB — `db dump --src typo.sqlite` would otherwise
    truncate the backup (and `db copy` report a successful 0-doc copy)
    while the user believes their data was exported."""
    import sys

    from orion_tpu.storage.base import create_storage

    config = _copy_spec_to_config(spec)
    if "path" in config and not os.path.exists(config["path"]):
        print(f"ERROR: source database {spec!r} does not exist", file=sys.stderr)
        return None
    return create_storage(config)


def main_dump(args):
    """Export every collection as JSON lines: ``{"collection": c, "doc": d}``
    per line — the lossless, diffable interchange format ``db load``
    re-imports (and the backup story for every backend, network included)."""
    import json
    import sys
    import tempfile

    from orion_tpu.storage.documents import json_default

    src = _source_storage_or_error(args.src)
    if src is None:
        return 1

    def _write_all(out):
        n = 0
        for collection in _COPY_COLLECTIONS:
            for doc in src.db.read(collection):
                out.write(
                    json.dumps(
                        {"collection": collection, "doc": doc},
                        default=json_default,
                    )
                    + "\n"
                )
                n += 1
        return n

    if args.out == "-":
        _write_all(sys.stdout)
        return 0
    # Atomic replace: a mid-dump failure (unserializable legacy document,
    # network source dropping) must never have truncated the previous
    # backup already.
    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    fd, tmp_path = tempfile.mkstemp(dir=out_dir, suffix=".dump-partial")
    try:
        with os.fdopen(fd, "w") as out:
            n = _write_all(out)
        # mkstemp creates 0600; preserve an existing backup's mode (a
        # group-readable file synced by another user must stay readable),
        # else the umask default a plain open() would have produced.
        import stat

        if os.path.exists(args.out):
            mode = stat.S_IMODE(os.stat(args.out).st_mode)
        else:
            current_umask = os.umask(0)
            os.umask(current_umask)
            mode = 0o666 & ~current_umask
        os.chmod(tmp_path, mode)
        os.replace(tmp_path, args.out)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp_path)
        raise
    print(f"dumped {n} documents to {args.out}")
    return 0


def _denormalize_mongo(value):
    """Strip Mongo extended-JSON wrappers so reference-Oríon exports load as
    plain documents: ``{"$oid": s}`` -> s, ``{"$date": ...}`` -> epoch
    seconds (float — this framework's timestamp convention), and the
    ``$number*`` scalar wrappers -> python numbers."""
    if isinstance(value, dict):
        if set(value) == {"$oid"}:
            return str(value["$oid"])
        if set(value) == {"$date"}:
            inner = value["$date"]
            if isinstance(inner, dict) and set(inner) == {"$numberLong"}:
                return int(inner["$numberLong"]) / 1000.0
            if isinstance(inner, (int, float)):
                return inner / 1000.0  # epoch millis
            import datetime

            return datetime.datetime.fromisoformat(
                str(inner).replace("Z", "+00:00")
            ).timestamp()
        if set(value) == {"$numberLong"} or set(value) == {"$numberInt"}:
            return int(next(iter(value.values())))
        if set(value) == {"$numberDouble"} or set(value) == {"$numberDecimal"}:
            return float(next(iter(value.values())))
        return {k: _denormalize_mongo(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_denormalize_mongo(v) for v in value]
    return value


def _iter_dump_docs(path, default_collection):
    """Yield (collection, doc) from a dump file: `db dump` JSONL lines
    carrying their collection, raw-JSONL documents, or one JSON array
    (mongoexport --jsonArray).  Raw forms need --collection."""
    import json

    from orion_tpu.utils.exceptions import CheckError

    with open(path) as handle:
        head = handle.read(1)
        handle.seek(0)
        if head == "[":
            if default_collection is None:
                raise CheckError(
                    "this file is a raw JSON array of documents; pass "
                    "--collection (experiments/trials/lying_trials) to say "
                    "where they belong"
                )
            for doc in json.load(handle):
                yield default_collection, _denormalize_mongo(doc)
            return
        for line_no, line in enumerate(handle, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError as exc:
                raise CheckError(f"{path}:{line_no}: not JSON: {exc}") from exc
            if (
                isinstance(entry, dict)
                and set(entry) == {"collection", "doc"}
            ):
                # Our own dump format: already plain documents — running the
                # Mongo denormalizer over them would mangle any legitimate
                # value shaped like a wrapper (a user metadata dict whose
                # only key is "$date"), breaking dump->load losslessness.
                yield entry["collection"], entry["doc"]
            elif default_collection is not None:
                yield default_collection, _denormalize_mongo(entry)
            else:
                raise CheckError(
                    f"{path}:{line_no}: raw document without --collection "
                    "(db-dump lines carry {'collection': ..., 'doc': ...})"
                )


def _depickle_values(value):
    """Normalize pickled reference values to this framework's document
    conventions: naive-UTC datetimes (the reference stamps
    ``datetime.utcnow()``) -> epoch-second floats, tuples -> lists."""
    import datetime

    if isinstance(value, datetime.datetime):
        if value.tzinfo is None:
            value = value.replace(tzinfo=datetime.timezone.utc)
        return value.timestamp()
    if isinstance(value, dict):
        return {k: _depickle_values(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_depickle_values(v) for v in value]
    return value


def _iter_reference_pickle_docs(path):
    """Yield (collection, doc) from a reference-Oríon ``PickledDB`` file
    (a pickled EphemeralDB — `reference pickleddb.py:162-174`).

    Unpickling needs the reference's classes, i.e. ``import orion`` must
    work — true for a real migrating user, who has Oríon installed next to
    this framework.  Run ``db upgrade`` on the destination afterwards to
    convert the reference's trial schema (params as [{name,type,value}])
    to this framework's params dict."""
    import pickle
    import sys

    from orion_tpu.storage.documents import MemoryDB
    from orion_tpu.utils.exceptions import CheckError

    try:
        # Unpickle FIRST: an orion-tpu pickle needs no reference package,
        # and its misdiagnosis ("install Oríon") must not shadow the real
        # answer ("use db copy").  A reference pickle without `orion`
        # importable surfaces here as ModuleNotFoundError.
        with open(path, "rb") as handle:
            database = pickle.load(handle)
    except ImportError as exc:
        raise CheckError(
            "this file is a pickled database whose classes are not "
            f"importable ({exc}); reading a reference-Oríon PickledDB "
            "requires the `orion` package (run the load where Oríon is "
            "installed, or export the data with mongoexport / its own "
            "tooling and load the JSON instead)"
        ) from exc
    if isinstance(database, MemoryDB):
        raise CheckError(
            "this is an orion-tpu pickled database, not a reference-Oríon "
            "one — use `db copy` to merge it"
        )
    collections = getattr(database, "_db", None)
    if collections is None:
        raise CheckError(
            "not a reference Oríon pickled database (no collections inside)"
        )
    for name, collection in collections.items():
        docs = collection.find()
        if not docs:
            continue
        if name not in _COPY_COLLECTIONS:
            print(
                f"skipping reference collection {name!r} "
                f"({len(docs)} document(s); no counterpart here)",
                file=sys.stderr,
            )
            continue
        for doc in docs:
            yield name, _depickle_values(dict(doc))


def _strip_id(doc):
    return {k: v for k, v in doc.items() if k != "_id"}


def _plan_merge(dst, docs_by_collection):
    """Plan-before-write merge shared by ``db copy`` and ``db load``:
    returns ``(plan, conflicts)`` where plan is
    ``[(collection, missing_docs, present_count), ...]``.

    A document is *present* (idempotent no-op) when the destination — or an
    earlier occurrence in the same source — already holds it with identical
    content; it is a *conflict* when the same _id (or the same
    unique-index key: experiments sharing name/version/user under distinct
    _ids) maps to DIFFERENT content.  Conflicts must abort before any
    write: the write phase would otherwise raise mid-batch with part of
    the source applied.  Documents WITHOUT an _id dedup by full content
    against the destination's _id-stripped documents (trials have no
    unique index to catch a re-insert)."""
    from orion_tpu.storage.base import INDEX_SPECS
    from orion_tpu.storage.documents import dumps_canonical

    unique_fields = {
        collection: fields for collection, fields, unique in INDEX_SPECS if unique
    }
    plan, conflicts = [], 0
    for collection, docs in docs_by_collection.items():
        fields = unique_fields.get(collection)
        existing = {}
        unique_seen = set()
        dst_docs = list(dst.db.read(collection))
        for doc in dst_docs:
            if "_id" in doc:
                existing[doc["_id"]] = doc
            if fields:
                unique_seen.add(_unique_key(doc, fields))
        # Content keys support only the raw-JSONL id-less path; built
        # lazily — `db copy` and db-dump loads always carry _ids, and
        # canonical-JSON-encoding every destination document would be O(N)
        # wasted work on their common path.
        existing_content = None

        def content_keys():
            nonlocal existing_content
            if existing_content is None:
                existing_content = set()
                for doc in dst_docs:
                    try:
                        existing_content.add(dumps_canonical(_strip_id(doc)))
                    except TypeError:
                        pass
            return existing_content
        first_by_id = {}
        missing, present = [], 0
        for doc in docs:
            _id = doc.get("_id")
            if _id is not None and _id in first_by_id:
                # Repeated inside the source (concatenated dumps): same
                # content merges, different content is a real conflict.
                if _same_content(first_by_id[_id], doc):
                    present += 1
                else:
                    conflicts += 1
                continue
            if _id is not None:
                first_by_id[_id] = doc
            other = existing.get(_id) if _id is not None else None
            if other is not None:
                if _same_content(other, doc):
                    present += 1
                else:
                    conflicts += 1
                continue
            if _id is None:
                try:
                    key = dumps_canonical(doc)
                except TypeError:
                    key = None
                if key is not None and key in content_keys():
                    present += 1
                    continue
                if key is not None:
                    content_keys().add(key)
            if fields is not None:
                key = _unique_key(doc, fields)
                if key in unique_seen:
                    conflicts += 1
                    continue
                unique_seen.add(key)
            missing.append(doc)
        plan.append((collection, missing, present))
    return plan, conflicts


def main_load(args):
    """Import a dump into a destination storage; duplicate documents with
    identical content merge idempotently, differing content aborts before
    anything is written (same contract as ``db copy``)."""
    import sys

    from orion_tpu.storage.base import create_storage
    from orion_tpu.utils.exceptions import CheckError, DuplicateKeyError

    if args.collection is not None and args.collection not in _COPY_COLLECTIONS:
        print(
            f"ERROR: unknown collection {args.collection!r}; expected one of "
            f"{_COPY_COLLECTIONS}",
            file=sys.stderr,
        )
        return 1
    dst = create_storage(_copy_spec_to_config(args.dst))
    by_collection = {}
    try:
        with open(args.src, "rb") as handle:
            is_pickle = handle.read(1) == b"\x80"  # pickle protocol-2+ magic
        if is_pickle:
            # A reference-Oríon PickledDB artifact (migration path; follow
            # with `db upgrade` on the destination to convert its schemas).
            docs_iter = _iter_reference_pickle_docs(args.src)
        else:
            docs_iter = _iter_dump_docs(args.src, args.collection)
        for collection, doc in docs_iter:
            if collection not in _COPY_COLLECTIONS:
                raise CheckError(f"unknown collection {collection!r} in dump")
            by_collection.setdefault(collection, []).append(doc)
    except OSError as exc:
        print(f"ERROR: cannot read {args.src!r}: {exc}", file=sys.stderr)
        return 1
    plan, conflicts = _plan_merge(dst, by_collection)
    if conflicts:
        print(
            f"ERROR: {conflicts} document(s) collide (same _id or same "
            "experiment name/version/user with DIFFERENT content) — "
            "NOTHING was loaded.  Bump the version or rename one side, "
            "then re-run.",
            file=sys.stderr,
        )
        return 1
    for collection, missing, present in plan:
        if missing:
            try:
                dst.db.write(collection, missing)
            except DuplicateKeyError as exc:
                print(
                    f"ERROR: destination changed during the load "
                    f"({collection}: {exc}) — the load is incomplete; "
                    "re-run to merge idempotently.",
                    file=sys.stderr,
                )
                return 1
        print(f"{collection}: loaded {len(missing)}, already present {present}")
    return 0


def main_copy(args):
    import sys

    from orion_tpu.storage.base import create_storage
    from orion_tpu.utils.exceptions import DuplicateKeyError

    src = _source_storage_or_error(args.src)
    if src is None:
        return 1
    dst = create_storage(_copy_spec_to_config(args.dst))
    # Plan everything BEFORE writing anything (shared with `db load`): a
    # conflicting experiment id must abort the whole copy, or its src
    # trials (carrying experiment=id) would attach to the unrelated dst
    # experiment.
    plan, conflicts = _plan_merge(
        dst,
        {
            collection: src.db.read(collection)
            for collection in _COPY_COLLECTIONS
        },
    )
    if conflicts:
        print(
            f"ERROR: {conflicts} document(s) collide with the destination "
            "with DIFFERENT content (same _id, or experiments sharing "
            "name/version/user) — NOTHING was copied.  For _id collisions "
            "from legacy auto-increment ids, run `orion-tpu db upgrade` on "
            "both sides first; for same-named experiments, bump the version "
            "or rename one side before copying.",
            file=sys.stderr,
        )
        return 1
    for collection, missing, present in plan:
        if missing:
            # One batched write: per-doc writes into a pickled destination
            # would re-lock and rewrite the whole file per document.
            try:
                dst.db.write(collection, missing)
            except DuplicateKeyError as exc:
                # Race: a dst writer created a colliding doc after planning.
                print(
                    f"ERROR: destination changed during the copy "
                    f"({collection}: {exc}) — the copy is incomplete; "
                    "re-run to merge idempotently.",
                    file=sys.stderr,
                )
                return 1
        print(f"{collection}: copied {len(missing)}, already present {present}")
    return 0


def main_serve(args):
    import sys

    from orion_tpu.storage.netdb import serve

    secret = None
    if args.secret_file:
        # Same read-strip-validate (and clean error surface) as the client
        # side's secret resolution.
        from orion_tpu.storage.base import _resolve_network_secret

        secret = _resolve_network_secret({"secret_file": args.secret_file})
    elif not args.no_auth:
        # Secure by default: binding 0.0.0.0 without credentials hands the
        # whole experiment to anyone on the network.
        print(
            "ERROR: refusing to serve without authentication.  Pass "
            "--secret-file <path> (recommended), or --no-auth for localhost "
            "development.",
            file=sys.stderr,
        )
        return 1
    serve(
        host=args.host,
        port=args.port,
        persist=args.persist,
        secret=secret,
        replicate_to=args.replicate_to,
        replica=args.replica,
        quorum=args.quorum,
    )
    return 0


def _sharded_router_or_error(args):
    """Resolve the configured storage and require the consistent-hash
    router; returns ``(storage, router)`` or ``(None, None)`` after
    printing the remedy."""
    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    router = storage.db
    if not hasattr(router, "describe_topology"):
        print(
            "storage is not sharded; add a `shards:` stanza to the storage "
            "config (or set ORION_DB_SHARDS) — see docs/multi_node.md"
        )
        return None, None
    return storage, router


def _print_plan(plan):
    summary = plan.summary()
    print(
        f"rebalance plan: {summary['moves']} of {summary['experiments']} "
        f"experiment(s) move ({summary['move_fraction']:.1%}); "
        f"{summary['stays']} already home"
    )
    for move in plan.moves:
        print(f"  {move.describe()}")
    for exp_id, homes in plan.strays:
        print(f"  STRAY {exp_id}: found on shards {homes} with no placement record")


def main_ring(args):
    """`db ring`: the operator's placement oracle — which shard owns each
    experiment, and what the topology looks like, computed from the SAME
    ring every router instance builds (no server round trips needed for
    the placement itself; the experiment list is read through the
    router).  ``--diff`` shows the rebalance plan instead: where each
    displaced experiment currently lives vs where the ring now puts it."""
    from orion_tpu.cli.base import describe_storage_topology

    storage, router = _sharded_router_or_error(args)
    if router is None:
        return 1
    print(describe_storage_topology(probe=True))
    topology = router.describe_topology()
    for shard in topology["shards"]:
        replicas = ", ".join(shard["replicas"]) or "none"
        serving = ""
        if shard.get("primary") and shard["primary"] != shard["address"]:
            serving = f"  primary NOW: {shard['primary']} (epoch {shard.get('epoch', 0)})"
        print(
            f"  shard {shard['index']}: {shard['address']}  "
            f"replicas: {replicas}{serving}"
        )
    if getattr(args, "diff", False):
        from orion_tpu.storage.rebalance import Rebalancer

        plan = Rebalancer(router).plan()
        _print_plan(plan)
        if plan.moves:
            n = len(topology["shards"])
            print(
                f"(~1/N invariant: {plan.move_fraction:.1%} moving vs "
                f"1/{n} = {1 / n:.1%} expected after adding one shard)"
            )
            print("run `orion-tpu db rebalance` to execute this plan")
        return 0
    docs = storage.fetch_experiments({})
    if not docs:
        print("no experiments in storage")
        return 0
    print(f"{len(docs)} experiment(s):")
    for doc in sorted(docs, key=lambda d: (d["name"], d.get("version", 1))):
        shard = router.shard_for(doc["_id"])
        print(
            f"  {doc['name']} v{doc.get('version', 1)} "
            f"({doc['_id']}) -> shard {shard}"
        )
    return 0


def main_rebalance(args):
    """`db rebalance`: execute the ring diff — migrate every displaced
    experiment to its ring home through the crash-resumable placement
    state machine (storage/rebalance.py).  Re-run after any crash: the
    plan is recomputed from the standing placement docs and resumes."""
    from orion_tpu.storage.rebalance import Rebalancer

    _storage, router = _sharded_router_or_error(args)
    if router is None:
        return 1
    rebalancer = Rebalancer(router, fence_grace=args.fence_grace)
    plan = rebalancer.plan()
    _print_plan(plan)
    if args.dry_run or not plan.moves:
        return 1 if plan.strays else 0
    if plan.strays:
        print("ERROR: strays present — resolve before rebalancing")
        return 1
    rebalancer.run(plan)
    moved = len(plan.moves)
    print(f"rebalanced {moved} experiment(s); placement == ring again")
    return 0


def _resolve_shard_arg(router, value):
    """A ``db drain`` SHARD operand: an index or a ring identity."""
    try:
        index = int(value)
    except ValueError:
        index = None
        for shard in router.describe_topology()["shards"]:
            if value in (shard["address"], shard["primary"]):
                index = shard["index"]
                break
    return index


def main_drain(args):
    """`db drain SHARD`: run the ring diff BEFORE the shard disappears —
    migrate every resident experiment to its post-removal ring home
    (storage/drain.py), verify the shard is empty, then tell the operator
    to drop it from the shards: stanza.  Re-run after any crash: the plan
    is recomputed from the standing placement docs and resumes."""
    import sys

    from orion_tpu.storage.drain import Drainer
    from orion_tpu.utils.exceptions import DatabaseError

    _storage, router = _sharded_router_or_error(args)
    if router is None:
        return 1
    index = _resolve_shard_arg(router, args.shard)
    if index is None:
        print(
            f"ERROR: no shard matches {args.shard!r} — pass an index or a "
            "ring identity from `db status`",
            file=sys.stderr,
        )
        return 1
    try:
        drainer = Drainer(router, index, fence_grace=args.fence_grace)
    except DatabaseError as exc:
        print(f"ERROR: {exc}", file=sys.stderr)
        return 1
    plan = drainer.plan()
    print(
        f"drain shard {index} ({drainer.drain_identity}): "
        f"{len(plan.moves)} experiment(s) to move "
        f"(ring share {drainer.ring_share():.1%})"
    )
    for move in plan.moves:
        print(f"  {move.describe()}")
    for exp_id, homes in plan.strays:
        print(f"  STRAY {exp_id}: needs `db rebalance` first (shards {homes})")
    if args.dry_run:
        return 1 if plan.strays else 0
    if plan.strays:
        print(
            "ERROR: strays present — run `orion-tpu db rebalance` first, "
            "then drain",
            file=sys.stderr,
        )
        return 1
    try:
        drainer.run(plan)
    except DatabaseError as exc:
        print(f"ERROR: drain failed: {exc}", file=sys.stderr)
        print(f"re-run `orion-tpu db drain {args.shard}` to resume", file=sys.stderr)
        return 1
    residual = drainer.residual_experiments()
    if residual:
        print(
            f"ERROR: {len(residual)} experiment(s) still resident after the "
            f"drain: {residual[:3]} — re-run to resume",
            file=sys.stderr,
        )
        return 1
    print(
        f"shard {index} drained: {len(plan.moves)} experiment(s) moved, "
        "0 resident"
    )
    print(
        f"now remove {drainer.drain_identity} from the storage shards: "
        "stanza (every router picks the new ring up via set_topology / "
        "restart) and retire the server"
    )
    return 0


def main_status(args):
    """`db status`: the storage fleet at a glance — one probed line per
    shard (role, epoch, seq, quorum floor, per-replica lag), same
    rendering discipline as the `top --all` fleet header."""
    import json

    from orion_tpu.cli.base import describe_storage_topology

    _storage, router = _sharded_router_or_error(args)
    if router is None:
        return 1
    topology = router.describe_topology()
    health = router.replication_health()
    if args.json:
        print(
            json.dumps(
                {
                    "vnodes": topology["vnodes"],
                    "replica_reads": topology["replica_reads"],
                    "shards": health,
                },
                indent=2,
                sort_keys=True,
            )
        )
        return 0
    print(describe_storage_topology(probe=True))
    for entry in health:
        if entry.get("error"):
            print(
                f"  s{entry['index']} {entry['address']}  "
                f"DOWN ({entry['error']})"
            )
            continue
        quorum = entry.get("quorum", 0)
        line = (
            f"  s{entry['index']} {entry['address']}  "
            f"{entry.get('role', '?')}@{entry['primary']}  "
            f"epoch {entry.get('epoch', 0)}  seq {entry.get('seq', 0)}  "
            f"quorum {quorum if quorum else 'off'}"
        )
        print(line)
        for row in entry.get("replicas", ()):
            if row.get("error"):
                detail = f"DOWN ({row['error']})"
            else:
                detail = f"seq {row.get('seq', 0)}  lag {row.get('lag', '?')}"
                if row.get("resyncing"):
                    detail += "  RESYNCING"
            print(f"      replica {row['address']}  {detail}")
    return 0


def main_migrate_ids(args):
    """`db migrate-ids`: rewrite trial ids to ``--scheme`` through the
    crash-resumable copy/verify/flip/delete state machine
    (storage/migrate_ids.py).  Re-run after any crash: the plan is
    recomputed from the standing migration docs and resumes.  Run with no
    active producers on the affected experiments."""
    import sys

    from orion_tpu.storage.migrate_ids import IdMigrator
    from orion_tpu.utils.exceptions import DatabaseError

    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    migrator = IdMigrator(storage, to_scheme=args.scheme)
    rows = migrator.plan(experiment=args.name)
    if not rows:
        print(f"nothing to migrate: every experiment already uses {args.scheme!r}")
        return 0
    print(f"migrate-ids plan: {len(rows)} experiment(s) -> {args.scheme!r}")
    for row in rows:
        print(f"  {row.describe()}")
    if args.dry_run:
        return 0
    try:
        migrator.run(rows)
    except DatabaseError as exc:
        print(f"ERROR: migrate-ids failed: {exc}", file=sys.stderr)
        print("re-run `orion-tpu db migrate-ids` to resume", file=sys.stderr)
        return 1
    rewritten = sum(row.rewritten for row in rows)
    print(
        f"migrated {len(rows)} experiment(s) ({rewritten} doc(s) rewritten); "
        "run `orion-tpu audit --all` to verify"
    )
    return 0


def main_backup(args):
    """`db backup --out DIR`: one consistent snapshot per shard + manifest."""
    import sys

    from orion_tpu.storage.backup import backup_topology
    from orion_tpu.utils.exceptions import DatabaseError

    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    db = storage.db
    if not hasattr(db, "_call") and not hasattr(db, "shard_connections"):
        print(
            "ERROR: `db backup` snapshots network/sharded storage; for "
            "file-backed storage use `db dump`",
            file=sys.stderr,
        )
        return 1
    try:
        manifest = backup_topology(db, args.out)
    except DatabaseError as exc:
        print(f"ERROR: backup failed: {exc}", file=sys.stderr)
        return 1
    total = sum(entry["docs"] for entry in manifest["shards"])
    for entry in manifest["shards"]:
        print(
            f"  shard {entry['index']} ({entry['address']}): "
            f"{entry['docs']} docs at seq {entry['seq']} epoch {entry['epoch']}"
        )
    print(f"backed up {total} documents from "
          f"{len(manifest['shards'])} shard(s) to {args.out}")
    return 0


def main_restore(args):
    """`db restore --src DIR`: rebuild a fresh topology from a backup."""
    import sys

    from orion_tpu.storage.backup import restore_topology
    from orion_tpu.utils.exceptions import DatabaseError

    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    db = storage.db
    if not hasattr(db, "apply_batch"):
        print(
            "ERROR: `db restore` targets network/sharded storage; for "
            "file-backed storage use `db load`",
            file=sys.stderr,
        )
        return 1
    try:
        summary = restore_topology(db, args.src, require_empty=not args.force)
    except DatabaseError as exc:
        print(f"ERROR: restore failed: {exc}", file=sys.stderr)
        return 1
    for collection, count in sorted(summary["collections"].items()):
        if count:
            print(f"  {collection}: {count}")
    print(
        f"restored {summary['documents']} documents through the current "
        "ring; run `orion-tpu audit --all` to verify"
    )
    return 0


def main_setup(args):
    path = user_config_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    storage = {"type": args.storage_type}
    if args.storage_type == "network":
        storage["host"] = args.host
        storage["port"] = args.port
        if args.secret_file:
            storage["secret_file"] = os.path.abspath(args.secret_file)
    elif args.path:
        storage["path"] = os.path.abspath(args.path)
    elif args.storage_type in ("pickled", "sqlite"):
        ext = "pkl" if args.storage_type == "pickled" else "sqlite"
        storage["path"] = os.path.join(
            os.path.dirname(path), f"orion_tpu_db.{ext}"
        )
    existing = {}
    if os.path.exists(path):
        with open(path) as handle:
            existing = yaml.safe_load(handle) or {}
    existing["storage"] = storage
    with open(path, "w") as handle:
        yaml.safe_dump(existing, handle, default_flow_style=False)
    print(f"Wrote storage configuration to {path}")
    return 0


# --- staged checks (reference cli/checks/: presence, creation, operations) ---


def check_presence(config):
    """Stage 1: a storage configuration can be resolved at all."""
    if not config.get("storage") or not config["storage"].get("type"):
        raise CheckError("no storage configuration found")
    return f"storage type {config['storage']['type']!r}"


def check_creation(config):
    """Stage 2: the backend can be instantiated and locked."""
    storage = setup_storage(config["storage"], force=True)
    storage.db.read("experiments", {"_id": "__check__"})
    return type(storage.db).__name__


def check_operations(config):
    """Stage 3: write / read / count / remove roundtrip."""
    storage = setup_storage(config["storage"], force=True)
    db = storage.db
    db.remove("_checks", {})
    db.write("_checks", {"_id": "c1", "value": 1})
    if db.count("_checks") != 1:
        raise CheckError("count after write != 1")
    doc = db.read_and_write("_checks", {"_id": "c1"}, {"value": 2})
    if doc is None or doc["value"] != 2:
        raise CheckError("read_and_write failed")
    db.remove("_checks", {})
    if db.count("_checks") != 0:
        raise CheckError("remove failed")
    return "write/read/cas/remove ok"


def main_test(args):
    config = load_cli_config(args)
    failures = 0
    for stage, check in (
        ("presence", check_presence),
        ("creation", check_creation),
        ("operations", check_operations),
    ):
        try:
            detail = check(config)
            print(f"check {stage}... ok ({detail})")
        except Exception as exc:
            print(f"check {stage}... FAIL: {exc}")
            failures += 1
            break  # later stages depend on earlier ones
    return 1 if failures else 0


def main_upgrade(args):
    """Schema migration: re-ensure indexes, backfill missing fields."""
    config = load_cli_config(args)
    storage = setup_storage(config["storage"], force=True)
    migrated = 0
    for doc in storage.fetch_experiments({}):
        updates = {}
        if "version" not in doc:
            updates["version"] = 1
        if "priors" not in doc:
            updates["priors"] = (doc.get("metadata") or {}).get("priors", {})
        if "refers" not in doc:
            updates["refers"] = {}
        if "strategy" not in doc:
            # Reference schema nests it (`producer.strategy`,
            # reference experiment.py:120 / configuration dict).
            strategy = (doc.get("producer") or {}).get("strategy")
            if isinstance(strategy, str):
                updates["strategy"] = strategy
        if updates:
            storage.update_experiment(uid=doc["_id"], **updates)
            migrated += 1
    # Trials: backfill parents list.
    n_trials = storage.db.write(
        "trials", {"parents": []}, query={"parents": None}
    )
    # Reference-schema trials: params is [{name, type, value}, ...]
    # (reference `core/worker/trial.py` Param list) — convert to this
    # framework's params dict keyed by name, batched so a file-backed
    # destination pays one lock/rewrite cycle, not one per trial.
    pairs = [
        (
            {"_id": doc["_id"]},
            {"params": {p["name"]: p["value"] for p in doc["params"]}},
        )
        for doc in storage.db.read("trials")
        if isinstance(doc.get("params"), list)
    ]
    if pairs:
        n_trials += storage.db.update_many("trials", pairs)
    print(f"Upgraded {migrated} experiments, {n_trials} trials.")
    return 0
