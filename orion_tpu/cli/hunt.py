"""`orion-tpu hunt`: run the optimization loop.

Capability parity: reference `src/orion/core/cli/hunt.py` — build/branch the
experiment from args, then `workon` it.
"""

import sys

from orion_tpu.cli.base import add_experiment_args, build_from_args
from orion_tpu.core.worker import format_stats, workon
from orion_tpu.utils.exceptions import BrokenExperiment


def add_subparser(subparsers):
    parser = subparsers.add_parser("hunt", help="run optimization")
    add_experiment_args(parser)
    group = parser.add_argument_group("worker")
    group.add_argument("--max-trials", type=int, default=None, help="total completed-trial budget")
    group.add_argument(
        "--worker-trials",
        type=int,
        default=None,
        help="trials this worker executes before exiting (default: unlimited)",
    )
    group.add_argument("--pool-size", type=int, default=None,
                       help="suggestions per producer round")
    group.add_argument("--working-dir", default=None, help="permanent trial working directory")
    group.add_argument("--max-broken", type=int, default=None, help="broken-trial budget")
    group.add_argument(
        "--heartbeat",
        type=float,
        default=None,
        help="seconds before a silent reserved trial counts as lost",
    )
    group.add_argument(
        "--max-idle-time",
        type=float,
        default=None,
        help="seconds the producer may go without registering a new point",
    )
    group.add_argument(
        "--pipeline-depth",
        type=int,
        default=None,
        help="speculative producer rounds kept in flight on device while "
        "host work (storage commit, codec, telemetry) runs underneath "
        "(default 1 = the classic single-slot pipeline; see "
        "docs/performance.md)",
    )
    group.add_argument(
        "--n-workers",
        type=int,
        default=1,
        help="run this many asynchronous workers against the shared storage "
        "(this process plus N-1 spawned ones; same semantics as launching "
        "the identical hunt command N times)",
    )
    group.add_argument(
        "--profile",
        metavar="DIR",
        default=None,
        help="write a jax.profiler trace of the whole hunt to DIR "
        "(inspect with TensorBoard / xprof)",
    )
    parser.set_defaults(func=main)
    return parser


# Children must never re-spawn.  Argv surgery is unsound both ways: flag
# stripping misses argparse prefix abbreviations (--n-worker), and an
# appended override lands inside the user_args REMAINDER, so the child
# still parses the original count — either way a fork bomb.  An env
# sentinel is immune to every argv form and leaves user args untouched.
_SPAWNED_ENV = "ORION_TPU_SPAWNED_WORKER"


def _spawn_workers(args, experiment):
    """N-1 child processes running the identical hunt (the reference's
    'submit the same command N times' cluster recipe, built in).  The
    experiment is built/branched BEFORE spawning so children resume it."""
    from orion_tpu.storage.documents import MemoryDB
    from orion_tpu.utils.exceptions import CheckError

    if isinstance(getattr(experiment.storage, "db", None), MemoryDB):
        raise CheckError(
            "--n-workers needs storage processes can share (--storage-path "
            "file, sqlite, or a network server); in-memory storage is "
            "per-process."
        )
    import os
    import subprocess

    argv = list(getattr(args, "_argv", []) or [])
    if not argv:
        # Programmatic callers building args by hand have no invocation to
        # replay; spawning bare children would print help and "fail".
        raise CheckError(
            "--n-workers requires the CLI invocation (argv) to replay in "
            "child processes; call through orion_tpu.cli.main, or launch "
            "workers yourself."
        )
    env = dict(os.environ)
    env[_SPAWNED_ENV] = "1"
    return [
        subprocess.Popen([sys.executable, "-m", "orion_tpu.cli", *argv], env=env)
        for _ in range(args.n_workers - 1)
    ]


def _run_worker(experiment, parser, args):
    from contextlib import nullcontext

    from orion_tpu.compiler_plane import profiler_capture

    profile_dir = getattr(args, "profile", None)
    # The shared capture helper: `orion-tpu profile --capture DIR` wraps its
    # analysis pass in the same context manager, so both commands print the
    # identical artifact summary line.
    capture = profiler_capture(profile_dir) if profile_dir else nullcontext()
    with capture:
        workon(
            experiment,
            parser,
            worker_trials=args.worker_trials,
            max_idle_time=experiment.max_idle_time,
            # Pacemaker must beat the sweep threshold comfortably or live
            # trials get recovered as lost.
            heartbeat_interval=experiment.heartbeat / 2.0,
        )


def main(args):
    import os

    experiment, parser = build_from_args(args)
    experiment.instantiate()
    workers = []
    if getattr(args, "n_workers", 1) > 1 and not os.environ.get(_SPAWNED_ENV):
        workers = _spawn_workers(args, experiment)
    try:
        try:
            _run_worker(experiment, parser, args)
        except BrokenExperiment as exc:
            print(f"Error: {exc}", file=sys.stderr)
            # Children hit the same broken budget and stop on their own.
            for proc in workers:
                proc.wait()
            return 1
    except BaseException:
        # Any other parent failure (storage errors, Ctrl-C): the cohort
        # must not be orphaned to keep consuming the budget in the
        # background after the command "exited".
        for proc in workers:
            proc.terminate()
        for proc in workers:
            proc.wait()
        raise
    # Stats must reflect the WHOLE cohort's work, so join EVERY child first
    # (a list, not a short-circuiting any(): stragglers would outlive the
    # command and keep consuming budget).
    codes = [proc.wait() for proc in workers]
    if not os.environ.get(_SPAWNED_ENV):
        # Only the parent reports; N interleaved copies of the same stats
        # block from the children would drown the terminal.
        print(format_stats(experiment))
    return 1 if any(code != 0 for code in codes) else 0
