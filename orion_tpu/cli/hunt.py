"""`orion-tpu hunt`: run the optimization loop.

Capability parity: reference `src/orion/core/cli/hunt.py` — build/branch the
experiment from args, then `workon` it.
"""

import sys

from orion_tpu.cli.base import add_experiment_args, build_from_args
from orion_tpu.core.worker import format_stats, workon
from orion_tpu.utils.exceptions import BrokenExperiment


def add_subparser(subparsers):
    parser = subparsers.add_parser("hunt", help="run optimization")
    add_experiment_args(parser)
    group = parser.add_argument_group("worker")
    group.add_argument("--max-trials", type=int, default=None, help="total completed-trial budget")
    group.add_argument(
        "--worker-trials",
        type=int,
        default=None,
        help="trials this worker executes before exiting (default: unlimited)",
    )
    group.add_argument("--pool-size", type=int, default=None, help="suggestions per producer round")
    group.add_argument("--working-dir", default=None, help="permanent trial working directory")
    group.add_argument("--max-broken", type=int, default=None, help="broken-trial budget")
    group.add_argument(
        "--heartbeat",
        type=float,
        default=None,
        help="seconds before a silent reserved trial counts as lost",
    )
    group.add_argument(
        "--max-idle-time",
        type=float,
        default=None,
        help="seconds the producer may go without registering a new point",
    )
    group.add_argument(
        "--profile",
        metavar="DIR",
        default=None,
        help="write a jax.profiler trace of the whole hunt to DIR "
        "(inspect with TensorBoard / xprof)",
    )
    parser.set_defaults(func=main)
    return parser


def main(args):
    experiment, parser = build_from_args(args)
    experiment.instantiate()
    profile_dir = getattr(args, "profile", None)
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
    try:
        workon(
            experiment,
            parser,
            worker_trials=args.worker_trials,
            max_idle_time=experiment.max_idle_time,
            # Pacemaker must beat the sweep threshold comfortably or live
            # trials get recovered as lost.
            heartbeat_interval=experiment.heartbeat / 2.0,
        )
    except BrokenExperiment as exc:
        print(f"Error: {exc}", file=sys.stderr)
        return 1
    finally:
        if profile_dir:
            import jax

            jax.profiler.stop_trace()
            print(f"jax profiler trace written to {profile_dir}", file=sys.stderr)
    print(format_stats(experiment))
    return 0
