"""`orion-tpu flight-record`: dump an experiment's flight-recorder events.

No reference counterpart — part of the TPU build's optimization-health
subsystem (orion_tpu.health).  Workers running with the flight recorder
enabled mirror their ring events into the spans storage channel every
producer round (as ``flight.*`` records); this command reconstructs that
timeline from storage, merges this process's own ring (usually empty for
a plain CLI invocation), and writes one JSONL artifact — the same format
a worker crash or a failed ``orion-tpu audit`` dumps automatically.
"""

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "flight-record",
        help="dump an experiment's flight-recorder events to a JSONL artifact",
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--out",
        default=None,
        metavar="path",
        help="output file (default: flight-<experiment>.jsonl)",
    )
    parser.set_defaults(func=main)
    return parser


def main(args):
    from orion_tpu.health import FLIGHT, spans_as_flight_events

    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    stored = spans_as_flight_events(experiment.storage.fetch_spans(experiment))
    local = FLIGHT.events()
    if not stored and not local:
        print(
            f"no flight events recorded for experiment {experiment.name!r} — "
            "run the hunt with ORION_TPU_TELEMETRY=1 (or `telemetry: true` "
            "in the config) to collect them"
        )
        return 1
    out = args.out or f"flight-{experiment.name}.jsonl"
    path = FLIGHT.dump(out, reason="on-demand", extra_events=stored)
    workers = {e.get("worker") for e in stored if e.get("worker")}
    print(
        f"wrote {len(stored) + len(local)} events "
        f"({len(stored)} from storage, {max(len(workers), 1)} worker(s)) "
        f"to {path}"
    )
    return 0
