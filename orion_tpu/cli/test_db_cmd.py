"""`orion-tpu test-db` — top-level alias for `db test`.

Capability parity: reference `src/orion/core/cli/test_db.py` keeps the
historical `orion test-db` spelling alongside `orion db test`; both run the
staged presence / creation / operations storage checks.
"""

from orion_tpu.cli.db import _common, main_test


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "test-db", help="run staged storage checks (alias for `db test`)"
    )
    _common(parser)
    parser.set_defaults(func=main_test)
    return parser
