"""`orion-tpu` command-line interface.

Capability parity: reference `src/orion/core/cli/__init__.py` + `cli/base.py`
— subcommand modules are auto-discovered (any module in this package exposing
``add_subparser``), global verbosity/version/debug flags, and common
experiment argument groups shared across commands.
"""

import argparse
import importlib
import logging
import pkgutil
import sys

import orion_tpu

log = logging.getLogger(__name__)


def build_parser():
    parser = argparse.ArgumentParser(
        prog="orion-tpu",
        description="TPU-native asynchronous hyperparameter optimization",
    )
    parser.add_argument(
        "-V", "--version", action="version", version=f"orion-tpu {orion_tpu.__version__}"
    )
    parser.add_argument(
        "-v",
        "--verbose",
        action="count",
        default=0,
        help="logging level: -v info, -vv debug",
    )
    subparsers = parser.add_subparsers(dest="command", metavar="COMMAND")

    import orion_tpu.cli as cli_pkg

    for module_info in sorted(pkgutil.iter_modules(cli_pkg.__path__), key=lambda m: m.name):
        if module_info.name.startswith("_") or module_info.name == "base":
            continue
        module = importlib.import_module(f"orion_tpu.cli.{module_info.name}")
        if hasattr(module, "add_subparser"):
            module.add_subparser(subparsers)
    return parser


def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    # Raw argv: commands that re-spawn themselves (hunt --n-workers) need
    # the exact invocation, not a reconstruction from parsed args.
    args._argv = argv
    level = {0: logging.WARNING, 1: logging.INFO}.get(args.verbose, logging.DEBUG)
    logging.basicConfig(level=level, format="%(levelname)s %(name)s: %(message)s")
    if not getattr(args, "func", None):
        parser.print_help()
        return 1
    from orion_tpu.utils.exceptions import (
        CheckError,
        DatabaseError,
        NoConfigurationError,
    )

    try:
        return args.func(args) or 0
    except (NoConfigurationError, DatabaseError, CheckError) as exc:
        # Expected operational failures (bad credentials, unreachable or
        # misconfigured storage) get a one-line error, not a traceback;
        # -v re-raises for debugging.
        if args.verbose:
            raise
        print(f"ERROR: {exc}", file=sys.stderr)
        return 1
