"""`orion-tpu serve`: run the multi-tenant suggest gateway.

No reference counterpart — part of the TPU build's serving subsystem
(``orion_tpu.serve``).  One long-lived process owns the device and the
algorithm instances for N experiments; workers point at it with
``serve: {address: host:port}`` (or ``--serve-address`` equivalents in
their config) and concurrent suggest traffic is coalesced into fused
device dispatches.  See ``docs/serving.md`` for the protocol, coalescing
semantics, and tenancy knobs.
"""


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "serve", help="run the multi-tenant suggest gateway"
    )
    parser.add_argument("--host", default="127.0.0.1", help="bind address")
    parser.add_argument(
        "--port", type=int, default=8777, help="bind port (default 8777)"
    )
    parser.add_argument(
        "--window-ms",
        type=float,
        default=4.0,
        metavar="ms",
        help="coalescing window: how long the dispatcher waits after the "
        "first queued suggest for more same-signature traffic (default 4ms)",
    )
    parser.add_argument(
        "--max-width",
        type=int,
        default=8,
        metavar="N",
        help="widest single coalesced dispatch (tenant axis, pow-2 padded)",
    )
    parser.add_argument(
        "--max-tenants",
        type=int,
        default=256,
        metavar="N",
        help="hosted-experiment cap; attaches beyond it evict the "
        "least-recently-active idle tenant",
    )
    parser.add_argument(
        "--max-inflight",
        type=int,
        default=4,
        metavar="N",
        help="per-tenant concurrent-suggest quota (excess gets RETRY-AFTER)",
    )
    parser.add_argument(
        "--max-q",
        type=int,
        default=4096,
        metavar="N",
        help="per-tenant per-ask suggestion cap",
    )
    parser.add_argument(
        "--pending-limit",
        type=int,
        default=256,
        metavar="N",
        help="bounded admission queue; a full queue answers RETRY-AFTER",
    )
    parser.add_argument(
        "--persist",
        default=None,
        metavar="path",
        help="snapshot tenant state (history, trust region, RNG stream) so "
        "a restarted gateway resumes its tenants without client replay.  "
        "In fleet mode this is a DIRECTORY of per-tenant snapshots "
        "(shared storage lets a survivor restore a killed member's "
        "tenants bit-identically)",
    )
    parser.add_argument(
        "--fleet",
        default=None,
        metavar="addr1,addr2,...",
        help="run as one member of a gateway fleet: the full comma-"
        "separated member list (this gateway included).  Tenants are "
        "placed on members by consistent hash; membership changes "
        "(fleet_set) migrate tenants through a fenced zero-loss handoff "
        "(docs/serving.md \"Fleet deployment\")",
    )
    parser.add_argument(
        "--advertise",
        default=None,
        metavar="host:port",
        help="this member's own address exactly as it appears in --fleet "
        "(defaults to host:port when that spelling is in the list)",
    )
    parser.add_argument(
        "--handoff-ttl",
        type=float,
        default=30.0,
        metavar="s",
        help="seconds a fenced tenant may stay in handoff before the "
        "DX008 doctor rule calls it stuck (default 30)",
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve /metrics (Prometheus text exposition of the gateway's "
        "telemetry registry) and /healthz (queue depth, tenant count) on "
        "this port",
    )
    parser.add_argument(
        "--secret-file",
        default=None,
        metavar="path",
        help="file holding the shared secret clients must prove knowledge "
        "of (the netdb HMAC handshake on the gateway wire).  Clients set "
        "ORION_SERVE_SECRET_FILE or serve.secret_file.",
    )
    parser.add_argument(
        "--no-auth",
        action="store_true",
        help="explicitly run WITHOUT authentication (localhost development "
        "only — any peer that can reach the port can drive every tenant's "
        "suggestion stream)",
    )
    parser.set_defaults(func=main)
    return parser


def main(args):  # pragma: no cover - thin CLI shim over serve()
    import sys

    from orion_tpu.serve.gateway import serve

    secret = None
    if args.secret_file:
        from orion_tpu.storage.base import resolve_wire_secret

        secret = resolve_wire_secret(
            {"secret_file": args.secret_file},
            env_prefix="ORION_SERVE",
            what="serve gateway",
        )
    elif not args.no_auth:
        # Secure by default, same contract as `db serve`: an open gateway
        # hands every tenant's suggestion stream to anyone on the network.
        print(
            "ERROR: refusing to serve without authentication.  Pass "
            "--secret-file <path> (recommended), or --no-auth for "
            "localhost development.",
            file=sys.stderr,
        )
        return 1
    if args.metrics_port is not None:
        # Asking for a scrape endpoint IS asking for metrics: a gateway
        # started with --metrics-port but without ORION_TPU_TELEMETRY
        # would serve an empty exposition forever.
        from orion_tpu.telemetry import TELEMETRY

        TELEMETRY.enable()
    fleet = None
    advertise = None
    if args.fleet:
        fleet = [s.strip() for s in args.fleet.split(",") if s.strip()]
        advertise = args.advertise or f"{args.host}:{args.port}"
    serve(
        host=args.host,
        port=args.port,
        window=args.window_ms / 1e3,
        max_width=args.max_width,
        max_tenants=args.max_tenants,
        max_inflight=args.max_inflight,
        max_q=args.max_q,
        pending_limit=args.pending_limit,
        persist=args.persist,
        metrics_port=args.metrics_port,
        secret=secret,
        fleet=fleet,
        advertise=advertise,
        handoff_ttl=args.handoff_ttl,
    )
    return 0
