"""`orion-tpu doctor`: SLO watchdog and automated diagnosis over every
telemetry plane.

No reference counterpart — the diagnosis layer (``orion_tpu.diagnosis``)
joins the storage telemetry/health/flight channels and the sharded
control plane's replication probes into one snapshot and evaluates the
severity-ranked rule catalog (docs/monitoring.md, "Diagnosis & runbook").

Exit-code contract for automation: 0 = healthy (info/warn findings are
advice), 1 = at least one CRITICAL finding.  ``--watch`` re-diagnoses
every interval, deduplicating repeat findings into one alert each
(published as ``flight.alert`` events into the experiment's spans channel
and as the ``doctor.findings.*`` gauges), and accumulates replication
probes so the lag-growth trend rule has a series to work with.
"""

import json
import sys
import time

from orion_tpu.cli.base import (
    add_experiment_args,
    build_all_experiments,
    build_from_args,
)


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "doctor",
        help="diagnose a hunt: severity-ranked findings with runbook links",
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--all",
        action="store_true",
        help="diagnose every experiment in the store (a serve gateway "
        "hosts many tenants), not just -n NAME",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="print machine-readable findings (one report per experiment)",
    )
    parser.add_argument(
        "--watch",
        action="store_true",
        help="re-diagnose every interval; repeat findings alert once and "
        "re-alert only after clearing",
    )
    parser.add_argument(
        "-i",
        "--interval",
        type=float,
        default=10.0,
        metavar="seconds",
        help="watch-mode interval (default: 10s)",
    )
    parser.add_argument(
        "--iterations",
        type=int,
        default=0,
        metavar="N",
        help="watch mode: run N passes then exit with the last status "
        "(default 0 = until interrupted)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the registered rule catalog and exit",
    )
    parser.set_defaults(func=main)
    return parser


def _resolve_experiments(args, view=True):
    """One-shot diagnosis is read-only (a view); ``--watch`` publishes
    alert spans into the storage channel, so it builds real experiments —
    the write is the point, not an accident a view should block."""
    if getattr(args, "all", False):
        return build_all_experiments(args, view=view)
    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=view
    )
    return [experiment]


def _diagnose(experiments, replication_series):
    """(label, experiment, report) per experiment; the per-experiment
    replication probe history is threaded through ``replication_series``
    (a dict the watch loop owns) so trend rules see a series."""
    from orion_tpu.diagnosis import collect_snapshot, run_rules

    out = []
    for experiment in experiments:
        label = f"{experiment.name} v{experiment.version}"
        snapshot = collect_snapshot(
            experiment, replication_series=replication_series.get(label)
        )
        if snapshot.replication is not None:
            history = replication_series.setdefault(label, [])
            history.append(snapshot.replication)
            del history[:-32]
        out.append((label, experiment, run_rules(snapshot)))
    return out


def main(args):
    if getattr(args, "list_rules", False):
        from orion_tpu.diagnosis import doctor_catalog

        for rule_id, name, severity, runbook, description in doctor_catalog():
            print(f"{rule_id} [{severity}] {name}: {description}")
            print(f"    runbook: docs/monitoring.md#{runbook}")
        return 0

    replication_series = {}
    if not args.watch:
        experiments = _resolve_experiments(args)
        results = _diagnose(experiments, replication_series)
        exit_code = 0
        outputs = []
        for label, _experiment, report in results:
            exit_code = max(exit_code, report.exit_code)
            if args.json:
                outputs.append({"experiment": label, **report.to_dict()})
            else:
                outputs.append(report.format_human(label))
        if args.json:
            print(json.dumps(outputs if getattr(args, "all", False) else outputs[0]))
        else:
            print("\n\n".join(outputs))
        return exit_code

    from orion_tpu.diagnosis import publish_report
    from orion_tpu.diagnosis.watch import AlertDeduper

    dedupers = {}
    passes = 0
    exit_code = 0
    try:
        while True:
            # --all re-resolves each pass: a watch on a gateway store must
            # pick up experiments attached after it started.
            experiments = _resolve_experiments(args, view=False)
            frames = []
            reports = []
            exit_code = 0
            for label, experiment, report in _diagnose(
                experiments, replication_series
            ):
                deduper = dedupers.setdefault(label, AlertDeduper())
                publish_report(
                    report,
                    new_findings=deduper.new_findings(report.findings),
                    storage=experiment.storage,
                    experiment=experiment,
                )
                exit_code = max(exit_code, report.exit_code)
                frames.append(report.format_human(label))
                reports.append({"experiment": label, **report.to_dict()})
            # Per-experiment watch state lives only as long as the
            # experiment does: a store with tenant churn must not grow
            # dedupers/probe history without bound, and a deleted-then-
            # recreated experiment must not inherit its predecessor's
            # dedup state (its first alert would be silently swallowed).
            current = {r["experiment"] for r in reports}
            for stale in set(dedupers) - current:
                del dedupers[stale]
            for stale in set(replication_series) - current:
                del replication_series[stale]
            if args.json:
                sys.stdout.write(
                    json.dumps(
                        {
                            "pass": passes + 1,
                            "time": time.time(),
                            "status": "critical" if exit_code else "ok",
                            # The full findings, not just the verdict: the
                            # JSON stream is the automation surface, and a
                            # consumer must learn WHICH rule fired where.
                            "experiments": reports,
                        }
                    )
                    + "\n"
                )
            else:
                sys.stdout.write("\x1b[2J\x1b[H" + "\n\n".join(frames) + "\n")
            sys.stdout.flush()
            passes += 1
            if args.iterations and passes >= args.iterations:
                return exit_code
            time.sleep(max(args.interval, 0.5))
    except KeyboardInterrupt:
        return exit_code
