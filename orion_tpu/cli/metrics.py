"""`orion-tpu metrics`: the merged cross-worker snapshot as Prometheus text.

No reference counterpart — part of the TPU build's metrics export plane
(orion_tpu.metrics).  Workers flush their telemetry snapshots through the
storage metrics channel; this command merges them
(``telemetry.merge_snapshots`` — counters/buckets sum, gauges MAX) and
renders the result in Prometheus text exposition format, the same body a
live ``/metrics`` endpoint serves.  For airgapped scraping: no open port
on any worker — run this against the shared store and hand the output to
a Pushgateway, a node-exporter textfile collector, or a file the scraper
reads.
"""

from orion_tpu.cli.base import add_experiment_args, build_from_args


def add_subparser(subparsers):
    parser = subparsers.add_parser(
        "metrics",
        help="merged cross-worker metrics in Prometheus exposition format",
    )
    add_experiment_args(parser, with_user_args=False)
    parser.add_argument(
        "--out",
        default=None,
        metavar="path",
        help="write the exposition to a file instead of stdout (textfile-"
        "collector handoff)",
    )
    parser.set_defaults(func=main)
    return parser


def main(args):
    from orion_tpu.metrics import render_exposition
    from orion_tpu.telemetry import merge_snapshots

    experiment, _parser = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    docs = experiment.storage.fetch_metrics(experiment)
    if not docs:
        print(
            f"no metrics recorded for experiment {experiment.name!r} — run "
            "the hunt with ORION_TPU_TELEMETRY=1 (or `telemetry: true` in "
            "the config) to collect them"
        )
        return 1
    body = render_exposition(merge_snapshots(docs))
    if args.out:
        with open(args.out, "w") as handle:
            handle.write(body)
        print(f"wrote exposition of {len(docs)} worker snapshot(s) to {args.out}")
    else:
        print(body, end="")
    return 0
