"""Layered configuration resolution.

Capability parity: reference `src/orion/core/io/resolve_config.py` +
`io/config.py` — precedence **defaults < environment < config file <
command line** (reference `experiment_builder.py:13-88`), with the worker
knobs (`heartbeat`, `max_broken`, `max_idle_time`) and storage selection
(`ORION_DB_TYPE` / `ORION_DB_ADDRESS` env overrides) of the reference's
global Configuration object.
"""

import os

import yaml


def user_config_path():
    """``~/.config/orion_tpu/config.yaml`` (XDG_CONFIG_HOME honored)."""
    base = os.environ.get(
        "XDG_CONFIG_HOME", os.path.join(os.path.expanduser("~"), ".config")
    )
    return os.path.join(base, "orion_tpu", "config.yaml")


def normalize_sections(cfg):
    """Accept sectioned config-file spellings alongside the canonical
    top-level keys, instead of silently ignoring them (a config whose
    `algorithms:` sits under an `experiment:` section otherwise runs
    RANDOM search without a word).  Applied to EVERY file layer — the
    user-level config.yaml is exactly where reference users keep their
    `database:` section:

    - ``experiment:`` — everything inside is hoisted to top level;
      explicit top-level keys win (shallow: the top-level value replaces
      the sectioned one whole);
    - ``producer: strategy:`` — the reference's spelling for the parallel
      strategy (`tests/functional/algos/asha_config.yaml` layout);
    - ``database:`` — the reference's storage section; create_storage
      already understands the reference's type aliases (pickleddb,
      ephemeraldb)."""
    cfg = dict(cfg)
    nested = cfg.pop("experiment", None)
    if isinstance(nested, dict):
        cfg = {**nested, **cfg}
    producer = cfg.pop("producer", None)
    if isinstance(producer, dict) and "strategy" in producer:
        cfg.setdefault("strategy", producer["strategy"])
    database = cfg.pop("database", None)
    if isinstance(database, dict):
        cfg.setdefault("storage", database)
    return cfg


def _user_file_config():
    path = user_config_path()
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as handle:
            return normalize_sections(yaml.safe_load(handle) or {})
    except Exception:  # pragma: no cover - malformed user config
        return {}


DEFAULTS = {
    "name": None,
    "version": None,
    # Per-experiment knobs default to None here: a value present at resolve
    # time is indistinguishable from a user choice and would override the
    # stored experiment's own settings on resume.  Creation-time defaults
    # live in Experiment.__init__ (max_trials=inf, max_broken=3, pool_size=1).
    "max_trials": None,
    "max_broken": None,
    "pool_size": None,
    "worker_trials": None,
    "working_dir": None,
    # algorithms/strategy defaults are applied at experiment CREATION inside
    # build_experiment, not here: a default injected at resolve time would be
    # indistinguishable from a user choice, and resuming a tpe experiment
    # without a config file would wrongly branch it back to random.
    "algorithms": None,
    "strategy": None,
    "heartbeat": 120.0,
    "max_idle_time": 60.0,
    # Producer speculative-pipeline depth (docs/performance.md "Wall ≈
    # device"): how many rounds the producer keeps in flight on device
    # while host work (storage commit, codec, telemetry flush) runs
    # underneath.  None = unset (the ORION_TPU_PIPELINE_DEPTH env var,
    # then the depth-1 pre-ring default, apply).  Worker-level knob, never
    # stored experiment identity.
    "pipeline_depth": None,
    "user_script_config": "config",
    # storage.retry holds the unified retry-policy knobs (max_attempts,
    # base_delay, max_delay, multiplier, jitter, deadline — the
    # RetryPolicy defaults apply for any omitted key; docs/robustness.md);
    # `retry: false` disables storage-level retries entirely.
    # A network storage section may also carry the sharded-topology stanza
    # (docs/multi_node.md): `shards:` — a list of "host:port" strings or
    # {address|host/port, replicas: [...]} dicts (consistent-hash routing
    # on experiment id, read-replica fan-out) — plus the router knobs
    # `vnodes`, `replica_reads`, `shard_retry`, `reconnect_jitter`.  The
    # ORION_DB_SHARDS env var carries the replica-less spelling.
    # storage.quorum is the SERVER-side replication-ack floor (`db serve
    # --quorum N`, docs/multi_node.md "Day-2 operations"): synchronous
    # collections (experiments/trials/placement) acknowledge a write only
    # after N replicas confirmed it — zero-loss under kill -9 by
    # construction; telemetry/health stay async.  Needs >= N live replicas
    # to stay writable; pair with replica auto-reprovisioning.
    "storage": {"type": "pickled", "path": "orion_tpu_db.pkl", "retry": {}},
    # Framework telemetry (orion_tpu.telemetry): None = leave the
    # process-wide registry as the ORION_TPU_TELEMETRY env var set it;
    # true/false here overrides (the CLI applies it in load_cli_config).
    "telemetry": None,
    # Metrics export plane (orion_tpu.metrics): a port number starts this
    # worker process's /metrics + /healthz daemon (Prometheus text
    # exposition of the telemetry registry); None = no server.  The env
    # spelling is ORION_TPU_METRICS_PORT.
    "metrics_port": None,
    # Self-diagnosis watchdog (orion_tpu.diagnosis, docs/monitoring.md
    # "Diagnosis & runbook"): a positive number of seconds makes every
    # workon loop run the doctor rule catalog at that interval, publishing
    # findings as flight.alert events + doctor.findings.* gauges; None =
    # no watchdog.  The env spelling is ORION_TPU_DOCTOR_INTERVAL.
    "doctor_interval": None,
    # Suggest gateway (orion_tpu.serve, docs/serving.md): a worker-level
    # knob, never part of the stored experiment identity.  None = local
    # algorithm instance (the default); {"address": "host:port", optional
    # "retry": {...}, "quotas": {"max_inflight": n, "max_q": n},
    # "timeout": s} = drive this experiment's suggest/observe through the
    # shared gateway (the ORION_SERVE_ADDRESS env var sets the address
    # alone).  A fleet is the same section with "addresses": [host:port,
    # ...] (env: ORION_SERVE_ADDRESSES, comma-separated): tenants are
    # placed on members by consistent hash (docs/serving.md "Fleet
    # deployment").
    "serve": None,
}


def _env_config():
    out = {}
    storage = {}
    db_type = os.getenv("ORION_DB_TYPE")
    if db_type:
        storage["type"] = db_type
    shards = os.getenv("ORION_DB_SHARDS")
    if shards:
        # Sharded control plane (storage/shard.py): a comma-separated list
        # of primary host:port addresses; per-shard replicas need the
        # config-file `shards:` stanza (see docs/multi_node.md).
        storage.setdefault("type", "network")
        storage["shards"] = [s.strip() for s in shards.split(",") if s.strip()]
    address = os.getenv("ORION_DB_ADDRESS")
    if address:
        if db_type in ("network", "netdb"):
            # Parse host[:port] here so the normal merge precedence applies —
            # a path-fallback downstream would lose to host/port keys merged
            # in from the user config file.
            host, _, port = address.partition(":")
            storage["host"] = host
            if port:
                storage["port"] = int(port)
        else:
            storage["path"] = address
    if storage:
        out["storage"] = storage
    serve_address = os.getenv("ORION_SERVE_ADDRESS")
    if serve_address:
        out["serve"] = {"address": serve_address}
    serve_addresses = os.getenv("ORION_SERVE_ADDRESSES")
    if serve_addresses:
        # Fleet membership: comma-separated member list.  Wins over the
        # single-address spelling when both are set (the list is the more
        # specific deployment statement).
        out.setdefault("serve", {})["addresses"] = [
            s.strip() for s in serve_addresses.split(",") if s.strip()
        ]
    # Explicit coercions — the DEFAULTS values are None, so their type can't
    # be used to coerce, and a string max_trials would poison comparisons.
    for key, cast in (("max_trials", float), ("pool_size", int), ("max_broken", int)):
        env = os.getenv(f"ORION_{key.upper()}")
        if env:
            out[key] = cast(env)
    return out


def merge_configs(*configs):
    """Deep merge, later wins; None values never override (reference
    `resolve_config.py:195-246`)."""
    out = {}
    for config in configs:
        for key, value in (config or {}).items():
            if value is None:
                continue
            if isinstance(value, dict) and isinstance(out.get(key), dict):
                out[key] = merge_configs(out[key], value)
            else:
                out[key] = value
    return out


def resolve_config(file_config=None, cmd_config=None, storage_override=None):
    """defaults < user config file < env < -c config file < cmdline."""
    config = merge_configs(
        DEFAULTS,
        _user_file_config(),
        _env_config(),
        normalize_sections(file_config or {}),
        cmd_config,
    )
    if storage_override:
        config["storage"] = storage_override
    return config
