"""XLA-vs-pallas micro-benchmark for the fused gram kernel.

`orion_tpu.ops.gram.fused_gram` claims an HBM-traffic win over the XLA
matmul+epilogue path once the (m, n) intermediate is large; this bench
MEASURES it on the attached backend so the `_PALLAS_MIN_WORK` crossover in
`algo/gp/kernels.py` is justified by data, not by argument
(VERDICT r2 weak #4).  Run:

    python -m orion_tpu.benchmarks.runner --op gram

One JSON line per shape with best-of-k wall times and the speedup.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.gp.kernels import kernel_matrix
from orion_tpu.ops.gram import _probe, fused_gram

SHAPES = [
    # (m candidates, n observations, d dims)
    (4096, 256, 8),
    (8192, 256, 8),
    (8192, 1024, 8),
    (8192, 1024, 50),
    (16384, 256, 50),
    (16384, 1024, 50),
    (16384, 1024, 128),
]


# Two chain lengths: per-op time = (t_hi - t_lo) / (K_HI - K_LO), which
# cancels the constant per-dispatch cost exactly.  The host<->device tunnel
# on this image costs ~70-80 ms per synchronous dispatch; a single-K
# amortization still leaves an RTT/K floor under every measurement (at
# K=32 that floor is ~2.3 ms — larger than the kernels being compared),
# and the K delta must be large enough that the op signal clears the
# tunnel's run-to-run jitter (sub-0.1ms ops need ~1000 iterations).
_K_LO = 8
_K_HI = 1032


def _chained(gram_fn, k):
    """k data-dependent gram computations under ONE jit.  The gram is
    consumed the way the production posterior consumes it — a matvec
    (mean = k @ alpha) plus an elementwise-square reduction (the variance
    path) — so XLA cannot slice the computation down to a single element,
    and whatever materialization it can or cannot avoid here matches what
    it can or cannot avoid in the real suggest step.  The carried scalar
    (scaled to ~1e-30) forces sequential iterations without perturbing
    numerics."""

    def many(a, b, v):
        def body(_, carry):
            acc, a_cur = carry
            g = gram_fn(a_cur, b)
            acc = acc + jnp.sum(g @ v) + jnp.sum(g * g)
            return acc, a_cur + acc * 1e-30
        acc, _ = jax.lax.fori_loop(0, k, body, (jnp.float32(0.0), a))
        return acc

    return jax.jit(many)


def _time_fn(fn, *args, reps=8, warmup=2):
    """Best-of-reps wall time (seconds); best (not mean/median) because the
    tunnel adds heavy-tailed latency noise on this image."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _per_op_seconds(gram_fn, xa, xb, v, reps):
    t_lo = _time_fn(_chained(gram_fn, _K_LO), xa, xb, v, reps=reps)
    t_hi = _time_fn(_chained(gram_fn, _K_HI), xa, xb, v, reps=reps)
    return max(t_hi - t_lo, 0.0) / (_K_HI - _K_LO)


def run_gram_bench(kind="matern52", reps=8):
    rng = np.random.default_rng(0)
    rows = []
    # Gate on the compile/run PROBE, not pallas_available(): the env
    # override forces the latter True on runtimes where lowering fails,
    # and the bench must skip the pallas column there, not crash.
    pallas_ok = _probe()
    for m, n, d in SHAPES:
        xa = jnp.asarray(rng.uniform(size=(m, d)), jnp.float32)
        xb = jnp.asarray(rng.uniform(size=(n, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
        inv_ls = jnp.ones((d,), jnp.float32) * 2.0
        amp = jnp.asarray(1.0, jnp.float32)

        xla_one = jax.jit(lambda a, b: kernel_matrix(kind, a, b, inv_ls, amp))
        t_xla = _per_op_seconds(
            lambda a, b: kernel_matrix(kind, a, b, inv_ls, amp), xa, xb, v, reps
        )
        row = {
            "op": "gram", "kind": kind, "m": m, "n": n, "d": d,
            "backend": jax.default_backend(),
            "xla_ms": round(t_xla * 1e3, 3),
        }
        if not pallas_ok:
            row["pallas_ms"] = None
            row["note"] = "pallas unavailable on this backend"
        else:
            # Numerical parity first: a fast wrong kernel is worthless.
            ref = np.asarray(xla_one(xa, xb))
            out = np.asarray(fused_gram(xa, xb, inv_ls, amp, kind=kind))
            err = float(np.max(np.abs(out - ref)))
            t_pal = _per_op_seconds(
                lambda a, b: fused_gram(a, b, inv_ls, amp, kind=kind),
                xa, xb, v, reps,
            )
            row["pallas_ms"] = round(t_pal * 1e3, 3)
            row["speedup"] = round(t_xla / max(t_pal, 1e-9), 2)
            row["max_abs_err"] = err
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows
