"""Host-side profiler for the full hunt loop.

The device decomposition (`runner --op suggest`) answers "what is the TPU
doing"; this tool answers "what is the HOST doing" — the producer/storage/
codec cycle that bounds trials/sec on the q-batch presets.  It warms the jit
caches with a short run first so compile time doesn't drown the steady-state
signal (round 5's storage copy-on-write, the inline scalar copy fast path,
and the cheap ASHA naive copies all came out of exactly this profile).

Run: ``python -m orion_tpu.benchmarks.host_profile [preset] [--trials N]``
(defaults: asha-ackley50, 2048 trials, batch 512).  Force
``JAX_PLATFORMS=cpu`` to profile host logic without a device tunnel in the
loop.
"""

import argparse
import cProfile
import io
import pstats


def main(argv=None):
    from orion_tpu.benchmarks.runner import PRESETS, run_preset

    parser = argparse.ArgumentParser(prog="orion_tpu.benchmarks.host_profile")
    parser.add_argument("preset", nargs="?", default="asha-ackley50",
                        choices=list(PRESETS))
    parser.add_argument("--trials", type=int, default=2048)
    parser.add_argument("--batch", type=int, default=512)
    parser.add_argument("--top", type=int, default=20,
                        help="rows of the tottime table to print")
    args = parser.parse_args(argv)

    # Warm pass: absorbs jit compiles and import time at a quarter budget.
    run_preset(args.preset, seed=0, max_trials=max(args.trials // 4, args.batch),
               batch_size=args.batch)

    profiler = cProfile.Profile()
    profiler.enable()
    out = run_preset(args.preset, seed=1, max_trials=args.trials,
                     batch_size=args.batch)
    profiler.disable()

    print(out)
    stream = io.StringIO()
    pstats.Stats(profiler, stream=stream).sort_stats("tottime").print_stats(args.top)
    print(stream.getvalue())


if __name__ == "__main__":
    main()
