"""Analytic benchmark functions + harness (BASELINE.md configs)."""

from orion_tpu.benchmarks.functions import (
    ackley,
    branin,
    hartmann6,
    rosenbrock,
    BENCHMARKS,
)

__all__ = ["ackley", "branin", "hartmann6", "rosenbrock", "BENCHMARKS"]
