"""Device-time / MFU measurement of the fused GP-BO suggest step.

Every published throughput number in BASELINE.md is wall-clock through this
image's remote device tunnel, whose ~100 ms round trip varies >5x run to run
(BASELINE.md:85-89) — so none of them says what the TPU itself is doing.
This bench separates the three components of a suggest round at the headline
shapes:

- ``device_ms``   — pure device execution time of the compiled step, via the
  repo's two-chain-length subtraction (gram_bench.py): K iterations of the
  step chained *inside one jit* (data-dependent, so XLA cannot elide them),
  per-step time = (t_hi - t_lo) / (K_hi - K_lo).  The constant per-dispatch
  tunnel cost cancels exactly.
- ``wall_ms``     — one dispatch of the same compiled step, forced to
  completion by the result transfer (see _time_fn: ``block_until_ready``
  does not wait on this image's remote backend), i.e. device_ms + tunnel
  round trip + the (q, d) result transfer a production round also pays.
- ``public_ms``   — one round through the public ``algo.suggest`` API
  (adds host-side copula transform, codec decode, param-dict construction).

FLOPs come from XLA's own cost model on the compiled executable via the
compiler plane's shared analysis path (``orion_tpu.compiler_plane`` — the
same ``lower().compile()`` + cost/memory extraction the CompileRegistry
runs for the runtime), not hand arithmetic; achieved
FLOP/s = flops / device_s, and MFU is quoted against the TPU v5e bf16 peak
(1.97e14 FLOP/s — "How to Scale Your Model" hardware table; the GP path
runs f32, whose MXU peak is lower, so the bf16-denominated MFU is a strict
lower bound on MXU utilization).

Run: ``python -m orion_tpu.benchmarks.runner --op suggest``
One JSON line per headline shape.
"""

import json
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.gp.gp import init_hypers
from orion_tpu.algo.tpu_bo import _suggest_step
from orion_tpu.compiler_plane import (
    device_hbm_capacity,
    lowered_analysis_fn,
    predict_hbm_bound_q,
)

V5E_PEAK_FLOPS = 1.97e14  # bf16; see module docstring

# The three headline shapes (VERDICT r4 next-1).  n_obs is the steady-state
# fit-buffer size: hartmann6's history pads to 256 through the whole timed
# bench.py loop; the trust-region presets cap the fit set at tr_local_m.
# fit_steps is the steady-state (warm-refit) count each preset actually runs.
SHAPES = {
    "hartmann6-q1024": dict(
        d=6, n_obs=192, q=1024, n_candidates=16384, fit_steps=40,
        fixed_tail_cols=0, rounds_per_run=None,
    ),
    "rosenbrock20-q256": dict(
        d=20, n_obs=256, q=256, n_candidates=16384, fit_steps=30,
        fixed_tail_cols=0, rounds_per_run=4,
    ),
    "ackley50-q512": dict(
        # asha_bo: 50 free dims + 1 pinned fidelity-context column.
        d=51, n_obs=512, q=512, n_candidates=8192, fit_steps=10,
        fixed_tail_cols=1, rounds_per_run=7,
    ),
}

_K_LO = 1
_K_HI = 17  # 16-step delta: >=80 ms of device signal at ~5 ms/step


def _step_kwargs(cfg, kernel="matern52"):
    return dict(
        q=cfg["q"],
        n_candidates=cfg["n_candidates"],
        kernel=kernel,
        acq="thompson",
        fit_steps=cfg["fit_steps"],
        local_frac=0.5,
        local_sigma=0.1,
        beta=2.0,
        trust_region=True,
        tr_perturb_dims=20,
        fixed_tail_cols=cfg["fixed_tail_cols"],
        mesh=None,
    )


def _make_args(cfg, rng):
    n, d = cfg["n_obs"], cfg["d"]
    n_pad = 1 << (n - 1).bit_length()
    x = np.zeros((n_pad, d), dtype=np.float32)
    y = np.zeros((n_pad,), dtype=np.float32)
    mask = np.zeros((n_pad,), dtype=np.float32)
    x[:n] = rng.uniform(size=(n, d))
    y[:n] = rng.normal(size=n)
    mask[:n] = 1.0
    best_x = x[int(np.argmin(y[:n]))]
    key = jax.random.PRNGKey(0)
    return (
        key,
        jnp.asarray(x),
        jnp.asarray(y),
        jnp.asarray(mask),
        jnp.asarray(best_x),
        init_hypers(d),
        jnp.float32(0.8),
    )


def _chained(k_iters, **step_kw):
    """k data-dependent suggest steps under ONE jit (see module docstring)."""

    @jax.jit
    def many(key, x, y, mask, best_x, warm, tr_len):
        def body(i, carry):
            x_cur, acc = carry
            rows, _ = _suggest_step(
                jax.random.fold_in(key, i), x_cur, y, mask, best_x, warm,
                tr_len, **step_kw,
            )
            acc = acc + jnp.sum(rows)
            # ~1e-30 perturbation: forces iteration i+1 to depend on i's
            # output without changing what is computed.
            return x + acc * 1e-30, acc

        _, acc = jax.lax.fori_loop(0, k_iters, body, (x, jnp.float32(0.0)))
        return acc

    return many


def _time_fn(fn, args, reps=8, warmup=2):
    """Best-of-reps (the tunnel adds heavy-tailed latency noise).

    Two tunnel-specific rules, both measured on this image:
    - every call gets a DISTINCT PRNG key (a byte-identical dispatch can
      come back in 0.2 ms where a fresh-keyed one costs 85-175 ms);
    - completion is forced by a HOST TRANSFER (np.asarray), because
      ``block_until_ready`` returns without waiting on the remote backend —
      timing it measures dispatch, not execution.  The transfer is part of
      every production round anyway (the producer reads the rows back)."""
    rest = args[1:]
    counter = [0]

    def call():
        counter[0] += 1
        return np.asarray(fn(jax.random.PRNGKey(1000 + counter[0]), *rest))

    for _ in range(warmup):
        call()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        call()
        best = min(best, time.perf_counter() - t0)
    return best


def _public_round_ms(name, cfg, reps=5):
    """One observe+suggest round through the public algorithm API at the
    same steady-state shape (hartmann6's is bench.py's timed loop)."""
    from orion_tpu.algo.base import create_algo
    from orion_tpu.space.dsl import build_space

    if cfg["fixed_tail_cols"]:
        return None  # asha_bo's public round is rung-scheduled, not shape-stable
    d = cfg["d"]
    rng = np.random.default_rng(0)
    space = build_space({f"x{i:02d}": "uniform(0, 1)" for i in range(d)})
    algo = create_algo(
        space,
        {"tpu_bo": {"n_init": 16, "n_candidates": cfg["n_candidates"],
                     "fit_steps": cfg["fit_steps"]}},
        seed=0,
    )
    n0 = cfg["n_obs"] - 32
    X = rng.uniform(size=(n0, d)).astype(np.float32)
    names = sorted(p for p in space.keys())

    def observe(Xb):
        params = [dict(zip(names, map(float, row))) for row in Xb]
        algo.observe(params, [{"objective": float(v)} for v in rng.normal(size=len(Xb))])

    observe(X)
    algo.suggest(cfg["q"])  # compile
    best = float("inf")
    for _ in range(reps):
        observe(rng.uniform(size=(16, d)).astype(np.float32))  # mark GP stale
        t0 = time.perf_counter()
        algo.suggest(cfg["q"])
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def device_seconds(shape, reps=8, k_hi=_K_HI, kernel="matern52"):
    """Pure device seconds per fused suggest step at a SHAPES entry, by
    two-chain subtraction — the ONE instrument, shared with bench.py's
    per-round decomposition."""
    cfg = SHAPES[shape]
    step_kw = _step_kwargs(cfg, kernel=kernel)
    args = _make_args(cfg, np.random.default_rng(0))
    t_lo = _time_fn(_chained(_K_LO, **step_kw), args, reps=reps)
    t_hi = _time_fn(_chained(k_hi, **step_kw), args, reps=reps)
    return max(t_hi - t_lo, 0.0) / (k_hi - _K_LO)


def run_suggest_bench(reps=8, shapes=None, kernel="matern52"):
    rng = np.random.default_rng(0)
    rows = []
    for name, cfg in SHAPES.items():
        if shapes and name not in shapes:
            continue
        step_kw = _step_kwargs(cfg, kernel=kernel)
        args = _make_args(cfg, rng)
        # The compiler plane's shared analysis closure (the exact code path
        # CompileRegistry.analyze_all runs for the runtime) — a bench IS a
        # declared cold path, so the AOT second compile is fine here.
        analysis = lowered_analysis_fn(_suggest_step, args, step_kw)() or {}
        flops = analysis.get("flops")
        flops = float("nan") if flops is None else flops
        hbm_bytes = analysis.get("hbm_bytes")

        one = jax.jit(partial(_suggest_step, **step_kw))
        wall_s = _time_fn(lambda *a: one(*a)[0], args, reps=reps)
        device_s = device_seconds(name, reps=reps, kernel=kernel)
        public_ms = _public_round_ms(name, cfg)

        achieved = flops / device_s if device_s > 0 else float("nan")
        row = {
            "shape": name,
            "n_obs": cfg["n_obs"],
            "q": cfg["q"],
            "n_candidates": cfg["n_candidates"],
            "fit_steps": cfg["fit_steps"],
            "device_ms": round(device_s * 1e3, 3),
            "wall_ms": round(wall_s * 1e3, 2),
            "tunnel_ms": round((wall_s - device_s) * 1e3, 2),
            "public_api_ms": round(public_ms, 2) if public_ms else None,
            "gflops_per_call": round(flops / 1e9, 3),
            "achieved_tflops": round(achieved / 1e12, 3),
            "mfu_vs_bf16_peak": round(achieved / V5E_PEAK_FLOPS, 5),
            # Per-plan HBM footprint + predicted HBM-bound q (ROADMAP item
            # 1's open tail) — from the same analysis pass as the FLOPs.
            "plan_hbm_bytes": hbm_bytes,
            "hbm_bound_q": predict_hbm_bound_q(
                {"q": cfg["q"]}, hbm_bytes, device_hbm_capacity()
            ),
            "backend": jax.devices()[0].platform,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    run_suggest_bench()
