"""Benchmark runner for the BASELINE.md configurations.

Measures, per (algorithm, function) pair: best objective, simple regret,
suggestions/sec, wall-clock.  The five BASELINE configs map to presets:

1. random / Branin 2D, 200 trials
2. (anchor — sequential CPU GP-EI, implemented in bench.py)
3. tpu_bo q=256 Thompson / Rosenbrock-20D
4. mixed Real/Integer/Categorical space (LeNet-style hparams, synthetic
   objective standing in for MNIST training — see examples/)
5. ASHA-style multi-fidelity / Ackley-50D, q=4096

Run as ``python -m orion_tpu.benchmarks.runner [preset ...]``.
"""

import json
import time

from orion_tpu.benchmarks.functions import BENCHMARKS
from orion_tpu.client.experiment import optimize


def _uniform_priors(n_dims):
    return {f"x{i:02d}": "uniform(0, 1)" for i in range(n_dims)}


def _ackley50_priors():
    """BASELINE config #5 search space, shared by all four ackley50 presets
    so the variants can never drift onto different spaces."""
    return {**_uniform_priors(50), "budget": "fidelity(1, 256, 4)"}


def _mixed_lenet_objective(params):
    """Cheap deterministic stand-in for the LeNet hparam landscape of
    BASELINE config #4 (the real trainable example is examples/mnist_lenet.py;
    the runner preset measures the mixed-space suggest machinery itself).
    Optimum: lr=1e-2, batch_size=128, width=3, act='relu' -> 0."""
    import math

    act_penalty = {"relu": 0.0, "gelu": 0.1, "tanh": 0.3}[params["act"]]
    return (
        (math.log10(params["lr"]) + 2.0) ** 2
        + ((params["batch_size"] - 128) / 96.0) ** 2
        + (params["width"] - 3) ** 2 / 4.0
        + act_penalty
    )


PRESETS = {
    "random-branin": dict(
        priors=_uniform_priors(2), fn="branin", algorithm="random",
        max_trials=200, batch_size=50,
    ),
    "tpu_bo-hartmann6": dict(
        priors=_uniform_priors(6), fn="hartmann6",
        # local_frac 0.3: smooth MULTIMODAL landscapes reward global
        # exploration — 15-seed A/B vs the 0.5 default: median 0.123 ->
        # 0.015, deep-basin seeds 6/15 -> 12/15.  The default stays 0.5
        # because categorical-heavy spaces invert the trade (mixed-lenet's
        # tail blows up below it: max 1.2e-3 -> 0.25); docs/algorithms.md
        # documents the knob per landscape class.
        algorithm={"tpu_bo": {"n_init": 16, "n_candidates": 8192,
                               "fit_steps": 40, "local_frac": 0.3}},
        max_trials=192, batch_size=16,
    ),
    "mixed-lenet": dict(
        priors={
            "lr": "loguniform(1e-4, 1e-1)",
            "batch_size": "uniform(32, 256, discrete=True)",
            "width": "uniform(1, 4, discrete=True)",
            "act": "choices(['relu', 'tanh', 'gelu'])",
        },
        fn_params=_mixed_lenet_objective, optimum=0.0,
        algorithm={"tpu_bo": {"n_init": 16, "n_candidates": 4096, "fit_steps": 30}},
        max_trials=128, batch_size=16,
    ),
    "thompson-rosenbrock20": dict(
        priors=_uniform_priors(20), fn="rosenbrock20",
        algorithm={"tpu_bo": {"n_init": 256, "n_candidates": 16384, "fit_steps": 30}},
        max_trials=1024, batch_size=256,
    ),
    # BASELINE config #5's literal shape: ONE q=4096 batch through the ASHA
    # machinery — a pure scheduling/throughput measurement (every point is
    # pre-model by construction).  The multi-round presets below are the
    # model-quality measurements at the same trial budget.
    "asha-ackley50-q4096": dict(
        priors=_ackley50_priors(),
        fn="ackley50", algorithm={"asha": {"num_brackets": 3}},
        strategy="NoParallelStrategy",
        max_trials=4096, batch_size=4096,
    ),
    # Multi-round schedule (q=512 under a 5-rung fidelity ladder, same
    # 4096-trial budget as round 2's single q=4096 shot) so the model-based
    # variants below actually get observation rounds to learn from — a
    # single-batch run measures scheduling only, and a shallow ladder lets
    # ASHA's is-done (first top-rung completion, reference parity
    # `asha.py:312-314`) fire before the models can act on what they saw.
    "asha-ackley50": dict(
        priors=_ackley50_priors(),
        fn="ackley50", algorithm={"asha": {"num_brackets": 3}},
        strategy="NoParallelStrategy",
        max_trials=4096, batch_size=512,
    ),
    # Config #5 model-based (round-1 verdict #10): fidelity-aware GP sampling
    # under the same ASHA scheduling/budget — compare against asha-ackley50.
    "asha_bo-ackley50": dict(
        priors=_ackley50_priors(),
        fn="ackley50",
        algorithm={"asha_bo": {"n_init": 128, "n_candidates": 8192,
                               "fit_steps": 30, "refit_steps": 10,
                               "local_frac": 0.8, "trust_region": True,
                               "y_transform": "copula",
                               "tr_perturb_dims": 12, "num_brackets": 3}},
        strategy="NoParallelStrategy",
        max_trials=4096, batch_size=512,
    ),
    # Trust-region GP-BO (TuRBO-style + elite-covariance/directional
    # candidates + posterior-mean polish) on the same 20-D valley and trial
    # budget as thompson-rosenbrock20/cmaes-rosenbrock20.  Small batches on
    # purpose: rounds of real success/failure signal are what walk the box
    # down the valley — measured on the chip, batch 8 (128 rounds) more
    # than halves batch 16's median (258 -> 47.5 over 5 seeds), and
    # round 5's fresh-region restarts take the 15-seed median to 35.8
    # [23.0-344] p90 218, ahead of cmaes' 43.6; see BENCH_SEEDS.json.
    "turbo-rosenbrock20": dict(
        priors=_uniform_priors(20), fn="rosenbrock20",
        algorithm={"turbo": {"n_init": 64, "n_candidates": 8192,
                             "fit_steps": 25, "refit_steps": 6,
                             "tr_fail_tol": 2, "tr_perturb_dims": 4,
                             "tr_length_init": 0.4, "tr_length_max": 0.8}},
        max_trials=1024, batch_size=8,
    ),
    # Evolution-strategy family on a hard multimodal landscape where GP
    # lengthscales saturate — same budget as thompson-rosenbrock20.
    "cmaes-rosenbrock20": dict(
        priors=_uniform_priors(20), fn="rosenbrock20",
        # Canonical generational cadence (batch == popsize): generations are
        # the scarce axis for ES, and each update wants samples drawn from
        # the freshly-updated distribution.  5 chip seeds at 1024 trials:
        # median regret 46 [42-408] vs 673 for the (round-4 robust-default)
        # GP preset and 258 for turbo — valley landscapes reward covariance
        # adaptation.
        algorithm={"cmaes": {"popsize": 16}},
        max_trials=1024, batch_size=16,
    ),
    # Differential evolution on the same valley/budget, for the honest
    # family comparison: DE's sweet spot is large-budget/low-D/noisy
    # problems, and at 1024 evals in 20-D it is NOT competitive — 15 chip
    # seeds: median 22,880 [14,840-43,378] vs turbo 35.8, cmaes 43.6
    # (BENCH_SEEDS.json r5-sweep5; best/1 chosen over rand/1 by a 5-seed
    # CPU A/B, ~2.9e4 vs ~5.3e4).  The published row is what routes
    # users to turbo/cmaes for this landscape class.
    "de-rosenbrock20": dict(
        priors=_uniform_priors(20), fn="rosenbrock20",
        algorithm={"de": {"popsize": 32, "mutation": "best1"}},
        max_trials=1024, batch_size=32,
    ),
    # TPE-under-Hyperband on the multi-fidelity config, comparable against
    # asha-ackley50 / asha_bo-ackley50 at equal trial budget.
    "bohb-ackley50": dict(
        priors=_ackley50_priors(),
        fn="ackley50",
        algorithm={"bohb": {"n_candidates": 8192, "min_points": 64}},
        strategy="NoParallelStrategy",
        max_trials=4096, batch_size=512,
    ),
}


def run_preset(name, seed=0, algo_overrides=None, **overrides):
    """``algo_overrides`` merge into the algorithm's OWN config dict (e.g.
    ``{"use_mesh": True}`` to shard an ackley50 preset's suggest step over
    the visible devices — BASELINE config #5's v5e-8 shape); ``overrides``
    replace top-level preset keys (max_trials, batch_size, ...)."""
    cfg = {**PRESETS[name], **overrides}
    if algo_overrides:
        import inspect

        from orion_tpu.algo.base import _import_builtins, algo_registry

        _import_builtins()
        algorithm = cfg["algorithm"]
        if not isinstance(algorithm, dict):
            algorithm = {algorithm: {}}
        merged = {}
        for algo, params in algorithm.items():
            accepted = inspect.signature(algo_registry.get(algo).__init__).parameters
            # A **kwargs constructor (turbo forwards everything to tpu_bo)
            # accepts any override.
            has_var_kw = any(
                p.kind is inspect.Parameter.VAR_KEYWORD for p in accepted.values()
            )
            extra = {
                k: v for k, v in algo_overrides.items()
                if has_var_kw or k in accepted
            }
            skipped = set(algo_overrides) - set(extra)
            if skipped:
                import sys

                # Loud, not fatal: `--use-mesh` over the full preset list must
                # not crash on the algorithms that have no mesh to use.  On
                # stderr — stdout is a machine-readable JSONL stream.
                print(
                    f"# {name}: {algo} does not accept {sorted(skipped)}; skipped",
                    file=sys.stderr,
                )
            merged[algo] = {**params, **extra}
        cfg["algorithm"] = merged
    if "fn_params" in cfg:
        # Host-side params-dict objective (mixed spaces with categoricals).
        fn, batch_eval = cfg.pop("fn_params"), None
        optimum = cfg.pop("optimum")
    else:
        spec = BENCHMARKS[cfg.pop("fn")]
        fn, batch_eval = None, spec["fn"]
        optimum = spec["optimum"]

    t0 = time.perf_counter()
    stats = optimize(
        fn=fn,
        priors=cfg["priors"],
        max_trials=cfg["max_trials"],
        batch_size=cfg["batch_size"],
        algorithm=cfg["algorithm"],
        strategy=cfg.get("strategy"),
        seed=seed,
        name=f"bench-{name}-{seed}",
        batch_eval=batch_eval,
    )
    wall = time.perf_counter() - t0
    best = stats["best_evaluation"]
    return {
        "preset": name,
        "best": best,
        "simple_regret": (best - optimum) if best is not None else None,
        "trials": stats["trials_completed"],
        "wall_s": round(wall, 2),
        "suggestions_per_sec": round(stats["trials_completed"] / wall, 2),
    }


def run_preset_seeds(name, n_seeds, algo_overrides=None, **overrides):
    """Run a preset over seeds 0..n_seeds-1 and aggregate.

    Single-seed numbers on these landscapes sit on >2x seed variance
    (BASELINE.md's own admissions) — any headline claim must be a
    median +/- range, so the aggregate carries per-seed regrets verbatim
    alongside median/min/max.
    """
    import statistics

    per_seed = [
        run_preset(name, seed=s, algo_overrides=algo_overrides, **overrides)
        for s in range(n_seeds)
    ]
    regrets = [r["simple_regret"] for r in per_seed if r["simple_regret"] is not None]
    rates = [r["suggestions_per_sec"] for r in per_seed]
    ordered = sorted(regrets)
    out = {
        "preset": name,
        "seeds": n_seeds,
        "regret_median": round(statistics.median(regrets), 6) if regrets else None,
        "regret_min": round(min(regrets), 6) if regrets else None,
        "regret_max": round(max(regrets), 6) if regrets else None,
        # Tail quantile, nearest-rank (ceil(0.9 n)-th order statistic):
        # heavy-tailed presets are the rule on valley landscapes, and a
        # min-max range is dominated by one seed.  At n=5 this IS the max —
        # small samples have no tail information to understate.
        "regret_p90": (
            round(ordered[-(-9 * len(ordered) // 10) - 1], 6) if ordered else None
        ),
        "regret_per_seed": [round(r, 6) for r in regrets],
        "suggestions_per_sec_median": round(statistics.median(rates), 2),
        "wall_s_total": round(sum(r["wall_s"] for r in per_seed), 2),
    }
    return out


def main(argv=None):
    import sys

    argv = list(argv if argv is not None else sys.argv[1:])
    import argparse

    parser = argparse.ArgumentParser(prog="orion_tpu.benchmarks.runner")
    parser.add_argument("--op", choices=["gram", "suggest"],
                        help="run an op micro-benchmark instead of presets")
    parser.add_argument("--kind", default="matern52",
                        choices=["matern52", "rbf"])
    parser.add_argument("--reps", type=int, default=8)
    parser.add_argument("--seeds", type=int, default=None, metavar="N",
                        help="run each preset over seeds 0..N-1 and print "
                             "the median [min-max] aggregate (N >= 1)")
    parser.add_argument("--use-mesh", action="store_true",
                        help="shard each preset's suggest step over the "
                             "visible devices (mesh-capable algorithms only)")
    parser.add_argument("presets", nargs="*", metavar="preset",
                        choices=[[]] + list(PRESETS),
                        help=f"presets to run (default: all). {list(PRESETS)}")
    args = parser.parse_args(argv)
    if args.op:
        # Explicit guard (parse_args accepts both): a user combining --op
        # with preset names must not believe the presets silently ran.
        if args.presets:
            parser.error("--op and preset names are mutually exclusive")
        if args.op == "suggest":
            from orion_tpu.benchmarks.suggest_bench import run_suggest_bench

            run_suggest_bench(reps=args.reps, kernel=args.kind)
            return
        from orion_tpu.benchmarks.gram_bench import run_gram_bench

        run_gram_bench(kind=args.kind, reps=args.reps)
        return
    if args.kind != "matern52" or args.reps != 8:
        # --kind/--reps configure the --op micro-bench only; dropping them
        # silently would let the user believe they shaped the preset runs.
        parser.error("--kind/--reps require --op")
    if args.seeds is not None and args.seeds < 1:
        parser.error("--seeds must be >= 1")
    algo_overrides = {"use_mesh": True} if args.use_mesh else None
    for name in args.presets or list(PRESETS):
        if args.seeds is not None:
            print(json.dumps(run_preset_seeds(
                name, args.seeds, algo_overrides=algo_overrides)))
        else:
            print(json.dumps(run_preset(name, algo_overrides=algo_overrides)))


if __name__ == "__main__":
    main()
