"""Analytic black-box functions on device (batched: (n, d) -> (n,)).

The BASELINE.md benchmark set: Branin 2D, Hartmann6, Rosenbrock-nD,
Ackley-nD.  All are written to take points in the **unit cube** and scale to
their canonical domains internally, matching how algorithms see the space.
"""

import jax.numpy as jnp
import numpy as np


def branin(u):
    """Branin-Hoo on [-5, 10] x [0, 15]; global min 0.397887."""
    x = -5.0 + u[:, 0] * 15.0
    y = u[:, 1] * 15.0
    a, b, c = 1.0, 5.1 / (4 * jnp.pi**2), 5.0 / jnp.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * jnp.pi)
    return a * (y - b * x**2 + c * x - r) ** 2 + s * (1 - t) * jnp.cos(x) + s


_H6_ALPHA = np.array([1.0, 1.2, 3.0, 3.2])
_H6_A = np.array(
    [
        [10, 3, 17, 3.5, 1.7, 8],
        [0.05, 10, 17, 0.1, 8, 14],
        [3, 3.5, 1.7, 10, 17, 8],
        [17, 8, 0.05, 10, 0.1, 14],
    ]
)
_H6_P = 1e-4 * np.array(
    [
        [1312, 1696, 5569, 124, 8283, 5886],
        [2329, 4135, 8307, 3736, 1004, 9991],
        [2348, 1451, 3522, 2883, 3047, 6650],
        [4047, 8828, 8732, 5743, 1091, 381],
    ]
)


def hartmann6(u):
    """Hartmann-6 on [0,1]^6; global min -3.32237."""
    diff = u[:, None, :] - jnp.asarray(_H6_P)[None, :, :]
    inner = jnp.sum(jnp.asarray(_H6_A)[None, :, :] * diff**2, axis=-1)
    return -jnp.sum(jnp.asarray(_H6_ALPHA)[None, :] * jnp.exp(-inner), axis=-1)


def rosenbrock(u, low=-5.0, high=10.0):
    """Rosenbrock-nD; global min 0 at x=1."""
    x = low + u * (high - low)
    return jnp.sum(
        100.0 * (x[:, 1:] - x[:, :-1] ** 2) ** 2 + (1.0 - x[:, :-1]) ** 2, axis=1
    )


def ackley(u, low=-32.768, high=32.768):
    """Ackley-nD; global min 0 at origin."""
    x = low + u * (high - low)
    d = x.shape[1]
    term1 = -20.0 * jnp.exp(-0.2 * jnp.sqrt(jnp.sum(x**2, axis=1) / d))
    term2 = -jnp.exp(jnp.sum(jnp.cos(2 * jnp.pi * x), axis=1) / d)
    return term1 + term2 + 20.0 + jnp.e


BENCHMARKS = {
    "branin": {"fn": branin, "dims": 2, "optimum": 0.397887},
    "hartmann6": {"fn": hartmann6, "dims": 6, "optimum": -3.32237},
    "rosenbrock20": {"fn": rosenbrock, "dims": 20, "optimum": 0.0},
    "ackley50": {"fn": ackley, "dims": 50, "optimum": 0.0},
}
