"""Multi-seed regret-trajectory regression gate.

The bench's single-seed regret anchor cannot pin optimizer-quality drift:
Hartmann6 under the default TuRBO config has a **bimodal** seed
distribution (lucky seeds descend into the global basin, regret ~0.01;
unlucky seeds converge in the second-best basin, regret ~0.13–0.20 — see
``BENCH_SEEDS.json`` and docs/performance.md), so any code change that
perturbs the trajectory bit-stream re-rolls which basin seed 0 lands in
and the headline "regret" jumps by 10x with no real regression.  That is
exactly what the r02–r05 drift was (0.0148 → 0.1408 → 0.1628: all draws
from one stable distribution).

This gate replaces anchor-parity with a statistical comparison of the
FULL regret distribution across seeds:

- run the bench regret scenario for N seeds, recording the whole
  incumbent-regret curve per seed;
- compare against the committed baseline (``BENCH_REGRET_BASELINE.json``)
  with a one-sided **Mann–Whitney U** test (rank-based: scale-free,
  robust to the bimodality) on BOTH the final regrets and the curve AUCs
  (mean log-regret over rounds — catches "gets there eventually but much
  slower" regressions the final value hides);
- corroborate with a **bootstrap** confidence interval on the median
  shift, and require a minimum relative effect — five seeds of pure noise
  must not fail CI.

The gate FAILS only when the regression is statistically significant
(p < alpha) AND the bootstrap CI excludes zero AND the median worsening
exceeds ``min_rel_effect`` — all three, because with 5-vs-5 seeds any
single criterion alone is too twitchy for a hard CI gate.  Pure python +
math (no scipy): the normal-approximation U test with tie correction is
deterministic and exact enough at these sample sizes.
"""

import json
import math
import random

#: Defaults shared by bench.py and the unit tests.
DEFAULT_ALPHA = 0.05
DEFAULT_MIN_REL_EFFECT = 0.25
EPS = 1e-12


def _rankdata(values):
    """Average ranks (1-based) with ties sharing their mean rank."""
    order = sorted(range(len(values)), key=lambda i: values[i])
    ranks = [0.0] * len(values)
    i = 0
    while i < len(order):
        j = i
        while j + 1 < len(order) and values[order[j + 1]] == values[order[i]]:
            j += 1
        mean_rank = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[order[k]] = mean_rank
        i = j + 1
    return ranks


def mann_whitney_u(current, baseline):
    """One-sided Mann–Whitney U: ``p`` small means ``current`` tends to be
    LARGER than ``baseline`` (for regrets: a regression).

    Normal approximation with tie correction and continuity correction —
    deterministic, dependency-free, and accurate to what a 5-vs-5 gate can
    resolve (the exact one-sided floor at n=m=5 is 1/252 ≈ 0.004).
    Returns ``(u_current, p_greater)``."""
    n, m = len(current), len(baseline)
    if not n or not m:
        return 0.0, 1.0
    pooled = list(current) + list(baseline)
    ranks = _rankdata(pooled)
    r_current = sum(ranks[:n])
    u = r_current - n * (n + 1) / 2.0  # U statistic of `current`
    mean_u = n * m / 2.0
    # Tie correction on the variance.
    counts = {}
    for value in pooled:
        counts[value] = counts.get(value, 0) + 1
    total = n + m
    tie_term = sum(c**3 - c for c in counts.values())
    var_u = (n * m / 12.0) * (total + 1 - tie_term / (total * (total - 1)))
    if var_u <= 0:
        return u, 1.0
    z = (u - mean_u - 0.5) / math.sqrt(var_u)  # continuity-corrected
    p_greater = 0.5 * math.erfc(z / math.sqrt(2.0))
    return u, p_greater


def _median(values):
    ordered = sorted(values)
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


def bootstrap_median_shift(current, baseline, n_boot=4000, seed=0):
    """95% bootstrap CI of ``median(current) - median(baseline)``.

    Seeded (deterministic in CI); returns ``(lo, hi)``."""
    rng = random.Random(seed)
    diffs = []
    for _ in range(n_boot):
        resampled_current = [rng.choice(current) for _ in current]
        resampled_baseline = [rng.choice(baseline) for _ in baseline]
        diffs.append(_median(resampled_current) - _median(resampled_baseline))
    diffs.sort()
    lo = diffs[int(0.025 * (len(diffs) - 1))]
    hi = diffs[int(0.975 * (len(diffs) - 1))]
    return lo, hi


def curve_auc(curve):
    """Mean log10-regret over the curve — the trajectory-wide summary (a
    run that reaches the same final regret twice as slowly scores worse).
    Floored at EPS so an exact-zero regret cannot blow up the log."""
    return sum(math.log10(max(float(v), EPS)) for v in curve) / max(len(curve), 1)


def _compare(current_stats, baseline_stats, alpha, min_rel_effect, seed):
    """One statistic's three-criterion verdict."""
    _u, p = mann_whitney_u(current_stats, baseline_stats)
    lo, hi = bootstrap_median_shift(current_stats, baseline_stats, seed=seed)
    base_med = _median(baseline_stats)
    curr_med = _median(current_stats)
    rel_effect = (curr_med - base_med) / max(abs(base_med), EPS)
    regressed = (
        p < alpha and lo > 0.0 and rel_effect > min_rel_effect
    )
    return {
        "p_value": round(p, 6),
        "baseline_median": base_med,
        "current_median": curr_med,
        "rel_effect": round(rel_effect, 6),
        "shift_ci95": [lo, hi],
        "regressed": bool(regressed),
    }


def evaluate_regret_gate(
    current_curves,
    baseline_curves,
    alpha=DEFAULT_ALPHA,
    min_rel_effect=DEFAULT_MIN_REL_EFFECT,
    seed=0,
):
    """Gate verdict dict for per-seed regret curves vs the committed
    baseline.  ``pass`` is False only when EITHER the final-regret or the
    curve-AUC comparison trips all three criteria (significance + CI +
    minimum effect) toward WORSE — improvements always pass."""
    current_final = [float(curve[-1]) for curve in current_curves]
    baseline_final = [float(curve[-1]) for curve in baseline_curves]
    final = _compare(current_final, baseline_final, alpha, min_rel_effect, seed)
    # AUC operates in log space already; its medians are log10 regrets, so
    # the relative-effect floor is applied to the LINEAR ratio implied by
    # the log shift (a +0.1 log10 shift = 26% slower descent).
    current_auc = [curve_auc(curve) for curve in current_curves]
    baseline_auc = [curve_auc(curve) for curve in baseline_curves]
    _u, p_auc = mann_whitney_u(current_auc, baseline_auc)
    lo, hi = bootstrap_median_shift(current_auc, baseline_auc, seed=seed + 1)
    auc_shift = _median(current_auc) - _median(baseline_auc)
    auc = {
        "p_value": round(p_auc, 6),
        "baseline_median": _median(baseline_auc),
        "current_median": _median(current_auc),
        "log10_shift": round(auc_shift, 6),
        "shift_ci95": [lo, hi],
        "regressed": bool(
            p_auc < alpha
            and lo > 0.0
            and 10.0**auc_shift - 1.0 > min_rel_effect
        ),
    }
    return {
        "pass": not (final["regressed"] or auc["regressed"]),
        "alpha": alpha,
        "min_rel_effect": min_rel_effect,
        "seeds": len(current_curves),
        "baseline_seeds": len(baseline_curves),
        "final": final,
        "auc": auc,
    }


def load_baseline(path):
    """Committed baseline file -> list of per-seed regret curves.

    Schema (``BENCH_REGRET_BASELINE.json``): ``{"seeds": [...],
    "curves": [[regret, ...], ...], "config": {...}, "justification":
    "..."}`` — curves indexed like seeds."""
    with open(path) as handle:
        data = json.load(handle)
    return [list(map(float, curve)) for curve in data["curves"]]
