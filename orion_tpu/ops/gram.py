"""Pallas fused gram kernel: pairwise kernel matrix in one VMEM pass.

The XLA path (`orion_tpu.algo.gp.kernels`) computes the candidate-scoring
cross-gram as a matmul producing an (m, n) squared-distance matrix followed
by the Matern/RBF elementwise epilogue.  At m ~ 8192 candidates that
intermediate is tens of MB: if XLA materializes it, the epilogue pays an HBM
round-trip at ~2x the matrix size in traffic.  This kernel tiles the output
over a (m/bm, n/bn) grid, runs the cross matmul per tile on the MXU, and
applies the epilogue while the tile is still in VMEM — one HBM write of the
final gram, nothing else.

Scope: forward-only scoring (acquisition / posterior over candidates).  The
MLL fit differentiates through its (n, n) kernel, and a pallas_call has no
autodiff rule — that path stays on XLA by design.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

_BM = 256  # output tile rows (candidates)
_BN = 256  # output tile cols (observations)
_LANE = 128  # TPU lane width: last dim of VMEM tiles


def _epilogue(kind, r2, amp):
    if kind == "rbf":
        return amp * jnp.exp(-0.5 * r2)
    if kind == "matern52":
        # No double-where guard needed here: this kernel is forward-only, and
        # sqrt(r2=0) itself is finite (the guard in the XLA path protects the
        # d(sqrt)/d(r2) gradient the MLL fit takes).
        r = jnp.sqrt(r2)
        sqrt5_r = jnp.sqrt(5.0) * r
        return amp * (1.0 + sqrt5_r + (5.0 / 3.0) * r2) * jnp.exp(-sqrt5_r)
    raise ValueError(f"unknown kernel {kind!r}")


def _gram_kernel(amp_ref, a_ref, b_ref, out_ref, *, kind):
    a = a_ref[:]  # (bm, d_pad) pre-scaled by 1/lengthscale
    b = b_ref[:]  # (bn, d_pad)
    # Full-precision cross term: the aa+bb-2ab cancellation amplifies the
    # default bf16 matmul error into an indefinite gram (see kernels.py).
    cross = jax.lax.dot_general(
        a,
        b,
        dimension_numbers=(((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    aa = jnp.sum(a * a, axis=1, keepdims=True)  # (bm, 1)
    bb = jnp.sum(b * b, axis=1, keepdims=True).T  # (1, bn)
    r2 = jnp.maximum(aa + bb - 2.0 * cross, 0.0)
    out_ref[:] = _epilogue(kind, r2, amp_ref[0])


def _pad2(x, rows, cols):
    r, c = x.shape
    if r == rows and c == cols:
        return x
    return jnp.pad(x, ((0, rows - r), (0, cols - c)))


@functools.partial(jax.jit, static_argnames=("kind", "interpret"))
def fused_gram(xa, xb, inv_lengthscales, amplitude, *, kind="matern52", interpret=False):
    """Kernel matrix k(xa, xb) -> (m, n), fused matmul + epilogue.

    Matches `orion_tpu.algo.gp.kernels.kernel_matrix` numerically (forward
    values; this path defines no gradient).
    """
    from jax.experimental import pallas as pl

    m, d = xa.shape
    n = xb.shape[0]
    a = (xa * inv_lengthscales).astype(jnp.float32)
    b = (xb * inv_lengthscales).astype(jnp.float32)

    d_pad = max(_LANE, -(-d // _LANE) * _LANE)
    m_pad = -(-m // _BM) * _BM
    n_pad = -(-n // _BN) * _BN
    a = _pad2(a, m_pad, d_pad)  # zero columns add nothing to distances
    b = _pad2(b, n_pad, d_pad)
    amp = jnp.reshape(amplitude.astype(jnp.float32), (1,))

    out = pl.pallas_call(
        functools.partial(_gram_kernel, kind=kind),
        grid=(m_pad // _BM, n_pad // _BN),
        in_specs=[
            pl.BlockSpec((1,), lambda i, j: (0,)),
            pl.BlockSpec((_BM, d_pad), lambda i, j: (i, 0)),
            pl.BlockSpec((_BN, d_pad), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((_BM, _BN), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(amp, a, b)
    return out[:m, :n]


def _env_opt_in():
    """ORION_TPU_PALLAS as a tri-state: True / False / None (unset)."""
    forced = os.environ.get("ORION_TPU_PALLAS", "").strip()
    if not forced:  # set-but-empty means unset
        return None
    return forced.lower() not in ("0", "false", "no", "off")


@functools.lru_cache(maxsize=1)
def _probe():
    """Does the fused gram actually compile AND run on the default backend?
    (Mosaic support varies across TPU runtimes; CPU/GPU interpret mode is
    for tests, not production dispatch.)"""
    if jax.default_backend() not in ("tpu",):
        return False
    try:
        x = jnp.asarray(np.random.default_rng(0).uniform(size=(8, 4)), jnp.float32)
        out = fused_gram(x, x, jnp.ones((4,)), jnp.asarray(1.0), kind="matern52")
        return bool(np.isfinite(np.asarray(out)).all())
    except Exception:  # pragma: no cover - backend-specific lowering failures
        return False


@functools.lru_cache(maxsize=1)
def pallas_available():
    """True when the fused gram can run here; ORION_TPU_PALLAS=1/0
    overrides autodetection (tests force both branches on CPU)."""
    forced = _env_opt_in()
    if forced is not None:
        return forced
    return _probe()


@functools.lru_cache(maxsize=1)
def pallas_enabled():
    """Should the GP engine DISPATCH to the fused gram?  Auto-enabled when
    the compile/run probe passes: the dispatch-amortized micro-bench
    (`--op gram`, docs/performance.md) measures the fused kernel 1.1-1.4x
    over XLA on every production shape.  ORION_TPU_PALLAS=0 opts out;
    ORION_TPU_PALLAS=1 cannot force dispatch past a FAILING probe — the
    env var must never push Mosaic lowering errors into the suggest path."""
    if _env_opt_in() is False:
        return False
    return _probe()
