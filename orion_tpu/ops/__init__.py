"""TPU kernels (Pallas) for the framework's hot ops.

The compute path is JAX/XLA throughout; these kernels cover the spots where
explicit fusion beats what the compiler schedules — currently the
candidate-scoring cross-gram (`fused_gram`), which fuses the distance matmul
with the Matern/RBF epilogue so the (m, n) intermediate never round-trips
through HBM.
"""

from orion_tpu.ops.gram import fused_gram, pallas_available, pallas_enabled

__all__ = ["fused_gram", "pallas_available", "pallas_enabled"]
