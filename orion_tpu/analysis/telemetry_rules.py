"""Telemetry discipline rules (``TEL001``–``TEL004``).

PR 3's contract: the registry is near-zero-cost when disabled, and stays
cheap when enabled.  Three ways code quietly breaks it — computing a
registry key per loop iteration, timing a block with a manually-managed
span (leaks the record on an exception path), and building f-string names
or args dicts at a call site that runs even when telemetry is off (the
mutator early-returns, but its arguments were already allocated).

``TEL004`` extends the allocation discipline to the optimization-health
emitters (PR 7, ``orion_tpu.health``): ``FLIGHT.record(...)`` and the
storage channel's ``record_health(...)`` sit on the same hot paths as the
TELEMETRY mutators and must not build allocating arguments on the
disabled path either (a guard on ``FLIGHT.enabled`` or
``TELEMETRY.enabled`` whitelists, exactly as for TEL003)."""

import ast

from orion_tpu.analysis.engine import (
    Diagnostic,
    Rule,
    ancestors,
    arg_names,
    dotted_name,
    enclosing_function,
)

#: Mutators of the process-wide registry.
_MUTATORS = frozenset({"count", "observe", "set_gauge", "record_span"})

#: Argument AST nodes whose construction allocates per call.
_ALLOCATING_NODES = (
    ast.JoinedStr,
    ast.Dict,
    ast.List,
    ast.Set,
    ast.ListComp,
    ast.SetComp,
    ast.DictComp,
    ast.GeneratorExp,
)


def _telemetry_call(node):
    """The mutator name when ``node`` is a TELEMETRY registry call
    (``TELEMETRY.count(...)``, ``tel.TELEMETRY.observe(...)``), else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    parts = name.split(".")
    if len(parts) >= 2 and parts[-2] == "TELEMETRY" and parts[-1] in _MUTATORS:
        return parts[-1]
    return None


def _enabled_polarity(test, negated=False):
    """``"pos"`` when the test can only be TRUE with telemetry enabled
    (the body is the enabled-only path), ``"neg"`` when it can only be
    FALSE with telemetry enabled (the else is), None when the flag does
    not dominate the branch.  Domination matters: in ``done or
    TELEMETRY.enabled`` the body still runs disabled, so the read must
    not whitelist it — only a bare flag read, ``not``, and the
    implication-preserving sides of and/or propagate polarity; anything
    else (comparisons, calls) is opaque."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _enabled_polarity(test.operand, not negated)
    name = dotted_name(test)
    if (
        name
        and name.split(".")[-1] == "enabled"
        and ("TELEMETRY" in name or "FLIGHT" in name)
    ):
        # Both observability flags dominate: TELEMETRY.enabled for the
        # registry mutators, FLIGHT.enabled for the health/flight
        # emitters (TEL004) — the CLI flips them together, and either
        # guard proves the disabled path skips the allocation.
        return "neg" if negated else "pos"
    if isinstance(test, ast.BoolOp):
        results = [_enabled_polarity(v, negated) for v in test.values]
        conjunction = isinstance(test.op, ast.And) != negated  # De Morgan
        if conjunction:
            # a and b true => every conjunct true: one "pos" suffices;
            # false => some conjunct false: "neg" needs ALL of them.
            if any(r == "pos" for r in results):
                return "pos"
            if all(r == "neg" for r in results):
                return "neg"
        else:
            # a or b true => some disjunct true: "pos" needs ALL;
            # false => every disjunct false: one "neg" suffices.
            if all(r == "pos" for r in results):
                return "pos"
            if any(r == "neg" for r in results):
                return "neg"
    return None


def _in_body(if_node, child):
    """Whether ``child`` (the ancestor-chain node directly under
    ``if_node``) sits in the ``if`` body rather than the ``else``."""
    return any(child is stmt for stmt in if_node.body)


def _mints_sentinel(ifexp):
    """True when the IfExp is truthy exactly when telemetry is enabled:
    ``clock() if TELEMETRY.enabled else None`` (or the inverted
    ``None if not TELEMETRY.enabled else clock()``) — the branch the
    DISABLED path takes must be a falsy constant, or the minted name is
    truthy with telemetry off."""
    polarity = _enabled_polarity(ifexp.test)
    if polarity == "pos":
        disabled_side = ifexp.orelse
    elif polarity == "neg":
        disabled_side = ifexp.body
    else:
        return False
    return isinstance(disabled_side, ast.Constant) and not disabled_side.value


def _sentinel_polarity(test, sentinels):
    """``"pos"`` when the test is truthy only if the sentinel is set
    (bare ``t0`` / ``t0 is not None``), ``"neg"`` for the inverse
    (``t0 is None`` / ``not t0``), None when no sentinel dominates —
    the side of the branch matters: ``if t0 is None:`` puts the DISABLED
    path in the body."""
    if isinstance(test, ast.Name) and test.id in sentinels:
        return "pos"
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = _sentinel_polarity(test.operand, sentinels)
        if inner == "pos":
            return "neg"
        if inner == "neg":
            return "pos"
        return None
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if (
            isinstance(left, ast.Name)
            and left.id in sentinels
            and isinstance(right, ast.Constant)
            and right.value is None
        ):
            if isinstance(op, ast.IsNot):
                return "pos"
            if isinstance(op, ast.Is):
                return "neg"
    return None


def _in_enabled_context(node):
    """True when ``node`` only executes with telemetry enabled: an
    ancestor ``if`` with enabled polarity puts it on the enabled side
    (body of ``if TELEMETRY.enabled:`` / else of the negation)."""
    child = node
    for parent in ancestors(node):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(parent, ast.If):
            polarity = _enabled_polarity(parent.test)
            in_body = _in_body(parent, child)
            if polarity == "pos" and in_body:
                return True
            if polarity == "neg" and not in_body:
                return True
        child = parent
    return False


def _early_exit_dominates(call):
    """True when an earlier SIBLING statement on the call's path is an
    ``if`` that leaves with telemetry disabled (``if not TELEMETRY.enabled:
    return/raise/continue``).  Sibling position is what makes this real
    dominance: reaching the call means its whole ancestor-statement chain
    executed, which means every earlier statement in each of those blocks
    ran without exiting — whereas a guard nested in some UNRELATED branch
    (or in a loop the call is outside of) proves nothing."""
    child = call
    for parent in ancestors(call):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(parent, field, None)
            if not isinstance(stmts, list):
                continue
            position = next(
                (i for i, stmt in enumerate(stmts) if stmt is child), None
            )
            if position is None:
                continue
            for stmt in stmts[:position]:
                if (
                    isinstance(stmt, ast.If)
                    and _enabled_polarity(stmt.test) == "neg"
                    and any(
                        isinstance(s, (ast.Return, ast.Raise, ast.Continue))
                        for s in stmt.body
                    )
                ):
                    return True
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        child = parent
    return False


def _is_guarded(call):
    """True when the mutator call only runs with telemetry enabled:

    - an ancestor ``if`` branch reachable only with the flag set: the body
      of ``if TELEMETRY.enabled:`` / the ``else`` of ``if not
      TELEMETRY.enabled:``, or the body of a test on a variable assigned
      from a ``... if TELEMETRY.enabled else None`` sentinel (the
      ``t0 is not None`` idiom); or
    - an earlier sibling statement on the call's path that exits with the
      flag unset (the ``if not TELEMETRY.enabled: return`` prologue idiom;
      see :func:`_early_exit_dominates` for why siblinghood is required).
    """
    fn = enclosing_function(call)
    sentinels = set()
    if fn is not None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.IfExp):
                # t0 = time.perf_counter() if TELEMETRY.enabled else None —
                # the flag must dominate the conditional AND the disabled
                # side must be falsy for the target to track enabled-ness.
                if _mints_sentinel(node.value):
                    sentinels |= {
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    }
            elif isinstance(node, ast.Assign) and _in_enabled_context(node):
                # t0 = None; if TELEMETRY.enabled: ...; t0 = clock() —
                # harvest targets only from enabled-only contexts, or an
                # assignment on the DISABLED side would mint a sentinel
                # that is truthy with telemetry off.
                sentinels |= {
                    t.id for t in node.targets if isinstance(t, ast.Name)
                }
        # A candidate only tracks enabled-ness if NO other write can leave
        # it truthy with telemetry disabled: every assignment to it must
        # be the minting IfExp, sit in an enabled-only context, or be a
        # falsy-constant reset (`t0 = None`).  `done = False` + `if
        # enabled: done = True` followed by an unconditional `done = True`
        # elsewhere is NOT a sentinel.
        if sentinels:
            # Bindings that aren't assignments can make the name truthy
            # with telemetry off regardless of any guard: parameters (the
            # caller picks the value), loop targets, with/except aliases,
            # global/nonlocal (rebindable elsewhere).
            ordered, extra = arg_names(fn)
            sentinels -= set(ordered) | set(extra)
            for node in ast.walk(fn):
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
                    targets, value = (node.target,), node.value
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    targets = (node.target,)
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    targets = tuple(
                        item.optional_vars
                        for item in node.items
                        if item.optional_vars is not None
                    )
                elif isinstance(node, ast.ExceptHandler):
                    sentinels.discard(node.name)
                    continue
                elif isinstance(node, (ast.Global, ast.Nonlocal)):
                    sentinels -= set(node.names)
                    continue
                else:
                    continue
                names = set()
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            names.add(sub.id)
                if not names & sentinels:
                    continue
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(value, ast.IfExp)
                    and _mints_sentinel(value)
                ):
                    continue
                if (
                    not isinstance(node, ast.AugAssign)
                    and isinstance(value, ast.Constant)
                    and not value.value
                ):
                    continue
                if _in_enabled_context(node):
                    continue
                sentinels -= names
    child = call
    for parent in ancestors(call):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(parent, ast.If):
            polarity = _enabled_polarity(parent.test)
            in_body = _in_body(parent, child)
            if polarity == "pos" and in_body:
                return True
            if polarity == "neg" and not in_body:
                return True
            if polarity is None:
                # The SIDE of a sentinel test matters: `if t0 is None:`
                # puts the disabled path in the body, so only the truthy
                # side of the sentinel whitelists the call.
                spol = _sentinel_polarity(parent.test, sentinels)
                if spol == "pos" and in_body:
                    return True
                if spol == "neg" and not in_body:
                    return True
        child = parent
    return _early_exit_dominates(call)


class DynamicKeyInLoop(Rule):
    id = "TEL001"
    name = "dynamic-key-in-loop"
    description = (
        "No per-iteration registry keys: a TELEMETRY mutator called inside "
        "a for/while loop must use a constant metric name — an f-string/"
        "concatenated name allocates and re-hashes the key every iteration "
        "of a hot loop (hoist the name, or batch the samples)."
    )

    def check(self, module):
        for node in ast.walk(module.tree):
            mutator = _telemetry_call(node)
            if mutator is None or not node.args:
                continue
            name_arg = node.args[0]
            # Constants are free; plain names/attributes are the sanctioned
            # hoisted form — only per-call COMPUTATION (f-string, concat,
            # call) of the key inside the loop is the violation.
            if isinstance(name_arg, (ast.Constant, ast.Name, ast.Attribute)):
                continue
            in_loop = any(
                isinstance(parent, (ast.For, ast.While))
                for parent in ancestors(node)
            )
            if in_loop:
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"TELEMETRY.{mutator}() with a computed metric name "
                    "inside a loop; hoist the key out of the loop or batch "
                    "the samples into one call",
                )


class UnmanagedSpan(Rule):
    id = "TEL002"
    name = "unmanaged-span"
    description = (
        "Spans must be context-managed: 'with TELEMETRY.span(...):' — a "
        "manually entered span leaks its record on every exception path "
        "and skews the histogram.  (Explicit record_span(...) with a "
        "measured duration is the sanctioned non-with form.)"
    )

    def check(self, module):
        with_items = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_items.add(id(item.context_expr))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            parts = name.split(".")
            if len(parts) >= 2 and parts[-2] == "TELEMETRY" and parts[-1] == "span":
                if id(node) not in with_items:
                    yield Diagnostic(
                        module.path,
                        node.lineno,
                        node.col_offset,
                        self.id,
                        "TELEMETRY.span() used outside a with statement; "
                        "context-manage it (or use record_span with an "
                        "explicit duration)",
                    )


class AllocationOnDisabledPath(Rule):
    id = "TEL003"
    name = "allocation-on-disabled-path"
    description = (
        "No allocation-bearing telemetry calls on the disabled fast path: "
        "a mutator whose arguments build f-strings/dicts/lists pays that "
        "allocation even when the registry is disabled (the early-return "
        "is inside the callee) — guard the call site with "
        "TELEMETRY.enabled."
    )

    def check(self, module):
        for node in ast.walk(module.tree):
            mutator = _telemetry_call(node)
            if mutator is None:
                continue
            allocating = None
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, _ALLOCATING_NODES):
                        allocating = sub
                        break
                if allocating is not None:
                    break
            if allocating is None:
                continue
            if _is_guarded(node):
                continue
            kind = (
                "f-string"
                if isinstance(allocating, ast.JoinedStr)
                else type(allocating).__name__.lower()
            )
            yield Diagnostic(
                module.path,
                node.lineno,
                node.col_offset,
                self.id,
                f"TELEMETRY.{mutator}() builds a {kind} argument on an "
                "unguarded path — it allocates even with telemetry "
                "disabled; wrap the call in 'if TELEMETRY.enabled:'",
            )


def _health_call(node):
    """The emitter label when ``node`` is an optimization-health emission
    call — ``FLIGHT.record(...)`` (any qualification) or a storage
    ``record_health(...)`` — else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name:
        return None
    parts = name.split(".")
    if len(parts) >= 2 and parts[-2] == "FLIGHT" and parts[-1] == "record":
        return "FLIGHT.record"
    if parts[-1] == "record_health":
        return "record_health"
    return None


class HealthEmissionOnDisabledPath(Rule):
    id = "TEL004"
    name = "health-emission-on-disabled-path"
    description = (
        "No allocation-bearing health/flight-record emissions on the "
        "disabled fast path: FLIGHT.record(...) and storage "
        "record_health(...) calls whose arguments build f-strings/dicts/"
        "lists allocate even when the recorder is off — guard the call "
        "site with FLIGHT.enabled (or TELEMETRY.enabled), same discipline "
        "as TEL003."
    )

    def check(self, module):
        for node in ast.walk(module.tree):
            emitter = _health_call(node)
            if emitter is None:
                continue
            allocating = None
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, _ALLOCATING_NODES):
                        allocating = sub
                        break
                if allocating is not None:
                    break
            if allocating is None:
                continue
            if _is_guarded(node):
                continue
            kind = (
                "f-string"
                if isinstance(allocating, ast.JoinedStr)
                else type(allocating).__name__.lower()
            )
            yield Diagnostic(
                module.path,
                node.lineno,
                node.col_offset,
                self.id,
                f"{emitter}() builds a {kind} argument on an unguarded "
                "path — it allocates even with the flight recorder "
                "disabled; wrap the call in 'if FLIGHT.enabled:'",
            )


#: Attribute names whose CALL marks a function as a wire-send path: the
#: socket send itself, the request/response exchange helpers, and the
#: gateway reply writer (``self.wfile.write``).
_WIRE_SEND_ATTRS = ("sendall",)
_WIRE_EXCHANGE_PREFIX = "_exchange"

#: Referenced names/attributes that count as touching the TraceContext
#: machinery (injecting into a payload, adopting off the wire, or stamping
#: a span's trace identity explicitly).
_CTX_NAME_MARKERS = frozenset(
    {
        "TraceContext",
        "current_trace_context",
        "set_trace_context",
        "trace_scope",
        "to_wire",
        "from_wire",
        "adopt_begin",
        "adopt_finish",
        "ctx",
    }
)
_CTX_KEYWORD_MARKERS = frozenset({"ctx", "span_ctx", "parent_ctx", "links", "root"})


def _is_wire_send_call(node):
    if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
        return False
    attr = node.func.attr
    if attr in _WIRE_SEND_ATTRS or attr.startswith(_WIRE_EXCHANGE_PREFIX):
        return True
    # The gateway reply writer: ...wfile.write(...)
    if attr == "write":
        receiver = dotted_name(node.func.value)
        return bool(receiver) and receiver.split(".")[-1] == "wfile"
    return False


def _is_span_call(node):
    """TELEMETRY.span(...) / any ``*.record_span(...)`` — the private
    server-side registries (``self._span_tel.record_span``) count too."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if not name:
        return False
    parts = name.split(".")
    if parts[-1] == "record_span":
        return True
    return len(parts) >= 2 and parts[-2] == "TELEMETRY" and parts[-1] == "span"


class WireSpanWithoutTraceContext(Rule):
    id = "TEL005"
    name = "wire-span-without-trace-context"
    description = (
        "A wire-send path (.sendall / _exchange* / gateway wfile.write) "
        "that opens or records a span must inject or adopt the ambient "
        "TraceContext — otherwise the server side of the hop records "
        "orphan spans and `orion-tpu trace --distributed` cannot join the "
        "processes (inject: payload['ctx'] = ctx.to_wire(); adopt: "
        "TraceContext.from_wire(...) / parent_ctx=...)."
    )

    def check(self, module):
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_wire_send = False
            span_calls = []
            touches_ctx = False
            for node in ast.walk(fn):
                if _is_wire_send_call(node):
                    has_wire_send = True
                if _is_span_call(node):
                    span_calls.append(node)
                    if any(
                        kw.arg in _CTX_KEYWORD_MARKERS for kw in node.keywords
                    ):
                        touches_ctx = True
                name = dotted_name(node) if isinstance(node, ast.Attribute) else None
                if isinstance(node, ast.Name) and node.id in _CTX_NAME_MARKERS:
                    touches_ctx = True
                elif name and any(
                    part in _CTX_NAME_MARKERS for part in name.split(".")
                ):
                    touches_ctx = True
            if has_wire_send and span_calls and not touches_ctx:
                for call in span_calls:
                    yield Diagnostic(
                        module.path,
                        call.lineno,
                        call.col_offset,
                        self.id,
                        "span on a wire-send path without TraceContext "
                        "injection/adoption — the cross-process trace "
                        "cannot join; inject the ambient context into the "
                        "payload (ctx.to_wire()) or adopt the wire ctx "
                        "(TraceContext.from_wire / parent_ctx=...)",
                    )


#: Severity strings a doctor rule may declare (mirrors
#: ``orion_tpu.diagnosis.engine.SEVERITIES`` — kept literal here so the
#: lint engine never imports the diagnosis package it checks).
_DOCTOR_SEVERITIES = frozenset({"info", "warn", "critical"})


def _doctor_rule_class(node):
    """True when ``node`` is a ClassDef subclassing ``DoctorRule`` (any
    qualification — ``DoctorRule``, ``engine.DoctorRule``)."""
    if not isinstance(node, ast.ClassDef):
        return False
    for base in node.bases:
        name = dotted_name(base)
        if name and name.split(".")[-1] == "DoctorRule":
            return True
    return False


def _class_constant(node, attr):
    """The ast.Constant assigned to ``attr`` directly in the class body,
    or None (absent, or assigned a non-constant).  Both the plain and the
    annotated spelling count — ``severity: str = "critical"`` is as
    explicit a declaration as the bare assignment."""
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = (stmt.target,)
            value = stmt.value
        else:
            continue
        for target in targets:
            if isinstance(target, ast.Name) and target.id == attr:
                return value if isinstance(value, ast.Constant) else None
    return None


class DoctorRuleDiscipline(Rule):
    id = "TEL006"
    name = "doctor-rule-discipline"
    description = (
        "Every DoctorRule subclass must DECLARE its severity (info|warn|"
        "critical) and a non-empty runbook anchor as class constants — a "
        "finding the report cannot rank, or whose runbook link resolves "
        "nowhere, is noise — and its evaluate()/methods must not build "
        "per-call computed metric keys (f-strings, concatenation): the "
        "per-rule gauge name is minted once at class definition, the same "
        "discipline TEL001 enforces in loops."
    )

    def check(self, module):
        for node in ast.walk(module.tree):
            if not _doctor_rule_class(node):
                continue
            severity = _class_constant(node, "severity")
            if severity is None or severity.value not in _DOCTOR_SEVERITIES:
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"doctor rule {node.name} must declare "
                    "severity = 'info'|'warn'|'critical' as a class "
                    "constant (inherited or computed severities are not "
                    "declarations)",
                )
            runbook = _class_constant(node, "runbook")
            if runbook is None or not (
                isinstance(runbook.value, str) and runbook.value
            ):
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"doctor rule {node.name} must declare a non-empty "
                    "runbook anchor (runbook = 'dxNNN-rule-name', resolved "
                    "into docs/monitoring.md)",
                )
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for call in ast.walk(fn):
                    mutator = _telemetry_call(call)
                    if mutator is None or not call.args:
                        continue
                    key = call.args[0]
                    if isinstance(key, (ast.Constant, ast.Name, ast.Attribute)):
                        continue
                    yield Diagnostic(
                        module.path,
                        call.lineno,
                        call.col_offset,
                        self.id,
                        f"TELEMETRY.{mutator}() with a computed metric key "
                        f"inside doctor rule {node.name}.{fn.name}() — "
                        "mint the name once at class definition "
                        "(gauge_name) instead of per evaluation",
                    )


TELEMETRY_RULES = (
    DynamicKeyInLoop,
    UnmanagedSpan,
    AllocationOnDisabledPath,
    HealthEmissionOnDisabledPath,
    WireSpanWithoutTraceContext,
    DoctorRuleDiscipline,
)
