"""Lock-order & shared-state safety rules (``LCK001``–``LCK003``).

The process hosts a growing set of cross-thread objects — the telemetry
ring, the bucket prewarmer, the network driver's socket, the serve
gateway's tenant tables — each with its own lock.  Deadlock needs only two
of them acquired in opposite orders on two threads, and the hang reproduces
only under production concurrency.  So the checker builds the static lock
graph: every declared lock (``self._x = threading.Lock()`` in a class,
``_x = threading.Lock()`` at module level), every ``with <lock>:`` nesting
(one edge per outer→inner pair), plus **two levels** of call resolution —
a call made while holding lock A resolves through the callee AND the
callee's own direct callees, so ``with self._lock: self._flush()`` where
``_flush`` calls ``TELEMETRY.count`` still finds the
``NetworkDB._lock → Telemetry._lock`` edge.  A **context-managed callee**
(``with self._guard():`` — the serve gateway's dominant idiom) contributes
the locks it acquires as held for the with-body, exactly like the plain
call form.  A cycle in the graph is ``LCK001``.

``LCK002`` is the simpler data-race screen: within a class that owns a
lock, an attribute assigned both inside and outside ``with <lock>:``
scopes is flagged at its unlocked sites (lifecycle methods are exempt —
``__init__``/``__setstate__`` run before the object is shared).

``LCK003`` closes the static↔dynamic loop with the runtime sanitizer
(``orion_tpu.analysis.sanitizer``, ``orion-tpu tsan``): a lock-order edge
*observed at runtime* between two statically-known locks that the static
graph never derived is a resolver blind spot — usually a lock-owning
object reached through a parameter or callback the AST cannot follow.  The
rule is silent unless runtime edges are supplied (in-process via
``sanitizer.set_lint_runtime_edges`` or the ``ORION_TPU_TSAN_EDGES`` env
file), so plain lint runs are unaffected.
"""

import ast
import os

from orion_tpu.analysis.engine import Diagnostic, Rule, dotted_name

_LOCK_FACTORIES = frozenset(
    {
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "Lock",
        "RLock",
        "Condition",
    }
)

_EXEMPT_METHODS = frozenset(
    {"__init__", "__new__", "__getstate__", "__setstate__", "__del__"}
)

#: Non-stmt AST children whose ``body`` is a statement list executed in the
#: enclosing scope (so lock holds carry into it).
_STMT_LIST_CHILDREN = (ast.ExceptHandler,) + (
    (ast.match_case,) if hasattr(ast, "match_case") else ()
)


def _module_name(path):
    return os.path.splitext(os.path.basename(path))[0]


def _is_lock_factory(value):
    return (
        isinstance(value, ast.Call)
        and (dotted_name(value.func) or "") in _LOCK_FACTORIES
    )


class _FunctionScan:
    """With-nesting walk of one function body: direct acquisitions, nested
    lock edges, calls made while holding locks, and the full callee set
    (for the second resolution level).

    Edge/held entries are *tokens*: either a lock id string, or
    ``("call", name)`` for a context-managed callee — the with-item
    ``with self._guard():`` holds whatever ``_guard`` acquires, which only
    the project index can expand (``build_static_edges`` does)."""

    def __init__(self, resolve):
        self._resolve = resolve  # expr -> lock id or None
        self.acquired = set()  # lock ids directly acquired
        self.call_names = set()  # every dotted callee name in the body
        self.edges = []  # (outer token, inner token, lineno)
        self.calls_under_lock = []  # (held token frozenset, callee name, lineno)
        self.assignment_sites = []  # (attr, under_lock, node)

    def walk(self, fn, class_locks):
        self._class_locks = class_locks
        self._visit_block(fn.body, [])

    def _visit_block(self, stmts, held):
        for stmt in stmts:
            self._visit(stmt, held)

    def _visit(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = []
            for item in node.items:
                lock = self._resolve(item.context_expr)
                if lock is not None:
                    self.acquired.add(lock)
                    for outer in held + pushed:
                        self.edges.append((outer, lock, node.lineno))
                    pushed.append(lock)
                    continue
                # A non-lock with-item is a call made under the current
                # holds ('with lock: with obj.enter():' acquires whatever
                # the callee acquires) — scanned BEFORE its own token is
                # pushed, so the callee is not recorded under itself.
                self._scan_calls(item.context_expr, held + pushed)
                if isinstance(item.context_expr, ast.Call):
                    name = dotted_name(item.context_expr.func)
                    if name:
                        # Context-managed callee: its acquired locks are
                        # HELD for the body (the gateway idiom LCK001 must
                        # see through) — expanded at finalize time.
                        token = ("call", name)
                        for outer in held + pushed:
                            self.edges.append((outer, token, node.lineno))
                        pushed.append(token)
            self._visit_block(node.body, held + pushed)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def's body runs later, not under the current holds.
            self._visit_block(node.body, [])
            return
        self._note_assignments(node, held)
        # Record calls in this statement's expression children — under the
        # current holds for edge formation, and unconditionally into
        # call_names for the second resolution level.  (Nested with-bodies
        # are re-visited below with the fuller held set — recording them
        # here too is redundant but still sound: the outer lock IS held
        # there.)
        for sub in ast.iter_child_nodes(node):
            if not isinstance(sub, (ast.stmt,) + _STMT_LIST_CHILDREN):
                self._scan_calls(sub, held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._visit(child, held)
            elif isinstance(child, _STMT_LIST_CHILDREN):
                # except handlers / match cases are not ast.stmt themselves,
                # but their bodies run under the same holds — error paths are
                # exactly where netdb mutates shared reconnect state.
                self._visit_block(child.body, held)

    def _scan_calls(self, node, held):
        # Recursive so deferred bodies PRUNE: a lambda/def created under a
        # lock runs later, not under it — ast.walk's flat iteration would
        # still descend and mint phantom lock-graph edges.
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name:
                self.call_names.add(name)
                if held:
                    self.calls_under_lock.append(
                        (frozenset(held), name, node.lineno)
                    )
        for child in ast.iter_child_nodes(node):
            self._scan_calls(child, held)

    def _note_assignments(self, node, held):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        under_class_lock = any(
            isinstance(lock, str) and lock in self._class_locks for lock in held
        )
        for target in targets:
            base = target
            if isinstance(base, ast.Subscript):
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                self.assignment_sites.append(
                    (base.attr, under_class_lock, node)
                )


class _ProjectIndex:
    """Cross-file lock inventory shared by the LCK rules and the runtime
    sanitizer's cross-check."""

    def __init__(self, modules):
        self.class_locks = {}  # class name -> set of lock ids "Class.attr"
        self.module_locks = {}  # module name -> {var name -> lock id}
        self.instance_of = {}  # module-level instance var -> class name
        self.fn_acquired = {}  # callee key -> set of lock ids
        self.fn_callees = {}  # callee key -> set of callee keys it calls
        self.fn_scans = []  # (module, class name or None, fn node, scan)
        self._collect_declarations(modules)
        self._scan_functions(modules)

    def _collect_declarations(self, modules):
        class_names = set()
        for module in modules:
            mod = _module_name(module.path)
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ClassDef):
                    class_names.add(node.name)
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Assign) and _is_lock_factory(
                            sub.value
                        ):
                            for target in sub.targets:
                                name = dotted_name(target)
                                if name and name.startswith("self."):
                                    self.class_locks.setdefault(
                                        node.name, set()
                                    ).add(f"{node.name}.{name[5:]}")
            for node in module.tree.body:
                if isinstance(node, ast.Assign) and _is_lock_factory(node.value):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            self.module_locks.setdefault(mod, {})[
                                target.id
                            ] = f"{mod}.{target.id}"
        for module in modules:
            for node in module.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id in class_names
                ):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            self.instance_of[target.id] = node.value.func.id

    def _resolver(self, module, class_name):
        mod = _module_name(module.path)

        def resolve(expr):
            name = dotted_name(expr)
            if not name:
                return None
            if name.startswith("self.") and class_name is not None:
                candidate = f"{class_name}.{name[5:]}"
                if candidate in self.class_locks.get(class_name, ()):
                    return candidate
                return None
            return self.module_locks.get(mod, {}).get(name)

        return resolve

    def _scan_functions(self, modules):
        for module in modules:
            mod = _module_name(module.path)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._scan_one(module, mod, node.name, item)
            for item in module.tree.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scan_one(module, mod, None, item)

    def _scan_one(self, module, mod, class_name, fn):
        scan = _FunctionScan(self._resolver(module, class_name))
        scan.walk(fn, self.class_locks.get(class_name, set()))
        self.fn_scans.append((module, class_name, fn, scan))
        if class_name is not None:
            key = ("method", class_name, fn.name)
        else:
            key = ("fn", mod, fn.name)
        self.fn_acquired.setdefault(key, set()).update(scan.acquired)
        callees = self.fn_callees.setdefault(key, set())
        for name in scan.call_names:
            callee = self.callee_key(module, class_name, name)
            if callee is not None and callee != key:
                callees.add(callee)

    def callee_key(self, module, class_name, call_name):
        """Map a dotted call like 'self._close' / '_note_done' /
        'TELEMETRY.count' to a key in fn_acquired, or None."""
        mod = _module_name(module.path)
        parts = call_name.split(".")
        if parts[0] == "self" and len(parts) == 2 and class_name is not None:
            return ("method", class_name, parts[1])
        if len(parts) == 1:
            return ("fn", mod, parts[0])
        owner = self.instance_of.get(parts[-2])
        if owner is not None:
            return ("method", owner, parts[-1])
        return None

    def acquired_through(self, key, depth=2):
        """Locks acquired by ``key`` resolved through ``depth`` call
        levels: its own direct acquisitions plus (at depth 2) those of its
        direct callees — 'a call under lock A to a method whose helper
        takes lock B' now contributes A→B."""
        acquired = set(self.fn_acquired.get(key, ()))
        if depth > 1:
            for callee in self.fn_callees.get(key, ()):
                acquired |= self.fn_acquired.get(callee, set())
        return acquired


def _project_index(modules):
    """Build the whole-project scan once per run: the LCK rules receive
    the same modules list from one run_lint call, so the index is cached on
    the first Module and dies with the run — a process-global cache would
    pin every parsed AST for the life of the process (bench.py's lint
    preflight runs in the same process as the timed rounds)."""
    if not modules:
        return _ProjectIndex(modules)
    key = tuple(id(m) for m in modules)
    cached = getattr(modules[0], "lint_lck_index", None)
    if cached is None or cached[0] != key:
        cached = (key, _ProjectIndex(modules))
        modules[0].lint_lck_index = cached
    return cached[1]


def project_index(modules):
    """Public entry for the runtime sanitizer's cross-check
    (``sanitizer.cross_check_static``)."""
    return _project_index(modules)


def _expand_token(index, module, class_name, token):
    """A scan token -> the set of lock ids it stands for: a lock id is
    itself; a ``("call", name)`` context-managed callee expands to the
    locks the callee acquires through two resolution levels."""
    if isinstance(token, str):
        return {token}
    key = index.callee_key(module, class_name, token[1])
    if key is None:
        return set()
    return index.acquired_through(key)


def build_static_edges(index):
    """THE static lock-order graph: ``{outer: {inner: (path, line)}}``,
    from with-nesting, two-level call resolution, and context-managed
    callees.  Shared by LCK001, LCK003 and the sanitizer cross-check so
    "the static graph" means one thing everywhere."""
    edges = {}

    def add(outer, inner, module, line):
        if inner != outer:
            edges.setdefault(outer, {}).setdefault(inner, (module.path, line))

    for module, class_name, _fn, scan in index.fn_scans:
        for outer_token, inner_token, line in scan.edges:
            for outer in _expand_token(index, module, class_name, outer_token):
                for inner in _expand_token(index, module, class_name, inner_token):
                    add(outer, inner, module, line)
        for held, call_name, line in scan.calls_under_lock:
            key = index.callee_key(module, class_name, call_name)
            if key is None:
                continue
            inners = index.acquired_through(key)
            if not inners:
                continue
            for token in held:
                for outer in _expand_token(index, module, class_name, token):
                    for inner in inners:
                        add(outer, inner, module, line)
    return edges


def known_lock_ids(index):
    """Every declared lock id the index knows (class + module locks)."""
    known = set()
    for locks in index.class_locks.values():
        known |= locks
    for locks in index.module_locks.values():
        known |= set(locks.values())
    return known


def iter_edge_cycles(edges):
    """Cycles in a ``{outer: {inner: meta}}`` graph, yielded once each as
    ``(cycle_tuple, closing_node, closing_child)`` — the closing edge is
    where LCK001 anchors its diagnostic.  Iterative DFS with a recursion
    stack."""
    seen_cycles = set()
    visited = set()
    for start in sorted(edges):
        stack = [(start, iter(sorted(edges.get(start, {}))))]
        on_path = [start]
        while stack:
            node, children = stack[-1]
            advanced = False
            for child in children:
                if child in on_path:
                    cycle = tuple(on_path[on_path.index(child):] + [child])
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        yield cycle, node, child
                    continue
                if (node, child) not in visited:
                    visited.add((node, child))
                    stack.append(
                        (child, iter(sorted(edges.get(child, {}))))
                    )
                    on_path.append(child)
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                on_path.pop()


class LockOrderCycle(Rule):
    id = "LCK001"
    name = "lock-order-cycle"
    description = (
        "The static lock graph (with-nesting plus two levels of call "
        "resolution, including context-managed callees) must stay acyclic: "
        "a cycle means two threads can acquire the same locks in opposite "
        "orders and deadlock under production concurrency."
    )

    def begin(self, modules):
        self._index = _project_index(modules)

    def finalize(self):
        edges = build_static_edges(self._index)
        for cycle, node, child in iter_edge_cycles(edges):
            path, line = edges[node][child]
            yield Diagnostic(
                path,
                line,
                0,
                self.id,
                "lock-order cycle: "
                + " -> ".join(cycle)
                + " (another thread may acquire these in "
                "the opposite order and deadlock)",
            )


class UnlockedSharedMutation(Rule):
    id = "LCK002"
    name = "unlocked-shared-mutation"
    description = (
        "Within a class that owns a lock, an attribute assigned both "
        "inside and outside 'with <lock>:' scopes is a data race waiting "
        "for a second thread; take the lock at the unlocked site (or "
        "suppress with the reason the site is single-threaded)."
    )

    def begin(self, modules):
        self._index = _project_index(modules)

    def finalize(self):
        # attr sites grouped per class across the whole project (a class's
        # methods may span files only in pathological cases, but grouping
        # is per class name either way).
        sites = {}  # (class, attr) -> list of (under_lock, module, node, fn)
        for module, class_name, fn, scan in self._index.fn_scans:
            if class_name is None or class_name not in self._index.class_locks:
                continue
            if fn.name in _EXEMPT_METHODS:
                continue
            for attr, under_lock, node in scan.assignment_sites:
                sites.setdefault((class_name, attr), []).append(
                    (under_lock, module, node, fn)
                )
        for (class_name, attr), entries in sorted(sites.items()):
            locked = [e for e in entries if e[0]]
            unlocked = [e for e in entries if not e[0]]
            if not locked or not unlocked:
                continue
            for _under, module, node, fn in unlocked:
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"'self.{attr}' is assigned under "
                    f"{class_name}'s lock elsewhere but without it in "
                    f"'{fn.name}'; take the lock here or document why "
                    "this site is single-threaded",
                )


class UnmodeledRuntimeEdge(Rule):
    id = "LCK003"
    name = "runtime-edge-missing-from-static-graph"
    description = (
        "A lock-order edge the runtime sanitizer observed between two "
        "statically-declared locks must exist in the static lock graph — "
        "an unmodeled edge is a resolver blind spot (a lock-owning object "
        "reached through a parameter or callback) that silently exempts "
        "that acquisition path from LCK001 cycle checking.  Silent unless "
        "runtime edges are supplied (orion-tpu tsan's cross-check, "
        "sanitizer.set_lint_runtime_edges, or ORION_TPU_TSAN_EDGES)."
    )

    def begin(self, modules):
        self._index = _project_index(modules)
        # Runtime reports carry absolute paths; linted modules whatever the
        # caller passed.  Re-anchoring a finding to the LINTED path is what
        # lets a suppression comment at the acquisition site argue it away.
        self._by_realpath = {os.path.realpath(m.path): m.path for m in modules}
        from orion_tpu.analysis.sanitizer import lint_runtime_edges

        self._runtime = lint_runtime_edges()

    def finalize(self):
        if not self._runtime:
            return
        edges = build_static_edges(self._index)
        static_pairs = {
            (outer, inner) for outer in edges for inner in edges[outer]
        }
        known = known_lock_ids(self._index)
        for edge in self._runtime:
            outer = edge.get("outer")
            inner = edge.get("inner")
            if not outer or not inner or (outer, inner) in static_pairs:
                continue
            # Both endpoints must be locks the linted tree DECLARES —
            # otherwise the report came from code outside this lint run
            # (e.g. a fixture dir checked against a full-app report) and
            # there is no graph to extend here.
            if outer not in known or inner not in known:
                continue
            path = str(edge.get("path", "<runtime>"))
            path = self._by_realpath.get(os.path.realpath(path), path)
            yield Diagnostic(
                path,
                int(edge.get("line", 1) or 1),
                0,
                self.id,
                f"runtime-observed lock edge {outer} -> {inner} is missing "
                "from the static lock graph: the static resolver cannot "
                "see this acquisition path, so LCK001 cannot check it for "
                "cycles — restructure the acquisition so the resolver sees "
                "it, or suppress here with the reason the ordering is safe",
            )


LOCK_RULES = (LockOrderCycle, UnlockedSharedMutation, UnmodeledRuntimeEdge)
