"""Rule-engine core for ``orion-tpu lint``.

The framework's fast paths rest on conventions nothing in Python enforces:
fused suggest steps must stay retrace-free, every storage protocol op must
ride the unified retry policy with a declared applied-or-not mode, telemetry
must be allocation-free when disabled, and the cross-thread objects must keep
one lock discipline.  Each convention has already been violated and fixed by
hand at review time; this engine makes the contracts machine-checked so a new
op or jit function that breaks one fails tier-1 instead of a review.

Design:

- A :class:`Module` is one parsed file (source, AST with parent links,
  comment map, suppressions).  Parsing happens once; every rule shares it.
- A :class:`Rule` sees the whole project first (``begin``), then each module
  (``check``), then gets a project-wide ``finalize`` — so cross-file
  analyses (the static lock graph, the jit call-site registry) ride the
  same protocol as single-file checks.
- Suppressions are per-line comments ``# lint: disable=RULE1,RULE2 -- reason``.
  The trailing reason is MANDATORY (enforced here as ``LNT001``): a silenced
  rule must say why, or the suppression is itself a violation.  A standalone
  suppression comment applies to the next line as well, so multi-line
  statements can be annotated above.

Rule identifiers are grouped by family: ``JIT*`` (retrace hygiene, see
``jit_rules``), ``STO*`` (storage retry/trace coverage, ``storage_rules``),
``TEL*`` (telemetry discipline, ``telemetry_rules``), ``LCK*`` (lock order
and shared state, ``lock_rules``), and ``LNT*`` (the engine's own checks).
``docs/static_analysis.md`` is the rule catalog.
"""

import ast
import io
import json
import os
import re
import tokenize

#: ``# lint: disable=RULE1,RULE2 -- reason`` — the reason clause is
#: mandatory; LNT001 fires on a suppression without one.
_SUPPRESS_RE = re.compile(
    # Anchored to the START of the comment: prose that merely MENTIONS the
    # syntax mid-sentence must not mint a live suppression.
    r"^#+\s*lint:\s*disable=([A-Za-z0-9_*,\s]+?)(?:\s*--\s*(.*\S))?\s*$"
)

#: Engine-level rule ids (not pluggable rules — always on).
MALFORMED_SUPPRESSION = "LNT001"
SYNTAX_ERROR = "LNT002"
UNREADABLE_PATH = "LNT003"


class Diagnostic:
    """One finding: file/line/col position, rule id, human message."""

    __slots__ = ("path", "line", "col", "rule_id", "message")

    def __init__(self, path, line, col, rule_id, message):
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.rule_id = rule_id
        self.message = message

    def format(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"

    def to_dict(self):
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule_id,
            "message": self.message,
        }

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<Diagnostic {self.format()}>"


class Rule:
    """Base class for lint rules.

    Subclasses set ``id``/``name``/``description`` and implement ``check``.
    Cross-file rules collect global state in ``begin`` (called once with
    every parsed module, before any ``check``) and report project-wide
    findings from ``finalize``.  One rule instance lints one project run —
    instances are created fresh per :func:`run_lint` call, so state needs
    no reset discipline.
    """

    id = "LNT000"
    name = "abstract"
    description = ""

    def begin(self, modules):
        """Project-wide pre-pass; ``modules`` is every parsed Module."""

    def check(self, module):
        """Yield Diagnostics for one module."""
        return ()

    def finalize(self):
        """Yield project-wide Diagnostics after every module was checked."""
        return ()


class Module:
    """One parsed source file shared by every rule."""

    def __init__(self, path, source):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.engine_diagnostics = []
        # line -> (frozenset of rule ids, reason or None)
        self._suppressions = {}
        self._collect_comments()
        self._extend_suppressions_past_decorators()
        annotate_parents(self.tree)

    def _collect_comments(self):
        source_lines = self.source.splitlines()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                match = _SUPPRESS_RE.search(tok.string)
                if not match:
                    continue
                ids = frozenset(
                    part.strip() for part in match.group(1).split(",") if part.strip()
                )
                reason = match.group(2)
                if "*" in ids:
                    # One wildcard would mute every current AND future rule
                    # with a single reason — the opposite of an audited
                    # list of argued exceptions.
                    self.engine_diagnostics.append(
                        Diagnostic(
                            self.path,
                            line,
                            tok.start[1],
                            MALFORMED_SUPPRESSION,
                            "wildcard suppression '*' is not allowed: "
                            "name the specific rule id(s)",
                        )
                    )
                    continue
                if not reason:
                    self.engine_diagnostics.append(
                        Diagnostic(
                            self.path,
                            line,
                            tok.start[1],
                            MALFORMED_SUPPRESSION,
                            "suppression without a reason: write "
                            "'# lint: disable=RULE -- why it is safe here'",
                        )
                    )
                    continue
                self._add_suppression(line, ids, reason)
                # A standalone comment line annotates the next CODE line:
                # skip stacked comment lines and blanks so several reasoned
                # suppressions can sit above one statement (each merges via
                # _add_suppression), and multi-line statements can carry
                # the suppression above.
                before = source_lines[line - 1][: tok.start[1]]
                if not before.strip():
                    target = line + 1
                    while target <= len(source_lines):
                        text = source_lines[target - 1].strip()
                        if text and not text.startswith("#"):
                            break
                        target += 1
                    self._add_suppression(target, ids, reason)
        except tokenize.TokenError:  # pragma: no cover - parse already passed
            pass

    def _add_suppression(self, line, ids, reason):
        # Merge, never overwrite: a line can be covered both by its own
        # inline comment and by a standalone comment above, each naming
        # different rules — both suppressions must hold.
        existing = self._suppressions.get(line)
        if existing is not None:
            ids = existing[0] | ids
            if existing[1] != reason:
                reason = f"{existing[1]}; {reason}"
        self._suppressions[line] = (ids, reason)

    def _extend_suppressions_past_decorators(self):
        # A suppression landing on a decorator line (standalone comment
        # above the decorator, or inline on it) must also reach the
        # def/class line, where the rules anchor their diagnostics —
        # otherwise the documented above-the-statement form is silently
        # ineffective on decorated functions.
        for node in ast.walk(self.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            for deco in node.decorator_list:
                entry = self._suppressions.get(deco.lineno)
                if entry is not None:
                    self._add_suppression(node.lineno, *entry)

    def suppressed(self, line, rule_id):
        entry = self._suppressions.get(line)
        if entry is None:
            return False
        ids, _reason = entry
        return rule_id in ids


# --- shared AST helpers ------------------------------------------------------


def annotate_parents(tree):
    """Attach ``.lint_parent`` links so rules can walk upward."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.lint_parent = node
    return tree


def ancestors(node):
    """Yield parent chain from the immediate parent to the module root."""
    node = getattr(node, "lint_parent", None)
    while node is not None:
        yield node
        node = getattr(node, "lint_parent", None)


def enclosing_function(node):
    """The innermost FunctionDef/AsyncFunctionDef containing ``node``."""
    for parent in ancestors(node):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return parent
    return None


def enclosing_class(node):
    """The innermost ClassDef containing ``node``."""
    for parent in ancestors(node):
        if isinstance(parent, ast.ClassDef):
            return parent
    return None


def dotted_name(node):
    """Dotted source form of a Name/Attribute chain, or None.

    ``self._lock`` -> "self._lock", ``tel.TELEMETRY.count`` ->
    "tel.TELEMETRY.count".  Subscripts/calls in the chain yield None —
    rules match on static attribute paths only."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_functions(tree):
    """Every function/method in the module, including nested ones."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def arg_names(fn):
    """All parameter names of a function def, in positional order first."""
    args = fn.args
    ordered = [a.arg for a in args.posonlyargs + args.args]
    extra = [a.arg for a in args.kwonlyargs]
    if args.vararg:
        extra.append(args.vararg.arg)
    if args.kwarg:
        extra.append(args.kwarg.arg)
    return ordered, extra


# --- discovery / running -----------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "node_modules", ".ruff_cache"}


def iter_python_files(paths):
    """Expand files/directories into a list of .py files, sorted within
    each argument and deduplicated across them — overlapping arguments
    (``lint orion_tpu orion_tpu/storage/netdb.py``) must not lint a file
    twice and double its diagnostics."""
    out = []
    seen = set()

    def add(candidate):
        real = os.path.realpath(candidate)
        if real not in seen:
            seen.add(real)
            out.append(candidate)

    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs if d not in _SKIP_DIRS and not d.startswith(".")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        add(os.path.join(root, name))
        elif path.endswith(".py") and os.path.isfile(path):
            add(path)
    return out


def load_module(path):
    """Parse one file; an unparsable file becomes a Diagnostic, not a
    crash — ast.parse raises ValueError (not SyntaxError) on null bytes,
    and a non-UTF-8 file fails at read time."""
    try:
        with open(path, encoding="utf-8") as handle:
            source = handle.read()
        return Module(path, source), None
    except SyntaxError as exc:
        return None, Diagnostic(
            path,
            exc.lineno or 1,
            exc.offset or 0,
            SYNTAX_ERROR,
            f"syntax error: {exc.msg}",
        )
    except (ValueError, UnicodeDecodeError) as exc:
        return None, Diagnostic(path, 1, 0, SYNTAX_ERROR, f"unparsable file: {exc}")
    except OSError as exc:
        return None, Diagnostic(path, 1, 0, UNREADABLE_PATH, f"cannot read file: {exc}")


def default_rules():
    """Fresh instances of every registered rule family."""
    from orion_tpu.analysis.jit_rules import JIT_RULES
    from orion_tpu.analysis.lock_rules import LOCK_RULES
    from orion_tpu.analysis.perf_rules import PERF_RULES
    from orion_tpu.analysis.storage_rules import STORAGE_RULES
    from orion_tpu.analysis.telemetry_rules import TELEMETRY_RULES

    rules = []
    for family in (
        JIT_RULES, STORAGE_RULES, TELEMETRY_RULES, LOCK_RULES, PERF_RULES
    ):
        rules.extend(cls() for cls in family)
    return rules


def rule_catalog():
    """(id, name, description) for every registered rule — docs and --help."""
    return [(r.id, r.name, r.description) for r in default_rules()]


def _selected(rule_id, select, ignore):
    """Prefix filtering: --select JIT keeps the family, --ignore JIT002
    drops one rule.  Ignore wins over select."""
    if ignore and any(rule_id.startswith(pat) for pat in ignore):
        return False
    if select:
        return any(rule_id.startswith(pat) for pat in select)
    return True


def run_lint(paths, select=None, ignore=None, rules=None):
    """Lint ``paths`` (files or directories) and return sorted Diagnostics.

    ``select``/``ignore`` are iterables of rule-id prefixes.  Engine
    diagnostics (``LNT*``: malformed suppressions, syntax errors) are
    ALWAYS reported — filtering or suppressing the suppression checker
    would be a self-licensing loophole.  Suppressed findings are dropped;
    the suppression's reason is the audit trail."""
    select = [s for s in (select or []) if s]
    ignore = [s for s in (ignore or []) if s]
    modules = []
    diagnostics = []
    files = iter_python_files(paths)
    # run_lint is the whole API for direct callers (CI wrappers, hooks) —
    # a typo'd path must surface as an LNT003 finding, never as a silent
    # clean run.  Emptiness is derived from the one collected file list
    # rather than re-walking each directory argument.
    reals = {os.path.realpath(f) for f in files}
    for path in paths:
        if not os.path.exists(path):
            diagnostics.append(Diagnostic(path, 1, 0, UNREADABLE_PATH, "no such path"))
        elif os.path.isfile(path) and not path.endswith(".py"):
            diagnostics.append(
                Diagnostic(path, 1, 0, UNREADABLE_PATH, "not a Python file")
            )
        elif os.path.isdir(path):
            root = os.path.realpath(path)
            prefix = root + os.sep
            if not any(r == root or r.startswith(prefix) for r in reals):
                diagnostics.append(
                    Diagnostic(
                        path, 1, 0, UNREADABLE_PATH, "no Python files under directory"
                    )
                )
    for path in files:
        module, error = load_module(path)
        if error is not None:
            diagnostics.append(error)
            continue
        modules.append(module)
        diagnostics.extend(module.engine_diagnostics)
    if rules is None:
        rules = default_rules()
    # A typo'd prefix must be loud: `--select ST0` matching nothing would
    # otherwise lint zero storage rules and report the tree clean.
    known = [rule.id for rule in rules] + [
        MALFORMED_SUPPRESSION,
        SYNTAX_ERROR,
        UNREADABLE_PATH,
    ]
    for prefix in (*select, *ignore):
        if not any(rule_id.startswith(prefix) for rule_id in known):
            raise ValueError(
                f"select/ignore prefix {prefix!r} matches no rule id"
            )
    # Filter the rules themselves, not just their findings: a deselected
    # family must not pay its cross-file passes (lock graph, jit call-site
    # registry) only to have every diagnostic dropped afterwards.
    rules = [rule for rule in rules if _selected(rule.id, select, ignore)]
    for rule in rules:
        rule.begin(modules)
    module_by_path = {m.path: m for m in modules}
    for rule in rules:
        for module in modules:
            for diag in rule.check(module):
                if not module.suppressed(diag.line, diag.rule_id):
                    diagnostics.append(diag)
        for diag in rule.finalize():
            module = module_by_path.get(diag.path)
            if module is None or not module.suppressed(diag.line, diag.rule_id):
                diagnostics.append(diag)
    diagnostics = [
        d
        for d in diagnostics
        if d.rule_id.startswith("LNT") or _selected(d.rule_id, select, ignore)
    ]
    diagnostics.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return diagnostics


def format_human(diagnostics):
    lines = [d.format() for d in diagnostics]
    n = len(diagnostics)
    lines.append(f"{n} violation{'s' if n != 1 else ''} found" if n else "clean")
    return "\n".join(lines)


def format_json(diagnostics):
    return json.dumps(
        {
            "violations": [d.to_dict() for d in diagnostics],
            "count": len(diagnostics),
        }
    )
