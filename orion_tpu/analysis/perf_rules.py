"""Hot-path vectorization rules (``PERF001``, ``PERF002``, ``PERF003``).

ISSUE 13 burned the per-trial python work out of the steady-state producer
round: the cube<->params codec runs one numpy/lookup-table pass per
DIMENSION, trial documents build in one columnar pass, and per-trial dicts
materialize only at the plugin-compat boundary.  Nothing in python keeps it
that way — the natural way to write the next feature is a
``for trial in trials:`` loop, and one q=1024 loop re-opens the exact host
tax the refactor removed.  ``PERF001`` pins the discipline: inside the
declared hot-path functions, a ``for`` loop or comprehension that iterates
a batch-sized value (a q-round of trials/params/docs) is flagged.  The
argued exceptions — per-point plugin APIs (``register_suggestion``, lie
strategies), the storage-document edge where one doc per trial IS the
output shape, dict-list fallbacks for pre-columnar plugins — carry
suppressions-with-reason, which is exactly the audit trail a hot-path loop
should leave behind.

Detection is structural so fixtures (and future hot paths) participate by
shape, not by file path: methods named in ``HOT_METHODS`` on classes named
in ``HOT_CLASSES`` (base-class names count), plus the module-level
functions in ``HOT_FUNCTIONS``.  A loop is batch-sized when its iterable
resolves — through ``enumerate``/``zip``/``reversed``/slices — to one of
the function's parameters with a batch-shaped name (``BATCH_NAMES``), or
to a local assigned from one.

``PERF002`` pins the host-tail endgame's dispatch-prep discipline the same
way: inside the declared hot-path PREP functions (the per-round plan
builders between ``suggest`` and the device dispatch), rebuilding a
signature-invariant product — a statics/kwargs dict, a signature
string/tuple — from scratch every round is flagged unless the build rides
a cache: lexically guarded by a conditional on a value loaded from a
``*cache*``/``*token*`` attribute or global (the ``self._step_kw_cache``
/ ``_PLAN_PREP_CACHE``/``PlanPrepToken`` shapes in ``algo/tpu_bo.py`` are
the exemplars).  Per-round ARRAY tuples (the donated device operands) are
not rebuild products — they change every round by definition — so the
rule keys on the declared product names, not on every tuple literal.

``PERF003`` pins the compiler plane's cost discipline (ISSUE 18):
``cost_analysis()`` / ``memory_analysis()`` synchronize on the compiled
executable, and the AOT ``.lower(...).compile()`` chain is a SECOND full
XLA compile — both are fine in a bench or a deliberate registry sweep,
and ruinous anywhere a production round can reach.  The registry module
(``compiler_plane.py``) is the one declared cold path that owns these
calls; everything else must route through ``CompileRegistry.analyze_all``
/ ``lowered_analysis_fn`` or carry a suppression-with-reason naming why
its call site is cold.
"""

import ast

from orion_tpu.analysis.engine import Diagnostic, Rule, dotted_name

#: Classes whose listed methods are hot-path (matched by the class's own
#: name or any base-class name, so subclasses inherit the discipline).
HOT_CLASSES = {
    "Space": {
        "arrays_to_params",
        "params_to_arrays",
        "params_to_cube",
        "decode_flat_np",
        "encode_flat_np",
    },
    "TrialBatch": {"prepare", "to_docs", "trials"},
    "Producer": {
        "_produce",
        "_cube_rows_for",
        "_dispatch_speculative",
        "_take_speculative",
    },
    "DocumentStorage": {"register_trials", "register_trial_docs"},
    "ParamBatch": set(),  # columnar by construction; listed for subclasses
}

#: Module-level hot-path functions, by name.
HOT_FUNCTIONS = {"compute_batch_ids"}

#: Parameter/local names that denote a q-sized batch.  Deliberately tight:
#: the rule must stay surgical (a ``for dim in self`` per-dimension pass is
#: the DESIRED shape and must never be flagged).
BATCH_NAMES = frozenset(
    {
        "params_list",
        "params_rows",
        "params_batch",
        "trials",
        "docs",
        "pairs",
        "suggested",
        "outcomes",
        "registered_trials",
    }
)

#: Reference twins are exempt by suffix: they exist precisely to RETAIN the
#: per-trial loops as differential anchors.
_REFERENCE_SUFFIX = "_reference"

#: Call wrappers that preserve batch-sizedness of their first argument.
_TRANSPARENT_CALLS = frozenset({"enumerate", "zip", "reversed", "list", "tuple"})


class PerTrialLoopInHotPath(Rule):
    id = "PERF001"
    name = "per-trial-loop-in-hot-path"
    description = (
        "per-trial python loop (for/comprehension over a q-sized batch) "
        "inside a producer/codec hot-path function; vectorize per-dim or "
        "move the loop behind the plugin-compat boundary (suppress with a "
        "reason if the boundary is argued)"
    )

    # --- hot-path discovery -------------------------------------------------
    def _hot_functions(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                names = {node.name} | {
                    (dotted_name(base) or "").split(".")[-1]
                    for base in node.bases
                }
                methods = set()
                for name in names:
                    methods |= HOT_CLASSES.get(name, set())
                if not methods:
                    continue
                for item in node.body:
                    if (
                        isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name in methods
                        and not item.name.endswith(_REFERENCE_SUFFIX)
                    ):
                        yield node.name, item
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if (
                    node.name in HOT_FUNCTIONS
                    and not node.name.endswith(_REFERENCE_SUFFIX)
                ):
                    yield None, node

    # --- batch-sizedness ----------------------------------------------------
    def _batch_locals(self, fn):
        """Parameters + locals assigned from a batch-sized expression."""
        args = fn.args
        names = {
            a.arg
            for a in (
                list(args.posonlyargs)
                + list(args.args)
                + list(args.kwonlyargs)
            )
            if a.arg in BATCH_NAMES
        }
        # One propagation level: ``chunk = suggested[:k]`` keeps q-size.
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if self._is_batch_expr(node.value, names):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            names.add(target.id)
        return names

    def _is_batch_expr(self, node, names):
        if isinstance(node, ast.Name):
            return node.id in names or node.id in BATCH_NAMES
        if isinstance(node, ast.Subscript):
            # A slice keeps batch size; a scalar index does not.
            if isinstance(node.slice, ast.Slice):
                return self._is_batch_expr(node.value, names)
            return False
        if isinstance(node, ast.Attribute):
            # ``batch.params`` / ``self.params`` style: the terminal
            # attribute name carries the batch shape.
            return node.attr in BATCH_NAMES
        if isinstance(node, ast.Call):
            callee = (dotted_name(node.func) or "").split(".")[-1]
            if callee in _TRANSPARENT_CALLS and node.args:
                return self._is_batch_expr(node.args[0], names)
        return False

    # --- check --------------------------------------------------------------
    def check(self, module):
        seen = set()
        for owner, fn in self._hot_functions(module.tree):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            names = self._batch_locals(fn)
            where = f"{owner}.{fn.name}" if owner else fn.name
            for node in ast.walk(fn):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iter_node = node.iter
                    kind = "for loop"
                elif isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.DictComp,
                           ast.GeneratorExp)
                ):
                    iter_node = node.generators[0].iter
                    kind = "comprehension"
                else:
                    continue
                if self._is_batch_expr(iter_node, names):
                    yield Diagnostic(
                        module.path,
                        node.lineno,
                        node.col_offset,
                        self.id,
                        f"per-trial {kind} over a q-sized batch in hot-path "
                        f"'{where}'; vectorize per-dim (numpy ufunc / lookup "
                        "table / columnar pass) or suppress with the argued "
                        "plugin-compat reason",
                    )


#: Per-round prep functions whose signature-invariant products must ride a
#: cache: module-level names and method names (matched on ANY class — prep
#: methods are declared by name, like HOT_FUNCTIONS, so algorithm
#: subclasses inherit the discipline without registration).
HOT_PREP_FUNCTIONS = {"make_fused_plan"}
HOT_PREP_METHODS = {"fused_step_plan", "_gp_plan"}

#: Local names that denote a signature-invariant prep product.  Tight on
#: purpose: ``arrays``/``prep_key``/``fast_key`` are per-round by nature
#: (fresh device operands, the cache's own probe key) and must stay quiet.
PREP_PRODUCT_NAMES = frozenset({"statics", "signature", "step_kw", "kw"})

#: Identifier substrings that mark a value as cache-loaded: a conditional
#: on such a value is the cache guard the rebuild must sit under.
_CACHE_MARKERS = ("cache", "token", "memo")


class UncachedPrepRebuild(Rule):
    id = "PERF002"
    name = "uncached-prep-rebuild-in-hot-path"
    description = (
        "per-round rebuild of a signature-invariant prep product (statics/"
        "kwargs dict, signature string or tuple) inside a hot-path plan-prep "
        "function, outside any cache guard; pin it behind a *_cache "
        "attribute / prep token (suppress with a reason if the rebuild is "
        "argued)"
    )

    # --- hot-path discovery -------------------------------------------------
    def _hot_functions(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if (
                        isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name in HOT_PREP_METHODS
                        and not item.name.endswith(_REFERENCE_SUFFIX)
                    ):
                        yield node.name, item
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if (
                    node.name in HOT_PREP_FUNCTIONS
                    and not node.name.endswith(_REFERENCE_SUFFIX)
                ):
                    yield None, node

    # --- cache-loaded names -------------------------------------------------
    @staticmethod
    def _is_cacheish(identifier):
        lowered = identifier.lower()
        return any(marker in lowered for marker in _CACHE_MARKERS)

    def _cache_loaded_names(self, fn):
        """Locals assigned from an expression that touches a cache/token —
        ``prep = _PLAN_PREP_CACHE.get(key)``, ``kw = self._step_kw_cache``,
        ``pinned = prep_token.pinned``.  A conditional on one of these IS
        the cache guard."""
        names = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            touches_cache = any(
                (isinstance(sub, ast.Name) and self._is_cacheish(sub.id))
                or (isinstance(sub, ast.Attribute) and self._is_cacheish(sub.attr))
                for sub in ast.walk(node.value)
            )
            if touches_cache:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        names.add(target.id)
        return names

    # --- rebuild products ---------------------------------------------------
    @staticmethod
    def _is_rebuild_expr(node):
        """A from-scratch build of a prep product: a dict literal/``dict()``
        call, an f-string, or a tuple literal."""
        if isinstance(node, (ast.Dict, ast.DictComp, ast.JoinedStr, ast.Tuple)):
            return True
        if isinstance(node, ast.Call):
            return (dotted_name(node.func) or "").split(".")[-1] == "dict"
        return False

    # --- check --------------------------------------------------------------
    def check(self, module):
        seen = set()
        for owner, fn in self._hot_functions(module.tree):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            guards = self._cache_loaded_names(fn)
            where = f"{owner}.{fn.name}" if owner else fn.name
            yield from self._scan(fn.body, guarded=False, guards=guards,
                                  where=where, path=module.path)

    def _scan(self, stmts, guarded, guards, where, path):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs are their own (non-hot) scope
            if isinstance(stmt, ast.Assign) and not guarded:
                for target in stmt.targets:
                    if (
                        isinstance(target, ast.Name)
                        and target.id in PREP_PRODUCT_NAMES
                        and self._is_rebuild_expr(stmt.value)
                    ):
                        yield Diagnostic(
                            path,
                            stmt.lineno,
                            stmt.col_offset,
                            self.id,
                            f"'{target.id}' is rebuilt from scratch every "
                            f"round in hot-path prep '{where}' with no cache "
                            "guard; load it from a *_cache attribute / prep "
                            "token and rebuild only on miss (suppress with a "
                            "reason if the per-round rebuild is argued)",
                        )
            if isinstance(stmt, ast.If):
                test_guards = guarded or any(
                    isinstance(sub, ast.Name) and sub.id in guards
                    for sub in ast.walk(stmt.test)
                )
                yield from self._scan(stmt.body, test_guards, guards, where, path)
                yield from self._scan(stmt.orelse, test_guards, guards, where, path)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from self._scan(stmt.body, guarded, guards, where, path)
                yield from self._scan(stmt.orelse, guarded, guards, where, path)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan(stmt.body, guarded, guards, where, path)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._scan(block, guarded, guards, where, path)
                for handler in stmt.handlers:
                    yield from self._scan(handler.body, guarded, guards,
                                          where, path)


#: The one module that OWNS compiler introspection: the CompileRegistry's
#: own lazy ``analyze_all`` path.  By basename so worktrees/venv layouts
#: don't matter; everything else is a hot path until argued otherwise.
COLD_COMPILER_MODULES = frozenset({"compiler_plane.py"})

#: Compiled-executable methods that synchronize and allocate host-side.
_INTROSPECTION_ATTRS = frozenset({"cost_analysis", "memory_analysis"})


class CompilerIntrospectionOutsideColdPath(Rule):
    id = "PERF003"
    name = "compiler-introspection-outside-cold-path"
    description = (
        "cost_analysis()/memory_analysis() or a chained .lower(...)"
        ".compile() outside the declared compiler-plane cold path; these "
        "synchronize on the executable (and the AOT chain is a second full "
        "XLA compile) — route through CompileRegistry.analyze_all / "
        "lowered_analysis_fn, or suppress with the reason the call site "
        "is cold"
    )

    @staticmethod
    def _basename(path):
        return str(path).replace("\\", "/").rsplit("/", 1)[-1]

    @staticmethod
    def _is_aot_chain(node):
        """``<expr>.lower(...).compile(...)`` — a Call on an Attribute
        named ``compile`` whose value is itself a Call on an Attribute
        named ``lower``."""
        return (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "compile"
            and isinstance(node.func.value, ast.Call)
            and isinstance(node.func.value.func, ast.Attribute)
            and node.func.value.func.attr == "lower"
        )

    def check(self, module):
        if self._basename(module.path) in COLD_COMPILER_MODULES:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _INTROSPECTION_ATTRS
            ):
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"'{node.func.attr}()' synchronizes on the compiled "
                    "executable outside the compiler-plane cold path; read "
                    "it from CompileRegistry entries (analyze_all / "
                    "lowered_analysis_fn) or suppress with the reason this "
                    "call site is cold",
                )
            elif self._is_aot_chain(node):
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    "chained '.lower(...).compile()' is a second full XLA "
                    "compile outside the compiler-plane cold path; use "
                    "lowered_analysis_fn or suppress with the reason this "
                    "call site is cold",
                )


PERF_RULES = (
    PerTrialLoopInHotPath,
    UncachedPrepRebuild,
    CompilerIntrospectionOutsideColdPath,
)
