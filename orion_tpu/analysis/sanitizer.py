"""Runtime concurrency sanitizer (``orion-tpu tsan``).

PR 6's ``LCK*`` rules check the lock discipline *statically*: they resolve
a bounded number of call levels and cannot see dynamically-formed edges
(a lock-owning object passed as a parameter, a callback registered at
runtime) or unsynchronized data access at all.  This module is the dynamic
half of that pairing — an opt-in instrumented run that *observes* what the
threads actually did:

- **Lock shims.**  ``TSAN.enable()`` (or env ``ORION_TPU_TSAN``) patches
  ``threading.Lock``/``RLock``/``Condition``/``Event`` so locks created by
  project code (never the stdlib's or third-party packages' — the factory
  checks the creating frame's file) are wrapped in recording shims.  Each
  shim derives the same static identity the lint lock graph uses
  (``Class._attr`` / ``module._var``) from its creation site, so runtime
  findings and static findings speak one naming scheme.  Per-thread
  held-lock sets build the **observed lock-order graph**; a cycle is a
  potential deadlock, reported with the acquisition stacks of both edges.

- **Happens-before race detection.**  Threads carry vector clocks; shims
  create release→acquire edges, patched ``Thread.start``/``join`` and the
  ``Event`` shim create fork/join/signal edges.  Hot shared state is
  *annotated* at its access sites (``TSAN.write("Telemetry._ring")`` /
  ``TSAN.read(...)`` — one attribute check when disabled, constant-string
  args, the TEL003 cost discipline): two accesses to a cell from different
  threads with no happens-before path, at least one a write, are a data
  race — detected from the clocks alone, whether or not the racy
  interleaving happened to corrupt anything on this run.

- **Seeded interleaving explorer.**  A deterministic RNG (PR 5's
  fault-schedule discipline) draws at every instrumented acquisition and
  forces a thread switch (a short sleep before the acquire) on a hit, so
  schedules that need an unlucky preemption reproduce under a pinned seed.
  Detection itself never depends on the perturbation — the clocks flag
  unordered accesses on ANY schedule — the explorer just widens the set of
  orders a short test actually exercises.

- **Static↔dynamic cross-check.**  :func:`cross_check_static` compares the
  observed lock graph against the lint pass's static graph: runtime edges
  the static resolver missed become ``LCK003`` findings (the feedback loop
  that grows the static graph), and static cycles whose every edge was
  observed at runtime are escalated from "theoretically possible" to
  "runtime-confirmed".

The DISABLED path is zero-overhead by the same contract the telemetry
registry keeps: ``threading.*`` stays unpatched, and every annotation call
early-returns on one attribute check with no locks and no allocations.

Entry points: ``orion-tpu tsan -- <cmd>`` (subprocess with the env knobs +
a JSON report, ``cli/tsan.py``), the ``tsan`` pytest marker
(``tests/conftest.py`` wraps marked tests in enable/disable and fails them
on violations), and ``bench.py --smoke``'s serve leg (hard-asserts
``tsan_violations: 0``).  Knobs: ``ORION_TPU_TSAN`` (enable),
``ORION_TPU_TSAN_SEED``, ``ORION_TPU_TSAN_SWITCH`` (switch rate),
``ORION_TPU_TSAN_REPORT`` (JSON dump path, written at process exit).
"""

import atexit
import itertools
import json
import linecache
import os
import random
import re
import sys
import threading
import time

_ENABLE_VALUES = ("1", "on", "true", "yes")

#: Real factories, captured at import so enable/disable can swap them.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_EVENT = threading.Event
_REAL_THREAD_START = threading.Thread.start
_REAL_THREAD_JOIN = threading.Thread.join

#: Files under these prefixes (the stdlib dir, plus every
#: site-/dist-packages on sys.path — in a venv those do NOT share the
#: stdlib prefix) create RAW locks even while the sanitizer is on:
#: instrumenting queue/socketserver internals or jax's own locks would
#: bury the project's discipline in noise and risk breaking third-party
#: lock-protocol assumptions.
_FOREIGN_PREFIXES = tuple(
    sorted(
        {os.path.dirname(threading.__file__)}
        | {
            entry
            for entry in sys.path
            if entry.rstrip("/\\").endswith(("site-packages", "dist-packages"))
        }
    )
)


def _is_foreign(path):
    return (
        path.startswith(_FOREIGN_PREFIXES)
        or "site-packages" in path
        or "dist-packages" in path
    )

_THIS_FILE = os.path.abspath(__file__)

#: ``self._lock = threading.Lock()`` / ``_completed_lock = Lock()`` — the
#: assignment-target sniff that maps a creation site to the static lock id
#: the lint graph uses.
_ASSIGN_RE = re.compile(r"^\s*(self\.[A-Za-z_]\w*|[A-Za-z_]\w*)\s*=")

DEFAULT_SWITCH_RATE = 0.05
DEFAULT_SWITCH_DELAY = 0.0005  # 500 µs: long enough to yield, short enough to soak

#: Frames kept per captured acquisition/access site.
_SITE_DEPTH = 8


def _capture_site(skip=2):
    """(frames, anchor) of the current call site.

    ``frames`` is a short outermost-last list of ``file:line in fn``
    strings (sanitizer frames skipped); ``anchor`` is the ``(path, line)``
    of the innermost PROJECT frame — what an LCK003 diagnostic anchors to.
    """
    frame = sys._getframe(skip)
    frames = []
    anchor = None
    while frame is not None and len(frames) < _SITE_DEPTH:
        code = frame.f_code
        path = code.co_filename
        if os.path.abspath(path) != _THIS_FILE:
            frames.append(f"{path}:{frame.f_lineno} in {code.co_name}")
            if anchor is None and not _is_foreign(path):
                anchor = (path, frame.f_lineno)
        frame = frame.f_back
    return frames, anchor


def _derive_identity(frame):
    """Static lock id + creation site for a lock made at ``frame``.

    Mirrors the lint graph's naming: ``self._x = threading.Lock()`` inside
    a method names ``Type._x`` (the runtime type, so subclasses get their
    own node), a module-level ``_x = Lock()`` names ``module._x``.  A lock
    made some other way (local variable, comprehension) falls back to
    ``module.fn:line`` — still stable across runs of the same source.
    """
    code = frame.f_code
    path = code.co_filename
    line = frame.f_lineno
    site = f"{path}:{line}"
    mod = os.path.splitext(os.path.basename(path))[0]
    match = _ASSIGN_RE.match(linecache.getline(path, line))
    if match:
        target = match.group(1)
        if target.startswith("self."):
            owner = frame.f_locals.get("self")
            if owner is not None:
                return f"{_defining_class(owner, code)}.{target[5:]}", site
        elif code.co_name == "<module>":
            return f"{mod}.{target}", site
    return f"{mod}.{code.co_name}:{line}", site


def _defining_class(owner, code):
    """The class whose method ``code`` belongs to — the static lock graph
    names locks after the class that DECLARES them, so an instance of a
    subclass must not mint a differently-named node."""
    for cls in type(owner).__mro__:
        fn = cls.__dict__.get(code.co_name)
        if getattr(fn, "__code__", None) is code:
            return cls.__name__
    return type(owner).__name__


def _merge_clock(into, other):
    for tid, epoch in other.items():
        if into.get(tid, 0) < epoch:
            into[tid] = epoch


#: Unique per-Thread tokens for the vector clocks.  OS thread idents are
#: RECYCLED the moment a thread exits — keying clocks on them would alias
#: a fresh thread with a dead one and silently drop races between them.
_TID_COUNTER = itertools.count(1)


#: Per-instance cell tokens (id() would be recycled by the allocator).
_CELL_COUNTER = itertools.count(1)


def _tsan_tid():
    current = threading.current_thread()
    tid = current.__dict__.get("tsan_tid")
    if tid is None:
        tid = next(_TID_COUNTER)  # atomic under the GIL
        current.tsan_tid = tid
    return tid


class _TsanLock:
    """Recording shim around one real lock (Lock or RLock).

    Forwards the lock protocol; successful acquisitions/releases feed the
    sanitizer's held-set, lock-order graph, and vector clocks.  Unknown
    attributes forward to the inner lock so RLock internals keep working.
    """

    def __init__(self, inner, key, site):
        self._tsan_inner = inner
        self.tsan_key = key
        self.tsan_site = site
        self.tsan_clock = {}

    def acquire(self, blocking=True, timeout=-1):
        TSAN.pre_acquire()
        ok = self._tsan_inner.acquire(blocking, timeout)
        if ok:
            TSAN.note_acquire(self)
        return ok

    def release(self):
        TSAN.note_release(self)
        self._tsan_inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._tsan_inner.locked()

    # --- Condition protocol ---------------------------------------------
    # The real Condition captures these bound methods at construction; if
    # __getattr__ forwarded them to the raw inner lock, cond.wait() would
    # release/reacquire INVISIBLY and the notifier->waiter happens-before
    # edge would be lost (annotated state correctly guarded by a Condition
    # would read as racy).  Recursion bookkeeping is approximate across a
    # saved-state restore (we record depth 1); the CLOCK edges — the part
    # race detection rests on — are exact.

    def _release_save(self):
        TSAN.note_release_save(self)
        return self._tsan_inner._release_save()

    def _acquire_restore(self, state):
        self._tsan_inner._acquire_restore(state)
        TSAN.note_acquire(self)

    def _is_owned(self):
        return self._tsan_inner._is_owned()

    def __getattr__(self, name):
        return getattr(self._tsan_inner, name)


class _TsanEvent:
    """Recording shim around ``threading.Event``: ``set()`` publishes the
    setter's clock, a successful ``wait()`` joins it — the signal edge the
    gateway's reply handoff (``_WorkItem.done``) synchronizes on."""

    def __init__(self, inner):
        self._tsan_inner = inner
        self.tsan_clock = {}

    def set(self):
        TSAN.note_publish(self)
        self._tsan_inner.set()

    def wait(self, timeout=None):
        ok = self._tsan_inner.wait(timeout)
        if ok:
            TSAN.note_join_clock(self)
        return ok

    def is_set(self):
        return self._tsan_inner.is_set()

    def clear(self):
        return self._tsan_inner.clear()

    def __getattr__(self, name):
        return getattr(self._tsan_inner, name)


def _instrumentable(frame):
    """True when the factory call at ``frame`` came from project code (not
    the stdlib / site-packages, whose locks must stay raw)."""
    return not _is_foreign(frame.f_code.co_filename)


def _lock_factory(real):
    def make():
        inner = real()
        if not TSAN.enabled:
            return inner
        frame = sys._getframe(1)
        if not _instrumentable(frame):
            return inner
        key, site = _derive_identity(frame)
        return _TsanLock(inner, key, site)

    return make


def _condition_factory(lock=None):
    """Patched ``threading.Condition``: a project-created condition with no
    explicit lock gets an instrumented RLock, so state guarded by the
    condition's mutex still gets happens-before edges.  (The real Condition
    drives our shim through its acquire/release fallback protocol.)"""
    if lock is None and TSAN.enabled and _instrumentable(sys._getframe(1)):
        frame = sys._getframe(1)
        key, site = _derive_identity(frame)
        lock = _TsanLock(_REAL_RLOCK(), key, site)
    return _REAL_CONDITION(lock)


def _event_factory():
    inner = _REAL_EVENT()
    if TSAN.enabled and _instrumentable(sys._getframe(1)):
        return _TsanEvent(inner)
    return inner


def _thread_start(thread):
    TSAN.note_thread_start(thread)
    return _REAL_THREAD_START(thread)


def _thread_join(thread, timeout=None):
    result = _REAL_THREAD_JOIN(thread, timeout)
    TSAN.note_thread_join(thread)
    return result


class TsanReport:
    """One instrumented run's findings: observed lock-order graph (+
    cycles), data races over the annotated cells, explorer bookkeeping."""

    def __init__(self, edges, races, cells, switches, seed):
        self.edges = edges  # [{"outer","inner","path","line",stacks...}]
        self.races = races
        self.cells = cells
        self.switches = switches
        self.seed = seed
        self.cycles = _cycles_in_edges(edges)

    def violation_count(self):
        return len(self.races) + len(self.cycles)

    def to_dict(self):
        return {
            "type": "tsan-report",
            "seed": self.seed,
            "switches": self.switches,
            "cells": sorted(self.cells),
            "edges": list(self.edges),
            "lock_order_cycles": list(self.cycles),
            "races": list(self.races),
            "violations": self.violation_count(),
        }

    def format_human(self):
        lines = []
        for cycle in self.cycles:
            lines.append(
                "POTENTIAL DEADLOCK: lock-order cycle "
                + " -> ".join(cycle["cycle"])
            )
            for edge in cycle["edges"]:
                lines.append(f"  edge {edge['outer']} -> {edge['inner']}:")
                lines.append(f"    outer acquired at: {edge['outer_stack'][0]}")
                lines.append(f"    inner acquired at: {edge['inner_stack'][0]}")
        for race in self.races:
            lines.append(
                f"DATA RACE ({race['kind']}) on {race['cell']}: "
                f"thread {race['thread_a']} at {race['site_a']} vs "
                f"thread {race['thread_b']} at {race['site_b']}"
            )
        n = self.violation_count()
        lines.append(
            f"{n} violation{'s' if n != 1 else ''} "
            f"({len(self.races)} race(s), {len(self.cycles)} cycle(s)), "
            f"{len(self.edges)} observed edge(s), {self.switches} forced "
            "switch(es)"
        )
        return "\n".join(lines)


def _cycles_in_edges(edge_list):
    """Cycles in an observed edge list, each reported once with its edges'
    stacks.  Rides the SAME traversal as the static LCK001 pass
    (``lock_rules.iter_edge_cycles``) so the runtime and static halves can
    never disagree on what counts as a cycle.  Imported lazily: report
    building is a cold path, and the lock_rules/engine import must stay
    off the instrumentation hot path."""
    from orion_tpu.analysis.lock_rules import iter_edge_cycles

    meta = {}
    graph = {}
    for edge in edge_list:
        graph.setdefault(edge["outer"], {}).setdefault(edge["inner"], edge)
        meta[(edge["outer"], edge["inner"])] = edge
    cycles = []
    for cycle, _node, _child in iter_edge_cycles(graph):
        pairs = list(zip(cycle, cycle[1:]))
        cycles.append(
            {
                "cycle": list(cycle),
                "edges": [meta[p] for p in pairs if p in meta],
            }
        )
    return cycles


class Tsan:
    """The process-wide sanitizer.  All mutable analysis state lives behind
    ONE internal (never-instrumented) lock; the disabled path never touches
    it — every public recording entry early-returns on ``self.enabled``.
    """

    #: Singleton locks created at import time, re-wrapped on enable so the
    #: observability layer's own discipline is observable too.  Each entry
    #: is (module, attribute-holder attr chain, lock attr, static id).
    _SINGLETON_LOCKS = (
        ("orion_tpu.telemetry", "TELEMETRY", "_lock", "Telemetry._lock"),
        ("orion_tpu.health", "FLIGHT", "_lock", "FlightRecorder._lock"),
        ("orion_tpu.algo.prewarm", None, "_completed_lock", "prewarm._completed_lock"),
        ("orion_tpu.algo.prewarm", None, "_prewarmers_lock", "prewarm._prewarmers_lock"),
        ("orion_tpu.algo.history", None, "_registry_lock", "history._registry_lock"),
        # The memory sampler's rate-limit cell and the worker metrics-server
        # singleton guard (both annotated shared cells).
        ("orion_tpu.devmem", None, "_lock", "devmem._lock"),
        ("orion_tpu.metrics", None, "_worker_lock", "metrics._worker_lock"),
        # The doctor's last-published-summary slot (read by /healthz
        # handler threads, written by the watchdog/CLI watch loop).
        ("orion_tpu.diagnosis.watch", None, "_last_lock", "diagnosis._last_lock"),
    )

    def __init__(self):
        self.enabled = False
        self._lock = _REAL_LOCK()
        self._tls = threading.local()
        self._generation = 0
        self._seed = 0
        self._switch_rate = 0.0
        self._switch_delay = DEFAULT_SWITCH_DELAY
        self._rng = random.Random(0)
        self._swapped = []  # (owner_or_module, attr, wrapper) to unwrap
        self._reset_state()

    def _reset_state(self):
        self._clocks = {}  # tsan tid -> vector clock dict
        self._owner_tokens = {}  # id(owner) -> token, for ownerless-__dict__ objects
        self._owner_refs = []  # pins those owners so ids stay stable this run
        self._edges = {}  # (outer, inner) -> first-observation dict
        self._cells = {}  # name -> {"writes": {tid: (epoch, site, frames)}, "reads": ...}
        self._races = []
        self._race_keys = set()
        self._switches = 0

    # --- lifecycle -----------------------------------------------------------
    def enable(self, seed=0, switch_rate=None, switch_delay=None):
        """Patch the factories and start recording.  Not reentrant: two
        owners flipping the sanitizer independently would unpatch each
        other's shims mid-run."""
        if self.enabled:
            raise RuntimeError("sanitizer already enabled")
        with self._lock:
            self._reset_state()
            # New enable window: per-thread held/recursion state from a
            # previous window is stale (a lock held across disable() was
            # released invisibly) — _state() drops it lazily per thread.
            self._generation += 1
            self._seed = int(seed)
            self._rng = random.Random(self._seed)
            if switch_rate is None:
                switch_rate = DEFAULT_SWITCH_RATE
            self._switch_rate = float(switch_rate)
            if switch_delay is not None:
                self._switch_delay = float(switch_delay)
        # Wrap the import-time singletons BEFORE patching the factories:
        # their modules import here with the RAW factories, so the wrap is
        # explicit and recorded — and therefore restored on disable.
        self._wrap_singletons()
        threading.Lock = _lock_factory(_REAL_LOCK)
        threading.RLock = _lock_factory(_REAL_RLOCK)
        threading.Condition = _condition_factory
        threading.Event = _event_factory
        threading.Thread.start = _thread_start
        threading.Thread.join = _thread_join
        self.enabled = True

    def disable(self):
        """Unpatch and return this run's :class:`TsanReport`.  Shims created
        while enabled keep working (their hooks early-return), so objects
        outliving the run are safe — just no longer observed."""
        if not self.enabled:
            return self.snapshot_report()
        self._unwrap_singletons()
        self.enabled = False
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        threading.Condition = _REAL_CONDITION
        threading.Event = _REAL_EVENT
        threading.Thread.start = _REAL_THREAD_START
        threading.Thread.join = _REAL_THREAD_JOIN
        return self.snapshot_report()

    def enable_from_env(self):
        """The ``orion-tpu tsan -- <cmd>`` child-process entry: seed/rate
        from env, report dumped to ``ORION_TPU_TSAN_REPORT`` at exit."""
        seed = int(os.environ.get("ORION_TPU_TSAN_SEED", "0") or 0)
        try:
            rate = float(
                os.environ.get("ORION_TPU_TSAN_SWITCH", "")
                or DEFAULT_SWITCH_RATE
            )
        except ValueError:
            rate = DEFAULT_SWITCH_RATE
        self.enable(seed=seed, switch_rate=rate)
        path = os.environ.get("ORION_TPU_TSAN_REPORT")
        if path:
            atexit.register(self._dump_report, path)

    def _dump_report(self, path):
        try:
            with open(path, "w") as handle:
                json.dump(self.snapshot_report().to_dict(), handle)
        except OSError:  # pragma: no cover - report path unwritable
            pass

    def _wrap_singletons(self):
        import importlib

        for mod_name, holder_attr, lock_attr, key in self._SINGLETON_LOCKS:
            try:
                module = importlib.import_module(mod_name)
                owner = getattr(module, holder_attr) if holder_attr else module
                current = getattr(owner, lock_attr)
            except (ImportError, AttributeError):  # pragma: no cover
                continue
            if isinstance(current, _TsanLock):
                # Already a shim (created natively under a previous enabled
                # window): record it so disable still restores the raw lock.
                self._swapped.append((owner, lock_attr, current))
                continue
            wrapper = _TsanLock(current, key, f"<singleton {key}>")
            setattr(owner, lock_attr, wrapper)
            self._swapped.append((owner, lock_attr, wrapper))

    def _unwrap_singletons(self):
        for owner, attr, wrapper in self._swapped:
            if getattr(owner, attr, None) is wrapper:
                setattr(owner, attr, wrapper._tsan_inner)
        self._swapped = []

    # --- per-thread state ----------------------------------------------------
    def _state(self):
        tls = self._tls
        if getattr(tls, "generation", None) != self._generation:
            tls.held = []  # [(lock id(), key, frames), ...]
            tls.rec = {}  # id(lock) -> recursion depth
            tls.generation = self._generation
        return tls

    def _clock_locked(self, tid):
        clock = self._clocks.get(tid)
        if clock is None:
            inherited = getattr(threading.current_thread(), "tsan_clock0", None)
            clock = dict(inherited) if inherited else {}
            clock[tid] = clock.get(tid, 0) + 1
            self._clocks[tid] = clock
        return clock

    def _bump_locked(self, clock, tid):
        clock[tid] = clock.get(tid, 0) + 1

    # --- lock hooks ----------------------------------------------------------
    def pre_acquire(self):
        """The seeded interleaving explorer: called BEFORE the real acquire
        so a forced switch hands the lock race to another thread."""
        if not self.enabled or self._switch_rate <= 0.0:
            return
        with self._lock:
            hit = self._rng.random() < self._switch_rate
            if hit:
                self._switches += 1
        if hit:
            time.sleep(self._switch_delay)

    def note_acquire(self, lock):
        if not self.enabled:
            return
        state = self._state()
        lock_id = id(lock)
        depth = state.rec.get(lock_id, 0)
        if depth:  # reentrant re-acquire: no new edges, no clock movement
            state.rec[lock_id] = depth + 1
            return
        frames, _anchor = _capture_site(skip=3)
        tid = _tsan_tid()
        with self._lock:
            clock = self._clock_locked(tid)
            _merge_clock(clock, lock.tsan_clock)
            for _outer_id, outer_key, outer_frames in state.held:
                if outer_key == lock.tsan_key:
                    continue
                pair = (outer_key, lock.tsan_key)
                if pair not in self._edges:
                    anchor = _anchor_of(frames)
                    self._edges[pair] = {
                        "outer": outer_key,
                        "inner": lock.tsan_key,
                        "path": anchor[0],
                        "line": anchor[1],
                        "outer_stack": list(outer_frames),
                        "inner_stack": list(frames),
                        "thread": tid,
                    }
        state.rec[lock_id] = 1
        state.held.append((lock_id, lock.tsan_key, frames))

    def note_release(self, lock):
        if not self.enabled:
            return
        state = self._state()
        lock_id = id(lock)
        depth = state.rec.get(lock_id, 0)
        if depth > 1:
            state.rec[lock_id] = depth - 1
            return
        state.rec.pop(lock_id, None)
        for index in range(len(state.held) - 1, -1, -1):
            if state.held[index][0] == lock_id:
                del state.held[index]
                break
        tid = _tsan_tid()
        with self._lock:
            clock = self._clock_locked(tid)
            _merge_clock(lock.tsan_clock, clock)
            self._bump_locked(clock, tid)

    def note_release_save(self, lock):
        """A Condition's wait() releasing ALL recursion levels at once:
        clear the recursion count, drop the hold, publish the clock."""
        if not self.enabled:
            return
        state = self._state()
        state.rec.pop(id(lock), None)
        for index in range(len(state.held) - 1, -1, -1):
            if state.held[index][0] == id(lock):
                del state.held[index]
                break
        tid = _tsan_tid()
        with self._lock:
            clock = self._clock_locked(tid)
            _merge_clock(lock.tsan_clock, clock)
            self._bump_locked(clock, tid)

    # --- event / thread hooks ------------------------------------------------
    def note_publish(self, event):
        if not self.enabled:
            return
        tid = _tsan_tid()
        with self._lock:
            clock = self._clock_locked(tid)
            _merge_clock(event.tsan_clock, clock)
            self._bump_locked(clock, tid)

    def note_join_clock(self, event):
        if not self.enabled:
            return
        tid = _tsan_tid()
        with self._lock:
            _merge_clock(self._clock_locked(tid), event.tsan_clock)

    def note_thread_start(self, thread):
        if not self.enabled:
            return
        tid = _tsan_tid()
        with self._lock:
            clock = self._clock_locked(tid)
            thread.tsan_clock0 = dict(clock)
            self._bump_locked(clock, tid)

    def note_thread_join(self, thread):
        if not self.enabled or thread.is_alive():
            return
        child_tid = getattr(thread, "tsan_tid", None)
        if child_tid is None:
            return  # the child never touched instrumented state
        tid = _tsan_tid()
        with self._lock:
            child = self._clocks.get(child_tid)
            if child:
                _merge_clock(self._clock_locked(tid), child)

    # --- annotated shared cells ----------------------------------------------
    def write(self, cell, owner=None):
        """Record a write to annotated cell ``cell`` (a constant string).
        ``owner`` scopes the cell to one instance — two GatewayClients'
        sockets are different cells, not one.  One attribute check when
        disabled — no locks, no allocations."""
        if not self.enabled:
            return
        self._access(cell, "w", owner)

    def read(self, cell, owner=None):
        """Record a read of annotated cell ``cell``."""
        if not self.enabled:
            return
        self._access(cell, "r", owner)

    def _access(self, cell, kind, owner):
        frames, anchor = _capture_site(skip=3)
        site = frames[0] if frames else "?"
        tid = _tsan_tid()
        with self._lock:
            if owner is not None:
                cell = f"{cell}#{self._owner_token_locked(owner)}"
            clock = self._clock_locked(tid)
            entry = self._cells.setdefault(cell, {"w": {}, "r": {}})
            opposing = list(entry["w"].items())
            if kind == "w":
                opposing += list(entry["r"].items())
            for other_tid, (epoch, other_site, other_frames, other_kind) in opposing:
                if other_tid == tid:
                    continue
                if clock.get(other_tid, 0) >= epoch:
                    continue  # ordered before this access
                self._record_race_locked(
                    cell, kind, other_kind, tid, site, frames, other_tid,
                    other_site, other_frames,
                )
            entry[kind][tid] = (clock.get(tid, 1), site, frames, kind)

    def _owner_token_locked(self, owner):
        """Stable per-instance token.  Stored as an attribute where the
        owner allows it; slotted/builtin owners fall back to an id-keyed
        map whose keys are pinned alive for the run (a recycled id must
        not alias two owners within one report)."""
        attrs = getattr(owner, "__dict__", None)
        if attrs is not None:
            token = attrs.get("tsan_cell_token")
            if token is None:
                token = next(_CELL_COUNTER)
                try:
                    owner.tsan_cell_token = token
                    return token
                except AttributeError:
                    pass  # read-only __dict__ (class/mappingproxy)
            else:
                return token
        token = self._owner_tokens.get(id(owner))
        if token is None:
            token = next(_CELL_COUNTER)
            self._owner_tokens[id(owner)] = token
            self._owner_refs.append(owner)
        return token

    def _record_race_locked(self, cell, kind, other_kind, tid, site, frames,
                            other_tid, other_site, other_frames):
        label = "write/write" if kind == "w" and other_kind == "w" else "read/write"
        key = (cell, label, site, other_site)
        if key in self._race_keys or (cell, label, other_site, site) in self._race_keys:
            return
        self._race_keys.add(key)
        self._races.append(
            {
                "cell": cell,
                "kind": label,
                "thread_a": tid,
                "site_a": site,
                "stack_a": list(frames),
                "thread_b": other_tid,
                "site_b": other_site,
                "stack_b": list(other_frames),
            }
        )

    # --- reporting -----------------------------------------------------------
    def snapshot_report(self):
        with self._lock:
            edges = [dict(meta) for meta in self._edges.values()]
            races = [dict(race) for race in self._races]
            cells = list(self._cells)
            switches = self._switches
        return TsanReport(edges, races, cells, switches, self._seed)


def _anchor_of(frames):
    """(path, line) of the innermost project frame in a captured site."""
    for entry in frames:
        path, _, rest = entry.partition(":")
        if not _is_foreign(path):
            line = rest.split(" ", 1)[0]
            try:
                return path, int(line)
            except ValueError:  # pragma: no cover - malformed frame text
                continue
    return "<unknown>", 0


# --- static <-> dynamic cross-check ------------------------------------------

#: In-process override for the LCK003 rule's runtime-edge source (tests,
#: the tsan CLI); None = fall back to the ORION_TPU_TSAN_EDGES env file.
_LINT_RUNTIME_EDGES = None


def set_lint_runtime_edges(edges):
    """Feed observed runtime edges to the ``LCK003`` lint rule in-process
    (``None`` restores the env-file fallback)."""
    global _LINT_RUNTIME_EDGES
    _LINT_RUNTIME_EDGES = list(edges) if edges is not None else None


def lint_runtime_edges():
    """The runtime edges the LCK003 rule checks: the in-process override
    when set, else the JSON report/edge-list named by the
    ``ORION_TPU_TSAN_EDGES`` env var, else nothing (the rule stays silent
    on plain lint runs)."""
    if _LINT_RUNTIME_EDGES is not None:
        return list(_LINT_RUNTIME_EDGES)
    path = os.environ.get("ORION_TPU_TSAN_EDGES", "").strip()
    if not path or not os.path.exists(path):
        return []
    try:
        with open(path) as handle:
            data = json.load(handle)
    except (OSError, ValueError):
        return []
    if isinstance(data, dict):
        data = data.get("edges") or []
    return [e for e in data if isinstance(e, dict)]


def cross_check_static(edges, paths):
    """Compare observed runtime lock edges against the static LCK graph.

    Returns ``{"unmodeled_edges": [...], "confirmed_static_cycles": [...]}``:
    runtime edges between locks the static pass KNOWS but whose ordering it
    never derived (the LCK003 findings — a resolver blind spot, usually a
    lock-owning object reached through a parameter or callback), and static
    LCK001 cycles whose every edge was actually observed at runtime
    (escalated: the deadlock is one unlucky schedule away, not a
    theoretical artifact of over-approximation)."""
    from orion_tpu.analysis.engine import iter_python_files, load_module
    from orion_tpu.analysis.lock_rules import (
        build_static_edges,
        known_lock_ids,
        iter_edge_cycles,
        project_index,
    )

    modules = []
    for path in iter_python_files(paths):
        module, _error = load_module(path)
        if module is not None:
            modules.append(module)
    index = project_index(modules)
    static_edges = build_static_edges(index)
    static_pairs = {
        (outer, inner) for outer in static_edges for inner in static_edges[outer]
    }
    known = known_lock_ids(index)
    unmodeled = [
        dict(edge)
        for edge in edges
        if (edge["outer"], edge["inner"]) not in static_pairs
        and edge["outer"] in known
        and edge["inner"] in known
    ]
    runtime_pairs = {(edge["outer"], edge["inner"]) for edge in edges}
    confirmed = []
    for cycle, _node, _child in iter_edge_cycles(static_edges):
        pairs = list(zip(cycle, cycle[1:]))
        if pairs and all(pair in runtime_pairs for pair in pairs):
            confirmed.append(list(cycle))
    return {"unmodeled_edges": unmodeled, "confirmed_static_cycles": confirmed}


#: THE process-wide sanitizer, next to telemetry.TELEMETRY/health.FLIGHT.
#: Enabled via ORION_TPU_TSAN at orion_tpu import (see orion_tpu/__init__),
#: tsan.enable(), the pytest ``tsan`` marker, or bench's serve leg.
TSAN = Tsan()


def env_requested():
    """True when ORION_TPU_TSAN asks for instrumentation at import."""
    return os.environ.get("ORION_TPU_TSAN", "").strip().lower() in _ENABLE_VALUES
