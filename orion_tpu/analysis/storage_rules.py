"""Storage retry/trace coverage rules (``STO001``–``STO004``).

PR 5 unified failure semantics on one invariant: every storage protocol op
rides the shared :class:`~orion_tpu.storage.retry.RetryPolicy` through the
``_traced``/``_retrying`` decorators, with a declared applied-or-not mode —
and every ambiguous wire loss carries ``maybe_applied`` so non-converging
ops can refuse a blind re-send.  A new protocol op that skips the decorator
silently reverts to pre-policy crash-on-transient behavior; a new
``DatabaseError`` raised after bytes may have hit the wire without the
flag silently turns CAS retries unsafe.  These rules pin both.

``STO004`` extends the discipline to the sharded router
(``storage/shard.py``): a fan-out method that aggregates per-shard
``DatabaseError``\\ s must propagate the STRICTEST ``maybe_applied`` of
its parts — one shard's ambiguous loss makes the whole fan-out ambiguous,
and a summary error raised without the merged verdict silently launders a
maybe-applied mutation into a blindly-retriable one.
"""

import ast

from orion_tpu.analysis.engine import Diagnostic, Rule, dotted_name

#: A class participates in the storage protocol when it, or any base by
#: name, carries one of these names.
_STORAGE_BASES = ("BaseStorage", "DocumentStorage")

#: Decorators that apply the unified retry policy.
_RETRY_DECORATORS = ("_traced", "_retrying")

#: The explicit-mode keyword each decorator takes.
_MODE_KEYWORDS = {"_traced": "retry", "_retrying": "mode"}

#: Wire-send markers: a function containing one of these calls may have put
#: bytes on the wire before any later failure.
_SEND_ATTRS = frozenset({"sendall", "_exchange"})


def _is_storage_class(node):
    if node.name in _STORAGE_BASES:
        return True
    for base in node.bases:
        name = dotted_name(base) or ""
        if name.split(".")[-1] in _STORAGE_BASES:
            return True
    return False


def _touches_db(fn):
    """True when the method body reads ``self._db`` (the raw backend)."""
    for node in ast.walk(fn):
        if dotted_name(node) == "self._db" and isinstance(node, ast.Attribute):
            return True
        # _db_batch / _db_batch_capable route to the backend too.
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.startswith("self._db_batch"):
                return True
    return False


def _retry_decorator(fn):
    """The ``_traced``/``_retrying`` decorator Call on ``fn``, or None."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = (dotted_name(dec.func) or "").split(".")[-1]
            if name in _RETRY_DECORATORS:
                return name, dec
        else:
            name = (dotted_name(dec) or "").split(".")[-1]
            if name in _RETRY_DECORATORS:
                return name, None
    return None, None


def _has_property_decorator(fn):
    return any((dotted_name(d) or "") == "property" for d in fn.decorator_list)


class UncoveredStorageOp(Rule):
    id = "STO001"
    name = "uncovered-storage-op"
    description = (
        "Every public method of a BaseStorage/DocumentStorage subclass that "
        "touches self._db must be wrapped in _traced(...)/_retrying(...) so "
        "it rides the unified retry policy (and, for hot ops, the telemetry "
        "span/histogram channel)."
    )

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef) or not _is_storage_class(node):
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                # Private methods (and thereby every dunder lifecycle hook)
                # are out of scope: the rule covers the public protocol.
                if item.name.startswith("_"):
                    continue
                if _has_property_decorator(item):
                    continue
                if not _touches_db(item):
                    continue
                name, _call = _retry_decorator(item)
                if name is None:
                    yield Diagnostic(
                        module.path,
                        item.lineno,
                        item.col_offset,
                        self.id,
                        f"storage op '{node.name}.{item.name}' touches "
                        "self._db without @_traced/@_retrying — it would "
                        "crash on the first transient backend failure "
                        "instead of riding the unified retry policy",
                    )


class ImplicitRetryMode(Rule):
    id = "STO002"
    name = "implicit-retry-mode"
    description = (
        "_traced/_retrying decorators must declare their applied-or-not "
        "mode explicitly (retry=MODE_ALWAYS/MODE_UNAPPLIED/None for "
        "_traced, mode=... for _retrying): whether an op converges under "
        "re-application is a per-op correctness decision, not a default."
    )

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name, call = _retry_decorator(node)
            if name is None:
                continue
            keyword = _MODE_KEYWORDS[name]
            if call is None or not any(kw.arg == keyword for kw in call.keywords):
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"@{name} on '{node.name}' relies on the default retry "
                    f"mode; declare {keyword}=MODE_ALWAYS / MODE_UNAPPLIED "
                    "(or None to opt out) so the convergence contract is "
                    "visible at the op",
                )


class AmbiguousWireError(Rule):
    id = "STO003"
    name = "ambiguous-wire-error"
    description = (
        "In a function that sends on the wire (calls .sendall()/"
        "._exchange()), every DatabaseError raised must carry an explicit "
        "maybe_applied decision — raise a variable whose .maybe_applied "
        "was assigned, or suppress with the reason why nothing was sent."
    )

    def _sends(self, fn):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _SEND_ATTRS:
                    return True
        return False

    def check(self, module):
        for fn in [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            if not self._sends(fn):
                continue
            # Names whose .maybe_applied is assigned somewhere in this fn.
            marked = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        if (
                            isinstance(target, ast.Attribute)
                            and target.attr == "maybe_applied"
                            and isinstance(target.value, ast.Name)
                        ):
                            marked.add(target.value.id)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                if isinstance(exc, ast.Call):
                    name = (dotted_name(exc.func) or "").split(".")[-1]
                    if name == "DatabaseError":
                        yield Diagnostic(
                            module.path,
                            node.lineno,
                            node.col_offset,
                            self.id,
                            "DatabaseError raised inline in a wire-send "
                            "function without a maybe_applied decision; "
                            "assign it to a variable and set "
                            ".maybe_applied before raising",
                        )
                elif isinstance(exc, ast.Name) and exc.id not in marked:
                    # `raise exc` re-raising the caught error propagates its
                    # own maybe_applied — but only if the caught name wasn't
                    # rebound to a fresh DatabaseError without the flag.
                    if self._binds_database_error(fn, exc.id):
                        yield Diagnostic(
                            module.path,
                            node.lineno,
                            node.col_offset,
                            self.id,
                            f"DatabaseError variable {exc.id!r} raised in a "
                            "wire-send function without .maybe_applied ever "
                            "being set on it",
                        )

    def _binds_database_error(self, fn, name):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                callee = (dotted_name(node.value.func) or "").split(".")[-1]
                if callee == "DatabaseError" and any(
                    isinstance(t, ast.Name) and t.id == name for t in node.targets
                ):
                    return True
        return False


#: Blessed aggregation surfaces for STO004: the error constructor that
#: stamps the merged verdict itself, and the merge predicate a hand-built
#: error may assign ``maybe_applied`` from.
_FANOUT_ERROR_BUILDERS = frozenset({"shard_fanout_error"})
_MERGE_PREDICATES = frozenset({"merge_maybe_applied"})


class UnmergedFanoutError(Rule):
    id = "STO004"
    name = "unmerged-fanout-error"
    description = (
        "In a shard-routing class (name contains 'Sharded') or a fan-out "
        "helper (name contains 'fan_out'/'fanout'), every DatabaseError "
        "raised must carry the strictest maybe_applied of the per-shard "
        "parts: raise shard_fanout_error(...) (which merges internally), "
        "or assign .maybe_applied from merge_maybe_applied(...) before "
        "raising.  An unmerged summary error would let the retry policy "
        "blind-resend a mutation one shard may already have applied."
    )

    def _fanout_functions(self, tree):
        """(owner, fn) pairs in scope: methods of Sharded* classes plus any
        function whose own name marks it a fan-out helper."""
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and "Sharded" in node.name:
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        yield node.name, item
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                lowered = node.name.lower()
                if "fan_out" in lowered or "fanout" in lowered:
                    yield None, node

    def _merged_names(self, fn):
        """Names whose error carries a merged verdict: assigned from a
        blessed builder, or whose .maybe_applied is assigned from a merge
        predicate call."""
        merged = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            callee = (dotted_name(node.value.func) or "").split(".")[-1]
            for target in node.targets:
                if isinstance(target, ast.Name) and callee in _FANOUT_ERROR_BUILDERS:
                    merged.add(target.id)
                if (
                    isinstance(target, ast.Attribute)
                    and target.attr == "maybe_applied"
                    and isinstance(target.value, ast.Name)
                    and callee in _MERGE_PREDICATES
                ):
                    merged.add(target.value.id)
        return merged

    def check(self, module):
        seen = set()
        for owner, fn in self._fanout_functions(module.tree):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            merged = self._merged_names(fn)
            where = f"{owner}.{fn.name}" if owner else fn.name
            for node in ast.walk(fn):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                if isinstance(exc, ast.Call):
                    callee = (dotted_name(exc.func) or "").split(".")[-1]
                    if callee in _FANOUT_ERROR_BUILDERS:
                        continue
                    if callee == "DatabaseError":
                        yield Diagnostic(
                            module.path,
                            node.lineno,
                            node.col_offset,
                            self.id,
                            f"DatabaseError raised inline in fan-out scope "
                            f"'{where}' without the merged per-shard "
                            "maybe_applied; raise shard_fanout_error(...) "
                            "or assign .maybe_applied from "
                            "merge_maybe_applied(...) first",
                        )
                elif isinstance(exc, ast.Name) and exc.id not in merged:
                    if self._binds_database_error(fn, exc.id):
                        yield Diagnostic(
                            module.path,
                            node.lineno,
                            node.col_offset,
                            self.id,
                            f"DatabaseError variable {exc.id!r} raised in "
                            f"fan-out scope '{where}' without its "
                            "maybe_applied merged from the per-shard parts "
                            "(merge_maybe_applied / shard_fanout_error)",
                        )

    def _binds_database_error(self, fn, name):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                callee = (dotted_name(node.value.func) or "").split(".")[-1]
                if callee == "DatabaseError" and any(
                    isinstance(t, ast.Name) and t.id == name for t in node.targets
                ):
                    return True
        return False


#: STO005 markers: the placement-override collection (either spelling) and
#: the epoch-mutating wire op.
_PLACEMENT_NAMES = frozenset({"_placement", "PLACEMENT_COLLECTION"})
_PLACEMENT_MUTATORS = frozenset({"write", "read_and_write", "remove"})
_EPOCH_WIRE_OPS = frozenset({"promote"})


class UnguardedPlacementMutation(Rule):
    id = "STO005"
    name = "unguarded-placement-mutation"
    description = (
        "Placement/epoch state is the routing ground truth of the live "
        "control plane: every mutation of the `_placement` collection "
        "(write/read_and_write/remove) and every `promote` wire call must "
        "ride a RetryPolicy.run(..., mode=...) with an EXPLICIT "
        "applied-or-not mode in the same (outermost) function — a bare "
        "call that dies mid-wire leaves the migration state machine "
        "half-flipped with no declared convergence contract."
    )

    def _outermost_functions(self, tree):
        """Top-level functions and class methods, NOT nested defs: the
        policy.run(mode=...) covering a nested thunk lives in the
        enclosing function, which is the unit of review."""
        stack = [(tree, False)]
        while stack:
            node, inside_fn = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if not inside_fn:
                        yield child
                    stack.append((child, True))
                else:
                    stack.append((child, inside_fn))

    @staticmethod
    def _first_arg_marks_placement(node):
        if not node.args:
            return False
        first = node.args[0]
        if isinstance(first, ast.Constant) and first.value == "_placement":
            return True
        name = dotted_name(first) or ""
        return name.split(".")[-1] in _PLACEMENT_NAMES

    def _flagged_calls(self, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            attr = node.func.attr
            if attr in _PLACEMENT_MUTATORS and self._first_arg_marks_placement(
                node
            ):
                yield node, "placement mutation"
            elif attr == "_call" and node.args:
                op = node.args[0]
                if (
                    isinstance(op, ast.Constant)
                    and op.value in _EPOCH_WIRE_OPS
                ):
                    yield node, f"'{op.value}' wire op"

    @staticmethod
    def _has_explicit_mode_run(fn):
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "run"
                and any(kw.arg == "mode" for kw in node.keywords)
            ):
                return True
        return False

    def check(self, module):
        for fn in self._outermost_functions(module.tree):
            covered = None
            for node, what in self._flagged_calls(fn):
                if covered is None:
                    covered = self._has_explicit_mode_run(fn)
                if covered:
                    continue
                yield Diagnostic(
                    module.path,
                    node.lineno,
                    node.col_offset,
                    self.id,
                    f"{what} in '{fn.name}' without a RetryPolicy.run(..., "
                    "mode=...) in the same function — placement/epoch "
                    "mutations must declare their applied-or-not "
                    "convergence mode explicitly",
                )


STORAGE_RULES = (
    UncoveredStorageOp,
    ImplicitRetryMode,
    AmbiguousWireError,
    UnmergedFanoutError,
    UnguardedPlacementMutation,
)
