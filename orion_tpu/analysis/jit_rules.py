"""JIT/retrace hygiene rules (``JIT001``–``JIT004``).

The fused suggest step's zero-retrace contract (PR 4) dies quietly: one
``.item()`` inside a jitted function turns every round into a blocking
device sync, one Python ``if`` on a traced value becomes a
ConcretizationTypeError only on the first call with a fresh shape, and one
Python scalar threaded positionally into a non-static slot forks the jit
cache signature the prewarmer so carefully pins.  These rules make the
contract static: they find every function compiled by ``jax.jit`` (as a
decorator, through ``partial(jax.jit, ...)``, by wrapping — ``g =
jax.jit(f)`` — or by name in :data:`FUSED_STEP_REGISTRY`), compute which
parameters are traced (everything not named by ``static_argnums`` /
``static_argnames``), and check the bodies and the call sites.
"""

import ast

from orion_tpu.analysis.engine import (
    Diagnostic,
    Rule,
    arg_names,
    dotted_name,
    enclosing_class,
    enclosing_function,
)

#: Functions treated as jit-compiled even when the decorator is indirect
#: (registered fused steps whose compilation happens behind a helper).
#: Extend this set when a new fused step is added outside the
#: decorator/wrapper forms the detector recognizes.
FUSED_STEP_REGISTRY = frozenset({"_suggest_step"})

#: Host-side numpy module aliases — calling these on traced values forces a
#: transfer (or a tracer leak) inside the compiled function.
_NUMPY_ALIASES = frozenset({"np", "numpy"})

#: Attribute calls that synchronize with the device.
_HOST_SYNC_ATTRS = frozenset({"item", "block_until_ready", "tolist", "numpy"})

#: Builtins that force a concrete (host) value out of a tracer.
_CONCRETIZING_BUILTINS = frozenset({"float", "int", "bool"})

#: Array attributes that are CONCRETE under tracing — reading them neither
#: syncs nor retraces, so ``x.shape[0]`` branch/float is trace-safe.
_STATIC_METADATA_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "weak_type"})


def _static_info_from_call(call):
    """(static_argnums, static_argnames) extracted from a jax.jit /
    partial(jax.jit, ...) call's keywords; unknown/dynamic values are
    treated as empty (conservative: more params count as traced)."""
    nums, names = set(), set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= set(_const_ints(kw.value))
        elif kw.arg == "static_argnames":
            names |= set(_const_strs(kw.value))
    return nums, names


def _const_ints(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def _const_strs(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _is_jax_jit(node):
    """True for ``jax.jit`` / ``jit`` references."""
    name = dotted_name(node)
    return name in ("jax.jit", "jit")


def _jit_decoration(fn):
    """(is_jit, static_argnums, static_argnames) from a function's
    decorator list.  Recognizes ``@jax.jit``, ``@partial(jax.jit, ...)``
    and ``@functools.partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return True, set(), set()
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return (True,) + _static_info_from_call(dec)
            fname = dotted_name(dec.func)
            if fname in ("partial", "functools.partial") and dec.args:
                if _is_jax_jit(dec.args[0]):
                    return (True,) + _static_info_from_call(dec)
    return False, set(), set()


class JitFunction:
    """One function known to be jit-compiled, with its static params.

    ``call_names`` are the names a HOST call site reaches the compiled
    object by: the def's own name for decorated functions, the binding
    target for the wrapper form (`fast = jax.jit(slow)` is called as
    ``fast`` — a direct ``slow(...)`` call runs eagerly and never touches
    the jit cache)."""

    __slots__ = ("node", "path", "static_nums", "static_names", "call_names")

    def __init__(self, node, path, static_nums, static_names, call_names=None):
        self.node = node
        self.path = path
        self.static_nums = set(static_nums)
        self.static_names = set(static_names)
        self.call_names = set(call_names) if call_names is not None else {node.name}

    def positional_params(self):
        ordered, _extra = arg_names(self.node)
        return ordered

    def traced_params(self):
        """Parameter names the tracer sees as abstract values."""
        ordered, extra = arg_names(self.node)
        static = set(self.static_names)
        for index in self.static_nums:
            if 0 <= index < len(ordered):
                static.add(ordered[index])
        return {name for name in ordered + extra if name not in static}

    def is_static_position(self, index):
        ordered = self.positional_params()
        if index in self.static_nums:
            return True
        return 0 <= index < len(ordered) and ordered[index] in self.static_names

    def is_method(self):
        return enclosing_class(self.node) is not None


def collect_jit_functions(module):
    """Every jit-compiled function defined in ``module``.

    Three forms: decorated defs, wrapper assignments (``g = jax.jit(f,
    ...)`` marks ``f``), and :data:`FUSED_STEP_REGISTRY` names.  The result
    is cached on the Module (JIT001/002 call this per check and JIT003 per
    project) and dies with it — same per-run discipline as
    ``lock_rules._project_index``."""
    cached = getattr(module, "lint_jit_functions", None)
    if cached is None:
        cached = module.lint_jit_functions = _collect_jit_functions(module)
    return cached


def _collect_jit_functions(module):
    # Every def, NOT collapsed by name: a jitted def sharing its name with
    # a plain def elsewhere in the module (method vs module function, or
    # shadowing) must still have its body checked, so the result is keyed
    # by node identity.
    defs = [
        node
        for node in ast.walk(module.tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    out = {}
    for node in defs:
        is_jit, nums, names = _jit_decoration(node)
        if not is_jit and node.name in FUSED_STEP_REGISTRY:
            is_jit = True
        if is_jit:
            out[id(node)] = JitFunction(node, module.path, nums, names)
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not (isinstance(value, ast.Call) and _is_jax_jit(value.func)):
            continue
        if not value.args:
            continue
        # The wrapper form `g = jax.jit(f)` references f by bare name, so
        # it can only mean a module-level def; with several, Python's
        # shadowing makes the LAST one the live binding.  The method form
        # `self._g = jax.jit(self._impl)` resolves within the enclosing
        # class instead.
        target_node = value.args[0]
        if isinstance(target_node, ast.Name):
            candidates = [
                d
                for d in defs
                if d.name == target_node.id
                and getattr(d, "lint_parent", None) is module.tree
            ]
        elif (
            isinstance(target_node, ast.Attribute)
            and isinstance(target_node.value, ast.Name)
            and target_node.value.id == "self"
        ):
            cls = enclosing_class(node)
            candidates = [
                d
                for d in defs
                if d.name == target_node.attr
                and cls is not None
                and enclosing_class(d) is cls
            ]
        else:
            continue
        if not candidates:
            continue
        wrapped = candidates[-1]
        nums, names = _static_info_from_call(value)
        # Host call sites reach the wrapper through its BINDING name(s);
        # self-attribute bindings contribute none (a bound-method wrap
        # shifts static positions — the body is still checked via
        # JIT001/002, only JIT003 call-site matching skips them).
        bind_targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        bindings = {t.id for t in bind_targets if isinstance(t, ast.Name)}
        existing = out.get(id(wrapped))
        if existing is not None:
            # Wrapped twice (donating/copying twins): statics must agree on
            # the conservative union of traced params -> intersect statics.
            existing.static_nums &= nums
            existing.static_names &= names
            existing.call_names |= bindings
        else:
            out[id(wrapped)] = JitFunction(
                wrapped, module.path, nums, names, call_names=bindings
            )
    return out


def _imported_module_aliases(module):
    """Dotted paths bound to imported MODULES: ``import x.y`` -> "x.y"
    (reached at call sites as ``x.y.fn``), ``import x.y as z`` -> "z".
    ``from``-imports are left out: they bind functions/classes/instances
    as often as submodules, and guessing wrong would re-open the
    method-vs-module misattribution this distinction exists to close."""
    cached = getattr(module, "lint_module_aliases", None)
    if cached is None:
        cached = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    cached.add(alias.asname or alias.name)
        module.lint_module_aliases = cached
    return cached


def _names_in(node, skip_is_none=False):
    """All Name identifiers read inside ``node``.  With ``skip_is_none``,
    reads that sit inside an ``x is None`` / ``x is not None`` compare are
    excluded (that test never inspects a traced value) — but only those
    READS, not the name wholesale: in ``x is None or x > 0`` the second
    read still concretizes ``x`` and must count.  Reads that only touch
    static array metadata (``x.shape``/``x.ndim``/``x.dtype``) are
    likewise exempt: those are concrete under tracing."""
    exempt_reads = set()
    if skip_is_none:
        for cmp_node in ast.walk(node):
            if not isinstance(cmp_node, ast.Compare):
                continue
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in cmp_node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in cmp_node.comparators
            ):
                exempt_reads |= {
                    id(n) for n in ast.walk(cmp_node) if isinstance(n, ast.Name)
                }
    names = set()
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Name)
            and isinstance(sub.ctx, ast.Load)
            and id(sub) not in exempt_reads
        ):
            parent = getattr(sub, "lint_parent", None)
            if (
                isinstance(parent, ast.Attribute)
                and parent.value is sub
                and parent.attr in _STATIC_METADATA_ATTRS
            ):
                continue
            names.add(sub.id)
    return names


class HostSyncInJit(Rule):
    id = "JIT001"
    name = "host-sync-in-jit"
    description = (
        "No host synchronization inside a jit-compiled function: .item() / "
        ".tolist() / .block_until_ready(), float()/int()/bool() on traced "
        "parameters, or numpy (np.*) calls over traced values."
    )

    def check(self, module):
        for jit_fn in collect_jit_functions(module).values():
            traced = jit_fn.traced_params()
            for node in ast.walk(jit_fn.node):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in _HOST_SYNC_ATTRS
                ):
                    yield Diagnostic(
                        module.path,
                        node.lineno,
                        node.col_offset,
                        self.id,
                        f".{func.attr}() inside jit function "
                        f"'{jit_fn.node.name}' forces a host sync; keep the "
                        "value on device or move the read outside the jit",
                    )
                    continue
                fname = dotted_name(func)
                if fname in _CONCRETIZING_BUILTINS and node.args:
                    used = _names_in(node.args[0]) & traced
                    if used:
                        yield Diagnostic(
                            module.path,
                            node.lineno,
                            node.col_offset,
                            self.id,
                            f"{fname}() concretizes traced value "
                            f"{sorted(used)[0]!r} inside jit function "
                            f"'{jit_fn.node.name}'; use jnp ops or make the "
                            "argument static",
                        )
                    continue
                if (
                    fname
                    and "." in fname
                    and fname.split(".", 1)[0] in _NUMPY_ALIASES
                ):
                    used = set()
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        used |= _names_in(arg) & traced
                    if used:
                        yield Diagnostic(
                            module.path,
                            node.lineno,
                            node.col_offset,
                            self.id,
                            f"numpy call {fname}() over traced value "
                            f"{sorted(used)[0]!r} inside jit function "
                            f"'{jit_fn.node.name}'; use jax.numpy instead",
                        )


class BranchOnTraced(Rule):
    id = "JIT002"
    name = "branch-on-traced"
    description = (
        "No Python control flow on traced values inside a jit-compiled "
        "function: if/while/assert on a traced parameter traces only one "
        "side (or raises ConcretizationTypeError); use lax.cond/jnp.where."
    )

    def check(self, module):
        for jit_fn in collect_jit_functions(module).values():
            traced = jit_fn.traced_params()
            for node in ast.walk(jit_fn.node):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.Assert):
                    test = node.test
                else:
                    continue
                used = _names_in(test, skip_is_none=True) & traced
                if used:
                    kind = type(node).__name__.lower()
                    yield Diagnostic(
                        module.path,
                        node.lineno,
                        node.col_offset,
                        self.id,
                        f"python {kind} on traced value {sorted(used)[0]!r} "
                        f"inside jit function '{jit_fn.node.name}'; use "
                        "jnp.where/lax.cond (or declare the argument static)",
                    )


class UnpinnedScalarArg(Rule):
    id = "JIT003"
    name = "unpinned-scalar-arg"
    description = (
        "No bare Python numeric literals threaded positionally into a "
        "non-static slot of a jit-compiled function from host code: the "
        "weak-typed scalar forks the jit cache signature the prewarmer "
        "pins (pass an array with an explicit dtype, or make the slot "
        "static)."
    )

    def begin(self, modules):
        # name -> list of JitFunction across the project: call sites usually
        # import the function by name, so the registry is keyed on it.  A
        # slot is flagged only if it is non-static in EVERY registration of
        # that name (conservative under collisions).
        self._registry = {}
        self._jit_spans = {}  # path -> list of jit function nodes
        for module in modules:
            fns = collect_jit_functions(module)
            for jit_fn in fns.values():
                for call_name in jit_fn.call_names:
                    self._registry.setdefault(call_name, []).append(jit_fn)
            self._jit_spans[module.path] = [f.node for f in fns.values()]

    def _inside_jit(self, module, node):
        """Literal scalars in jit-to-jit calls are constant-folded into the
        trace — only host-side call sites can fork the cache signature."""
        jit_nodes = set(map(id, self._jit_spans.get(module.path, ())))
        fn = enclosing_function(node)
        while fn is not None:
            if id(fn) in jit_nodes:
                return True
            fn = enclosing_function(fn)
        return False

    def check(self, module):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                name = func.id
                # A bare-name call can only reach a module-level jit
                # function; bound methods arrive as Attribute calls with
                # the self slot implicit, shifting positions by one.
                offset = 0
                wants_method = False
            elif isinstance(func, ast.Attribute):
                name = func.attr
                # `mod.fn(...)` / `pkg.mod.fn(...)` through an imported
                # module path is a module-level call (no self slot); any
                # other base is assumed to be a bound method.
                dotted = dotted_name(func)
                if (
                    dotted is not None
                    and dotted.rsplit(".", 1)[0]
                    in _imported_module_aliases(module)
                ):
                    offset = 0
                    wants_method = False
                else:
                    offset = 1
                    wants_method = True
            else:
                continue
            candidates = self._registry.get(name)
            if not candidates or self._inside_jit(module, node):
                continue
            candidates = [
                fn for fn in candidates if fn.is_method() == wants_method
            ]
            if not candidates:
                continue
            for index, arg in enumerate(node.args):
                position = index + offset
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, (int, float))
                    and not isinstance(arg.value, bool)
                ):
                    continue
                if any(fn.is_static_position(position) for fn in candidates):
                    continue
                if all(
                    position >= len(fn.positional_params()) for fn in candidates
                ):
                    continue
                yield Diagnostic(
                    module.path,
                    arg.lineno,
                    arg.col_offset,
                    self.id,
                    f"python scalar {arg.value!r} passed positionally into "
                    f"non-static slot {position} of jit function '{name}'; "
                    "wrap in jnp.asarray(..., dtype=...) or pin it via "
                    "static_argnums/static_argnames",
                )


#: Per-round dispatch surfaces (the fused suggest prep/dispatch chain and
#: the gateway's coalesced twin) that must not construct sharding objects
#: per call even though they are not jit-compiled themselves: ``Mesh(...)``
#: re-hashes the device list and ``NamedSharding(...)`` re-derives the
#: per-device layout on every call, and — worse — a fresh Mesh object is a
#: fresh jit-cache static, so a per-call construction silently retraces
#: what the prewarmer pinned.  Everything here must go through the cached
#: helpers in ``orion_tpu.algo.sharding`` (``get_mesh``/``candidate_spec``/
#: ``replicated_spec``), which return the SAME object per signature.
HOT_PATH_REGISTRY = frozenset({
    "_suggest_step",
    "_stacked_suggest_step",
    "_tenant_parallel_suggest_step",
    "make_fused_plan",
    "run_fused_plan",
    "run_suggest_step_arrays",
    "stack_plans",
    "run_coalesced_plans",
})

#: Sharding-object constructors whose call cost (and jit-static identity)
#: the rule polices.  Matched on the last dotted component, so ``Mesh``,
#: ``jax.sharding.Mesh`` and ``sharding.NamedSharding`` all count.
_SHARDING_CONSTRUCTORS = frozenset({"Mesh", "NamedSharding"})


class ShardingConstructionInHotPath(Rule):
    id = "JIT004"
    name = "sharding-construction-in-hot-path"
    description = (
        "No per-call Mesh(...)/NamedSharding(...) construction inside a "
        "jit-compiled function or a declared hot-path function "
        "(HOT_PATH_REGISTRY): a fresh Mesh is a fresh jit-cache static "
        "(silent retrace) and the construction re-hashes the device list "
        "every round; use the cached orion_tpu.algo.sharding helpers "
        "(get_mesh/candidate_spec/replicated_spec)."
    )

    def check(self, module):
        jit_nodes = {
            id(fn.node) for fn in collect_jit_functions(module).values()
        }
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(node) not in jit_nodes and node.name not in HOT_PATH_REGISTRY:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                fname = dotted_name(call.func)
                if fname is None:
                    continue
                if fname.rsplit(".", 1)[-1] not in _SHARDING_CONSTRUCTORS:
                    continue
                yield Diagnostic(
                    module.path,
                    call.lineno,
                    call.col_offset,
                    self.id,
                    f"{fname}(...) constructed inside hot-path function "
                    f"'{node.name}' — a per-call sharding object re-hashes "
                    "the device list and forks the jit-cache statics; use "
                    "the cached orion_tpu.algo.sharding helpers "
                    "(get_mesh/candidate_spec/replicated_spec)",
                )


JIT_RULES = (HostSyncInJit, BranchOnTraced, UnpinnedScalarArg,
             ShardingConstructionInHotPath)
