"""Static analysis for orion-tpu's own invariants (``orion-tpu lint``).

Four rule families over the codebase's AST, each pinning a convention the
runtime cannot check for itself:

- ``JIT*``  — retrace hygiene inside jit-compiled functions and at their
  call sites (``jit_rules``);
- ``STO*``  — storage protocol ops ride the unified retry policy with an
  explicit applied-or-not mode, and wire errors carry ``maybe_applied``
  (``storage_rules``);
- ``TEL*``  — telemetry stays allocation-free when disabled and cheap when
  enabled (``telemetry_rules``);
- ``LCK*``  — the static lock graph stays acyclic and shared attributes
  stay behind their lock (``lock_rules``).

``run_lint(paths)`` is the whole API; the tier-1 self-lint test and the
bench ``--smoke`` preflight both call it directly.  Rule catalog and
suppression syntax: ``docs/static_analysis.md``.
"""

from orion_tpu.analysis.engine import (
    Diagnostic,
    Rule,
    default_rules,
    format_human,
    format_json,
    rule_catalog,
    run_lint,
)

__all__ = [
    "Diagnostic",
    "Rule",
    "default_rules",
    "format_human",
    "format_json",
    "rule_catalog",
    "run_lint",
]
