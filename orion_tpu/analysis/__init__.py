"""Static analysis for orion-tpu's own invariants (``orion-tpu lint``).

Four rule families over the codebase's AST, each pinning a convention the
runtime cannot check for itself:

- ``JIT*``  — retrace hygiene inside jit-compiled functions and at their
  call sites (``jit_rules``);
- ``STO*``  — storage protocol ops ride the unified retry policy with an
  explicit applied-or-not mode, and wire errors carry ``maybe_applied``
  (``storage_rules``);
- ``TEL*``  — telemetry stays allocation-free when disabled and cheap when
  enabled (``telemetry_rules``);
- ``LCK*``  — the static lock graph stays acyclic and shared attributes
  stay behind their lock (``lock_rules``).

``run_lint(paths)`` is the whole API; the tier-1 self-lint test and the
bench ``--smoke`` preflight both call it directly.  Rule catalog and
suppression syntax: ``docs/static_analysis.md``.

The dynamic half lives in ``orion_tpu.analysis.sanitizer`` (``orion-tpu
tsan``): instrumented lock shims, vector-clock race detection, and the
static↔dynamic cross-check that feeds runtime-observed lock edges back
into the ``LCK`` graph as ``LCK003`` findings.

The package facade is LAZY (PEP 562): ``sanitizer`` is stdlib-only and
imported at module scope by the telemetry/health/serve/storage hot paths
for their cell annotations — an eager engine import here would tax every
process start ~35 ms for a lint facility most processes never run.
"""

__all__ = [
    "Diagnostic",
    "Rule",
    "default_rules",
    "format_human",
    "format_json",
    "rule_catalog",
    "run_lint",
]


def __getattr__(name):
    if name in __all__:
        from orion_tpu.analysis import engine

        return getattr(engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
