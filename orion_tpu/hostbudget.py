"""The host-budget factor — ONE source of truth for every consumer.

ROADMAP item 5 (the host-tail endgame) tightens the steady-state bar to
``host_ms_per_round <= 1.25 x device_ms_per_round``.  Three consumers
read the SAME knob so they can never drift apart:

- ``bench.py``'s ``_check_host_budget`` (WARN on full runs, hard
  SystemExit under ``--smoke``);
- the doctor's DX004 ``HostBudgetBreach`` rule — its threshold over the
  ``producer.round`` / ``device.dispatch`` histogram means is derived as
  ``1.0 + host_budget_factor()`` because the producer round CONTAINS the
  device window (host tax of F x device makes the round (1+F) x device);
- ``orion-tpu top``/``info``'s live host/device ratio column, which
  flags workers over the same derived bar.

``ORION_TPU_HOST_BUDGET_FACTOR`` overrides everywhere at once, so an
unusual runner (e.g. a remote-tunnel TPU with pathological transfer
latency) re-tunes the whole stack without editing any gate.
"""

import logging
import os

log = logging.getLogger(__name__)

#: The ROADMAP item-5 bar: host tax per steady-state round may cost at
#: most this multiple of the device time (was 2.0 through ISSUE 13).
DEFAULT_HOST_BUDGET_FACTOR = 1.25

ENV_VAR = "ORION_TPU_HOST_BUDGET_FACTOR"


def host_budget_factor():
    """The effective host-budget factor: env override, else the default.

    Read at call time (not import time) so a test or runner can flip the
    env var without re-importing every consumer.  A malformed override
    falls back to the default (warned once per call site's logger config)
    rather than crashing the bench, the doctor AND the CLIs together."""
    raw = os.environ.get(ENV_VAR, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            log.warning(
                "ignoring malformed %s=%r (want a float); using %s",
                ENV_VAR, raw, DEFAULT_HOST_BUDGET_FACTOR,
            )
    return DEFAULT_HOST_BUDGET_FACTOR


def round_budget_factor():
    """DX004's derived threshold over ``producer.round`` vs
    ``device.dispatch``: the round INCLUDES the device window, so a host
    budget of F x device bounds the whole round at (1 + F) x device."""
    return 1.0 + host_budget_factor()
