"""Programmatic trial insertion.

Capability parity: reference `src/orion/client/manual.py` — validate points
against the experiment space and register them as new trials.
"""

from orion_tpu.core.trial import Trial


def insert_trials(experiment, params_list, validate=True):
    """Register fixed-parameter trials on an experiment."""
    trials = []
    for params in params_list:
        params = dict(params)
        if validate and experiment.space is not None:
            if not experiment.space.contains_point(params):
                raise ValueError(
                    f"Point {params} is not contained in space "
                    f"{experiment.space}"
                )
        trial = Trial(params=params)
        experiment.register_trial(trial)
        trials.append(trial)
    return trials
