"""Client helpers imported by the *user's* script.

Capability parity: reference `src/orion/client/__init__.py` — the script-side
half of the results contract: `report_results(data)` writes JSON to
``$ORION_RESULTS_PATH`` when running under a worker (once only), else prints
to stdout so scripts stay runnable standalone.  ``IS_ORION_ON`` tells the
script whether it is being orchestrated.
"""

import json
import os

IS_ORION_ON = False
RESULTS_FILENAME = os.getenv("ORION_RESULTS_PATH", None)
_HAS_REPORTED_RESULTS = False

if RESULTS_FILENAME and os.path.exists(os.path.dirname(os.path.abspath(RESULTS_FILENAME))):
    IS_ORION_ON = True


def report_results(data):
    """Report final evaluation results of this trial.

    ``data`` is a list of dicts ``{"name", "type", "value"}`` where exactly
    one entry should have type ``"objective"``.  May be called once.
    """
    global _HAS_REPORTED_RESULTS
    if _HAS_REPORTED_RESULTS:
        raise RuntimeWarning("Has already reported evaluation results once.")
    if IS_ORION_ON:
        with open(RESULTS_FILENAME, "w") as handle:
            json.dump(data, handle)
    else:
        print(json.dumps(data))
    _HAS_REPORTED_RESULTS = True


def report_objective(value, name="objective"):
    """Convenience wrapper for the common single-objective case."""
    report_results([{"name": name, "type": "objective", "value": value}])
