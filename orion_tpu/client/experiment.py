"""Library-level optimization API — no subprocess, no CLI.

The reference exposes `workon` as a library (used in
tests/functional/demo/test_demo.py "workon as library"); here that surface is
a first-class `optimize()` driving a python callable directly, plus an
`ExperimentClient` with suggest/observe for external loops (e.g. evaluating
a whole q-batch on device at once — the benchmark harness does exactly
this).
"""

import jax.numpy as jnp
import numpy as np

from orion_tpu.core.experiment import build_experiment
from orion_tpu.core.producer import Producer
from orion_tpu.core.trial import Result
from orion_tpu.storage.base import create_storage
from orion_tpu.utils.exceptions import AlgorithmExhausted, WaitingForTrials


class ExperimentClient:
    """suggest/observe handle over a built experiment."""

    def __init__(self, experiment, max_idle_time=60.0):
        self.experiment = experiment
        if experiment.algorithm is None:
            experiment.instantiate()
        self.producer = Producer(experiment, max_idle_time=max_idle_time)

    @property
    def space(self):
        return self.experiment.space

    def suggest(self, num=1):
        """Reserve ``num`` trials, producing fresh ones as needed.  Batched:
        a q-batch reservation is one pipelined storage round trip on the
        network backend instead of q serialized ones."""
        out = []
        self.producer.update()
        while len(out) < num:
            got = self.experiment.reserve_trials(num - len(out))
            if not got:
                try:
                    # Tell the producer how many reserved trials WE hold:
                    # an opt-out must not wait on our own reservations (we
                    # are the one who would complete them — deadlock), but
                    # must still wait on other workers' in-flight trials.
                    self.producer.produce(num - len(out), own_in_flight=len(out))
                except AlgorithmExhausted:
                    if out:
                        # Hand back the partial batch; the next call (with
                        # nothing reserved) re-raises for the caller to stop.
                        return out
                    raise
                got = self.experiment.reserve_trials(num - len(out))
            if not got:
                if out:
                    return out  # partial batch: a finite algorithm ran dry
                raise WaitingForTrials("could not reserve after producing")
            out.extend(got)
        return out

    def observe(self, trial, objective, **aux_results):
        results = [Result("objective", "objective", float(objective))]
        for name, value in aux_results.items():
            results.append(Result(name, "statistic", value))
        self.experiment.update_completed_trial(trial, results)

    def observe_all(self, trials, objectives):
        """Batch completion: one pipelined storage round trip on the network
        backend.  Raises the first per-trial failure after applying the whole
        batch (matching ``observe``'s FailedUpdate contract)."""
        pairs = [
            (trial, [Result("objective", "objective", float(objective))])
            for trial, objective in zip(trials, objectives)
        ]
        outcomes = self.experiment.update_completed_trials(pairs)
        for outcome in outcomes:
            if isinstance(outcome, Exception):
                raise outcome

    @property
    def is_done(self):
        return self.experiment.is_done

    def stats(self):
        return self.experiment.stats()


def optimize(
    fn,
    priors,
    max_trials=100,
    batch_size=1,
    algorithm="random",
    strategy=None,
    seed=None,
    storage=None,
    name="optimize",
    batch_eval=None,
):
    """Minimize ``fn(params_dict) -> float`` over a prior-DSL space.

    ``batch_eval``: optional vectorized evaluator taking the (n, D) unit-cube
    jnp array and returning (n,) objectives — keeps whole q-batches on device
    (used for analytic benchmarks).
    """
    storage = storage or create_storage({"type": "memory"})
    experiment = build_experiment(
        storage,
        name,
        priors=dict(priors),
        max_trials=max_trials,
        algorithms=algorithm,
        strategy=strategy,
        pool_size=batch_size,
    ).instantiate(seed=seed)
    client = ExperimentClient(experiment)

    n_done = 0
    while n_done < max_trials and not client.is_done:
        want = min(batch_size, max_trials - n_done)
        try:
            trials = client.suggest(want)
        except AlgorithmExhausted:
            # Finite algorithm ran dry before max_trials — a clean finish.
            break
        if batch_eval is not None:
            space = experiment.space
            arrays = space.params_to_arrays([t.params for t in trials])
            cube = space.encode_flat(arrays)
            values = np.asarray(batch_eval(jnp.asarray(cube)))
            client.observe_all(trials, [float(v) for v in values])
        else:
            client.observe_all(trials, [float(fn(t.params)) for t in trials])
        n_done += len(trials)
    return client.stats()
