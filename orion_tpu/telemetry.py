"""Unified framework telemetry: metrics registry + span tracer + exporters.

One process-wide :class:`Telemetry` registry replaces the three dialects the
stack grew organically — producer ``_record_timing`` samples, storage
``txn_count``/``wire_requests`` counters, bench ``breakdown_ms`` stages —
with primitives that can all be correlated in time:

- **counters** (monotonic ints), **gauges** (last-set floats), and
  **histograms** (fixed log2 buckets over seconds — mergeable across
  workers by summing buckets, percentile-queryable without storing samples);
- a **span tracer**: monotonic-clock ``(name, ts, dur, pid, tid)`` records
  in a preallocated ring buffer, exported as JSONL or Chrome trace-event
  JSON (loads directly in Perfetto / chrome://tracing).

The registry is near-zero-cost when disabled: every mutator early-returns
on one attribute check, and ``span()`` returns a shared no-op context
manager — no locks, no allocations, no clock reads.  Toggle with the
``ORION_TPU_TELEMETRY`` env var (``1/on/true/yes``), the ``telemetry:``
config key, or programmatically via ``TELEMETRY.enable()``.

Contract shared with the producer's ``_flush_timings``: telemetry must
never raise into a hot path.  Mutators swallow their own failures; only
the explicit exporters propagate I/O errors.

Cross-worker story: each worker flushes ``snapshot()`` (metrics) and
``drain_spans()`` (new span records) through the storage channel
(``DocumentStorage.record_metrics`` / ``record_spans``) every producer
round; ``orion-tpu info`` merges the snapshots with
:func:`merge_snapshots`, and ``orion-tpu trace`` merges every worker's
spans into one Chrome trace (span timestamps are wall-anchored monotonic
readings, so processes line up on a shared timeline).
"""

import json
import os
import threading
import time
import weakref

# Annotated-cell hooks for the runtime concurrency sanitizer
# (orion-tpu tsan): one attribute check when disabled, constant-string
# args — the same cost discipline the registry itself keeps.
from orion_tpu.analysis.sanitizer import TSAN

_ENABLE_VALUES = ("1", "on", "true", "yes")

#: Histogram shape: bucket ``i`` counts durations in ``[2**(i-1), 2**i)``
#: microseconds (bucket 0 is < 1 µs).  48 buckets reach ~1.6 days — far
#: past any single operation this framework times.  FIXED across versions:
#: merged snapshots sum buckets elementwise, so every writer must agree.
N_BUCKETS = 48

DEFAULT_SPAN_CAPACITY = 4096


def _bucket_of(seconds):
    """Index of the log2-µs bucket holding ``seconds``."""
    micros = int(seconds * 1e6)
    if micros <= 0:
        return 0
    return min(micros.bit_length(), N_BUCKETS - 1)


def bucket_upper_seconds(index):
    """Upper bound (seconds) of bucket ``index`` — what percentile queries
    report (conservative: the true sample is at most this)."""
    return float(2**index) / 1e6


class _NullSpan:
    """The disabled-path span: ONE shared instance, allocation-free."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """An enabled span: records itself into the registry on exit."""

    __slots__ = ("_telemetry", "name", "args", "_t0")

    def __init__(self, telemetry, name, args):
        self._telemetry = telemetry
        self.name = name
        self.args = args
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._telemetry.record_span(self.name, start=self._t0, args=self.args)
        return False


class Telemetry:
    """Process-wide counters/gauges/histograms + span ring buffer.

    Thread-safe: one registry lock guards every mutation.  Recording rates
    are per-operation (a handful per producer round), so lock contention is
    not a concern — the DISABLED path is the one that must stay free, and
    it never touches the lock.
    """

    def __init__(self, enabled=None, span_capacity=None):
        if enabled is None:
            enabled = (
                os.environ.get("ORION_TPU_TELEMETRY", "").strip().lower()
                in _ENABLE_VALUES
            )
        if span_capacity is None:
            try:
                span_capacity = int(
                    os.environ.get("ORION_TPU_TELEMETRY_SPANS", "")
                    or DEFAULT_SPAN_CAPACITY
                )
            except ValueError:
                span_capacity = DEFAULT_SPAN_CAPACITY
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        # name -> [buckets list, count, sum, min, max]
        self._histograms = {}
        # name -> list of (weakref, attr): external monotonic counters
        # (SQLiteDB.txn_count, NetworkDB.wire_requests, ...) sampled at
        # snapshot time — zero hot-path cost for the owning backend.
        self._external = {}
        # Preallocated span ring: slot i%capacity holds span seq i.
        self._capacity = max(int(span_capacity), 8)
        self._ring = [None] * self._capacity
        self._seq = 0
        self._drained = 0
        # Wall anchor: ts_wall = _anchor + perf_counter reading.  Spans use
        # the monotonic clock for start/duration; the anchor puts every
        # process on one comparable wall timeline at export/merge time.
        self._anchor = time.time() - time.perf_counter()

    # --- toggling -----------------------------------------------------------
    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    # --- metrics ------------------------------------------------------------
    def count(self, name, n=1):
        """Increment counter ``name`` by ``n``."""
        if not self.enabled:
            return
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def counter_value(self, name, default=0):
        """Current value of counter ``name`` (``default`` when never
        incremented).  Reader for in-process assertions — the
        boundary-crossing tests and ``bench.py --smoke`` check
        ``jax.retraces``/``jax.prewarms`` deltas through this."""
        with self._lock:
            TSAN.read("Telemetry._metrics", self)
            return self._counters.get(name, default)

    def set_gauge(self, name, value):
        """Set gauge ``name`` to ``value`` (last write wins)."""
        if not self.enabled:
            return
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            self._gauges[name] = float(value)

    def observe(self, name, seconds):
        """Record one duration sample into histogram ``name``."""
        if not self.enabled:
            return
        seconds = float(seconds)
        with self._lock:
            self._observe_locked(name, seconds)

    def _observe_locked(self, name, seconds):
        """THE histogram update — callers hold the registry lock.  Shared
        by observe() and record_span() so the two sample sources can never
        drift apart."""
        TSAN.write("Telemetry._metrics", self)
        hist = self._histograms.get(name)
        if hist is None:
            hist = [[0] * N_BUCKETS, 0, 0.0, seconds, seconds]
            self._histograms[name] = hist
        hist[0][_bucket_of(seconds)] += 1
        hist[1] += 1
        hist[2] += seconds
        hist[3] = min(hist[3], seconds)
        hist[4] = max(hist[4], seconds)

    def register_external_counter(self, name, obj, attr):
        """Expose ``obj.attr`` (a monotonic int the owner already maintains,
        e.g. ``SQLiteDB.txn_count``) as counter ``name``.  Sampled lazily at
        snapshot time; held by weakref so registration never extends the
        owner's lifetime.  Multiple registrations under one name sum."""
        try:
            ref = weakref.ref(obj)
        except TypeError:  # pragma: no cover - exotic objects without weakref
            return
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            self._external.setdefault(name, []).append((ref, attr))

    def _external_counts(self):
        out = {}
        with self._lock:
            TSAN.write("Telemetry._metrics", self)  # prunes dead registrations
            for name, entries in list(self._external.items()):
                live = [(ref, attr) for ref, attr in entries if ref() is not None]
                if not live:
                    del self._external[name]
                    continue
                self._external[name] = live
                total = 0
                for ref, attr in live:
                    owner = ref()
                    if owner is not None:
                        try:
                            total += int(getattr(owner, attr, 0))
                        except Exception:  # pragma: no cover - hostile attr
                            pass
                out[name] = total
        return out

    # --- spans --------------------------------------------------------------
    def span(self, name, args=None):
        """Context manager timing a block.  Disabled: the shared no-op
        singleton (no allocation, no clock read).  Enabled: records a span
        AND a duration sample into the histogram of the same name."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def record_span(self, name, start=None, duration=None, args=None, histogram=True):
        """Record one finished span explicitly.

        ``start``/``duration`` are ``time.perf_counter()`` readings/deltas;
        give either or both (a missing start is back-computed from now, a
        missing duration runs to now).  Callers that already measured a
        phase (the producer's ``_record_timing``) route through here so the
        span and its histogram sample come from the same clock reading.
        ``histogram=False`` records the span only — for call sites that
        feed a differently-keyed histogram themselves (the storage layer's
        per-backend op histograms) and must not double-book the sample."""
        if not self.enabled:
            return
        try:
            record, duration = self._build_span_record(
                name, start, duration, args, time.perf_counter()
            )
            with self._lock:
                TSAN.write("Telemetry._ring", self)
                self._ring[self._seq % self._capacity] = record
                self._seq += 1
                if histogram:
                    self._observe_locked(name, duration)
        except Exception:  # pragma: no cover - must never raise into hot path
            pass

    def _build_span_record(self, name, start, duration, args, now):
        """THE span-record builder — shared by :meth:`record_span` and
        :meth:`record_spans_batch` so the None-start back-computation and
        the record schema cannot drift between the per-call and batched
        paths.  Returns ``(record, duration_seconds)``."""
        if start is None:
            duration = float(duration or 0.0)
            start = now - duration
        elif duration is None:
            duration = now - start
        record = {
            "name": name,
            "ts": self._anchor + start,
            "dur": float(duration),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            record["args"] = dict(args)
        return record, float(duration)

    def record_spans_batch(self, entries):
        """Record many finished spans under ONE lock acquisition.

        ``entries`` is ``[(name, start, duration, args), ...]`` with the
        same semantics as :meth:`record_span` (``start`` a perf_counter
        reading; a None start is back-computed from ``duration`` against
        the batch's shared "now").  The producer buffers its per-sample
        spans across a round and flushes them here — per-sample
        ``record_span`` calls each paid a lock round-trip and a clock read
        inside the hot loop (see ``bench.py``'s ``telemetry_us_saved``)."""
        if not self.enabled or not entries:
            return
        try:
            now = time.perf_counter()
            records = [
                (name,) + self._build_span_record(name, start, duration, args, now)
                for name, start, duration, args in entries
            ]
            with self._lock:
                TSAN.write("Telemetry._ring", self)
                for name, record, duration in records:
                    self._ring[self._seq % self._capacity] = record
                    self._seq += 1
                    self._observe_locked(name, duration)
        except Exception:  # pragma: no cover - must never raise into hot path
            pass

    def iter_spans(self):
        """Every span currently in the ring, oldest first (wraparound has
        dropped anything older than ``capacity`` records)."""
        with self._lock:
            TSAN.read("Telemetry._ring", self)
            start = max(0, self._seq - self._capacity)
            return [self._ring[i % self._capacity] for i in range(start, self._seq)]

    def drain_spans(self):
        """Spans recorded since the last drain (each span is returned
        exactly once — the worker flush channel).  Wraparound between
        drains loses the overwritten oldest records, by design."""
        with self._lock:
            TSAN.write("Telemetry._ring", self)  # advances the drain cursor
            start = max(self._drained, self._seq - self._capacity)
            out = [self._ring[i % self._capacity] for i in range(start, self._seq)]
            self._drained = self._seq
            return out

    # --- snapshots / merging ------------------------------------------------
    def snapshot(self):
        """One mergeable metrics snapshot: counters (external ones sampled
        now), gauges, histograms.  This is the document a worker flushes
        through ``DocumentStorage.record_metrics`` every round."""
        external = self._external_counts()
        with self._lock:
            TSAN.read("Telemetry._metrics", self)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = {
                name: {
                    "buckets": list(hist[0]),
                    "count": hist[1],
                    "sum": hist[2],
                    "min": hist[3],
                    "max": hist[4],
                }
                for name, hist in self._histograms.items()
            }
        for name, value in external.items():
            counters[name] = counters.get(name, 0) + value
        return {"counters": counters, "gauges": gauges, "histograms": histograms}

    def reset(self):
        """Drop every metric and span, INCLUDING external-counter
        registrations (test/bench isolation: a still-alive backend's
        monotonic txn/wire totals must not bleed into a fresh measurement;
        a backend created after the reset re-registers on construction)."""
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            TSAN.write("Telemetry._ring", self)
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._external.clear()
            self._ring = [None] * self._capacity
            self._seq = 0
            self._drained = 0

    # --- exporters ----------------------------------------------------------
    def export_jsonl(self, path):
        """One JSON object per line: every span in the ring, then one
        ``{"type": "metrics", ...}`` snapshot line."""
        spans = self.iter_spans()
        with open(path, "w") as handle:
            for span in spans:
                handle.write(json.dumps({"type": "span", **span}) + "\n")
            handle.write(json.dumps({"type": "metrics", **self.snapshot()}) + "\n")
        return path

    def export_chrome_trace(self, path):
        """Chrome trace-event JSON of the ring (loads in Perfetto)."""
        return write_chrome_trace(path, self.iter_spans())


def histogram_percentile(hist, p):
    """Nearest-rank percentile (seconds) from a snapshot histogram dict —
    the upper bound of the bucket holding the rank, so the report is
    conservative within one 2x bucket."""
    count = int(hist.get("count", 0))
    if count <= 0:
        return 0.0
    rank = max(1, -(-int(p * count) // 100))  # ceil(p/100 * count)
    seen = 0
    for index, n in enumerate(hist.get("buckets", ())):
        seen += n
        if seen >= rank:
            return min(bucket_upper_seconds(index), float(hist.get("max", 0.0)))
    return float(hist.get("max", 0.0))


def merge_snapshots(snapshots):
    """Aggregate worker snapshot docs into one: counters and histogram
    buckets SUM (they are per-worker monotonic totals); gauges merge by
    MAX — they are risk signals (heartbeat lag), and the worker whose
    gauge matters is exactly the stalled one that stopped flushing, so
    freshest-write-wins would mask it behind a healthy worker's ~0.
    Accepts raw ``snapshot()`` dicts or storage docs carrying extra keys
    (``experiment``/``worker``/``time``)."""
    counters = {}
    gauges = {}
    histograms = {}
    for doc in snapshots:
        for name, value in (doc.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(value)
        for name, value in (doc.get("gauges") or {}).items():
            value = float(value)
            gauges[name] = max(gauges[name], value) if name in gauges else value
        for name, hist in (doc.get("histograms") or {}).items():
            merged = histograms.get(name)
            if merged is None:
                histograms[name] = {
                    "buckets": list(hist.get("buckets") or [0] * N_BUCKETS),
                    "count": int(hist.get("count", 0)),
                    "sum": float(hist.get("sum", 0.0)),
                    "min": float(hist.get("min", 0.0)),
                    "max": float(hist.get("max", 0.0)),
                }
                continue
            buckets = hist.get("buckets") or ()
            for index, n in enumerate(buckets):
                if index < len(merged["buckets"]):
                    merged["buckets"][index] += n
            merged["count"] += int(hist.get("count", 0))
            merged["sum"] += float(hist.get("sum", 0.0))
            merged["min"] = min(merged["min"], float(hist.get("min", 0.0)))
            merged["max"] = max(merged["max"], float(hist.get("max", 0.0)))
    return {"counters": counters, "gauges": gauges, "histograms": histograms}


def chrome_trace_events(spans):
    """Span records -> Chrome trace-event dicts (complete 'X' events, µs).

    Spans may come from one process's ring or from the storage channel
    (several workers).  Tracks are keyed by the WORKER identity (host:pid
    when present — a bare OS pid collides across hosts, e.g. two
    containerized workers both running as pid 1), mapped to synthetic
    sequential pids; each track gets a process_name metadata event so
    Perfetto labels the rows."""
    events = []
    tracks = {}  # worker label -> synthetic pid
    for span in spans:
        if not span:
            continue
        label = str(span.get("worker") or f"orion-tpu:{span.get('pid', 0)}")
        if label not in tracks:
            tracks[label] = len(tracks) + 1
        event = {
            "name": str(span.get("name", "?")),
            "cat": str(span.get("name", "?")).split(".", 1)[0],
            "ph": "X",
            "ts": float(span.get("ts", 0.0)) * 1e6,
            "dur": float(span.get("dur", 0.0)) * 1e6,
            "pid": tracks[label],
            "tid": int(span.get("tid", 0)),
        }
        args = span.get("args")
        if args:
            event["args"] = dict(args)
        events.append(event)
    for label, pid in tracks.items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": label},
            }
        )
    return events


def write_chrome_trace(path, spans):
    """Write ``spans`` as a Chrome trace-event JSON file (Perfetto-ready)."""
    payload = {
        "traceEvents": chrome_trace_events(spans),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as handle:
        json.dump(payload, handle)
    return path


#: THE process-wide registry every subsystem records into.  Enabled state
#: comes from ORION_TPU_TELEMETRY at import; the CLI layers the
#: ``telemetry:`` config key on top (cli/base.py).
TELEMETRY = Telemetry()
