"""Unified framework telemetry: metrics registry + span tracer + exporters.

One process-wide :class:`Telemetry` registry replaces the three dialects the
stack grew organically — producer ``_record_timing`` samples, storage
``txn_count``/``wire_requests`` counters, bench ``breakdown_ms`` stages —
with primitives that can all be correlated in time:

- **counters** (monotonic ints), **gauges** (last-set floats), and
  **histograms** (fixed log2 buckets over seconds — mergeable across
  workers by summing buckets, percentile-queryable without storing samples);
- a **span tracer**: monotonic-clock ``(name, ts, dur, pid, tid)`` records
  in a preallocated ring buffer, exported as JSONL or Chrome trace-event
  JSON (loads directly in Perfetto / chrome://tracing).

The registry is near-zero-cost when disabled: every mutator early-returns
on one attribute check, and ``span()`` returns a shared no-op context
manager — no locks, no allocations, no clock reads.  Toggle with the
``ORION_TPU_TELEMETRY`` env var (``1/on/true/yes``), the ``telemetry:``
config key, or programmatically via ``TELEMETRY.enable()``.

Contract shared with the producer's ``_flush_timings``: telemetry must
never raise into a hot path.  Mutators swallow their own failures; only
the explicit exporters propagate I/O errors.

Cross-worker story: each worker flushes ``snapshot()`` (metrics) and
``drain_spans()`` (new span records) through the storage channel
(``DocumentStorage.record_metrics`` / ``record_spans``) every producer
round; ``orion-tpu info`` merges the snapshots with
:func:`merge_snapshots`, and ``orion-tpu trace`` merges every worker's
spans into one Chrome trace (span timestamps are wall-anchored monotonic
readings, so processes line up on a shared timeline).

Distributed tracing: a :class:`TraceContext` (128-bit ``trace_id``, 64-bit
``span_id``, ``sampled`` flag) rides a thread-local ambient slot.  With
telemetry enabled, a ``with``-managed span minted under an ambient context
becomes a CHILD of it (fresh ``span_id``, same ``trace_id``) and installs
itself as the ambient for its body, so nesting builds a real tree; span
records carry ``trace_id``/``span_id``/``parent_span_id``.  The wire
drivers (``storage/netdb.py``, ``serve/client.py``) inject the ambient
context as an optional ``ctx`` field in their request envelopes and the
servers adopt it as the parent of their own spans — pre-upgrade peers
simply ignore the extra key, so the field is wire-compatible in both
directions.  :func:`chrome_trace_events` turns the cross-process
parent/link edges into Perfetto flow events (``s``/``f`` phases), so the
merged trace draws arrows across process tracks.
"""

import json
import os
import threading
import time
import weakref

# Annotated-cell hooks for the runtime concurrency sanitizer
# (orion-tpu tsan): one attribute check when disabled, constant-string
# args — the same cost discipline the registry itself keeps.
from orion_tpu.analysis.sanitizer import TSAN

_ENABLE_VALUES = ("1", "on", "true", "yes")

#: Histogram shape: bucket ``i`` counts durations in ``[2**(i-1), 2**i)``
#: microseconds (bucket 0 is < 1 µs).  48 buckets reach ~1.6 days — far
#: past any single operation this framework times.  FIXED across versions:
#: merged snapshots sum buckets elementwise, so every writer must agree.
N_BUCKETS = 48

DEFAULT_SPAN_CAPACITY = 4096


# --- distributed trace context ----------------------------------------------
class TraceContext:
    """One hop of a distributed trace: ``trace_id`` names the end-to-end
    request (128-bit hex), ``span_id`` the CURRENT span within it (64-bit
    hex), ``sampled`` whether downstream hops should record at all.

    Immutable by convention: crossing into a new span mints a :meth:`child`
    (same trace, fresh span id) rather than mutating in place, so a context
    captured into a wire payload or a buffered span entry stays valid."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id=None, span_id=None, sampled=True):
        self.trace_id = trace_id or os.urandom(16).hex()
        self.span_id = span_id or os.urandom(8).hex()
        self.sampled = bool(sampled)

    def child(self):
        """Same trace, fresh span id — the context a nested span runs as."""
        return TraceContext(self.trace_id, os.urandom(8).hex(), self.sampled)

    def to_wire(self):
        """The optional ``ctx`` field of a wire envelope.  Peers that
        predate distributed tracing ignore unknown top-level keys, so
        injecting this is compatible in both directions."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": self.sampled,
        }

    @staticmethod
    def from_wire(payload):
        """Adopt a wire ``ctx`` field; tolerant — anything malformed (or
        absent) yields None so a hostile/buggy peer can never break the
        server's dispatch path."""
        if not isinstance(payload, dict):
            return None
        trace_id = payload.get("trace_id")
        span_id = payload.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        return TraceContext(trace_id, span_id, bool(payload.get("sampled", True)))


_AMBIENT = threading.local()


def current_trace_context():
    """This thread's ambient :class:`TraceContext`, or None."""
    return getattr(_AMBIENT, "ctx", None)


def set_trace_context(ctx):
    """Install ``ctx`` (or None) as the ambient context; returns the
    previous one so callers can restore it."""
    prev = getattr(_AMBIENT, "ctx", None)
    _AMBIENT.ctx = ctx
    return prev


class trace_scope:
    """``with trace_scope(ctx):`` — adopt an explicit context (e.g. one
    decoded off the wire) for a block, restoring the previous ambient on
    exit.  ``ctx=None`` is a no-op scope."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        if self._ctx is not None:
            self._prev = set_trace_context(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        if self._ctx is not None:
            set_trace_context(self._prev)
        return False


def _bucket_of(seconds):
    """Index of the log2-µs bucket holding ``seconds``."""
    micros = int(seconds * 1e6)
    if micros <= 0:
        return 0
    return min(micros.bit_length(), N_BUCKETS - 1)


def bucket_upper_seconds(index):
    """Upper bound (seconds) of bucket ``index`` — what percentile queries
    report (conservative: the true sample is at most this)."""
    return float(2**index) / 1e6


class _NullSpan:
    """The disabled-path span: ONE shared instance, allocation-free."""

    __slots__ = ()

    #: Same surface as _Span: a caller that checked ``enabled`` and then
    #: raced a concurrent disable() gets this singleton from span() — its
    #: ``.ctx`` read must degrade to "untraced", never AttributeError.
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """An enabled span: records itself into the registry on exit.

    Trace threading: a ``root=True`` span mints a FRESH :class:`TraceContext`
    (a new distributed trace — the producer round); otherwise, when an
    ambient sampled context exists, the span runs as its child and installs
    itself as the ambient for the body, so nested spans (and wire
    injections inside the body) parent here."""

    __slots__ = ("_telemetry", "name", "args", "_t0", "_root", "_ctx", "_prev")

    def __init__(self, telemetry, name, args, root=False):
        self._telemetry = telemetry
        self.name = name
        self.args = args
        self._root = root
        self._t0 = None
        self._ctx = None
        self._prev = None

    @property
    def ctx(self):
        """This span's own :class:`TraceContext` (None when untraced)."""
        return self._ctx

    def __enter__(self):
        self._t0 = time.perf_counter()
        prev = current_trace_context()
        if self._root:
            self._ctx = TraceContext()
        elif prev is not None and prev.sampled:
            self._ctx = prev.child()
        if self._ctx is not None:
            self._prev = set_trace_context(self._ctx)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._ctx is not None:
            set_trace_context(self._prev)
        self._telemetry.record_span(
            self.name,
            start=self._t0,
            args=self.args,
            span_ctx=self._ctx,
            # A root span STARTS its trace: the enclosing ambient (an
            # embedder's unrelated trace) must not become its parent, or
            # the record's parent_span_id points into a foreign trace and
            # attribution finds no root.
            parent_ctx=None if self._root else self._prev,
        )
        return False


class Telemetry:
    """Process-wide counters/gauges/histograms + span ring buffer.

    Thread-safe: one registry lock guards every mutation.  Recording rates
    are per-operation (a handful per producer round), so lock contention is
    not a concern — the DISABLED path is the one that must stay free, and
    it never touches the lock.
    """

    def __init__(self, enabled=None, span_capacity=None):
        if enabled is None:
            enabled = (
                os.environ.get("ORION_TPU_TELEMETRY", "").strip().lower()
                in _ENABLE_VALUES
            )
        if span_capacity is None:
            try:
                span_capacity = int(
                    os.environ.get("ORION_TPU_TELEMETRY_SPANS", "")
                    or DEFAULT_SPAN_CAPACITY
                )
            except ValueError:
                span_capacity = DEFAULT_SPAN_CAPACITY
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        # name -> [buckets list, count, sum, min, max]
        self._histograms = {}
        # name -> list of (weakref, attr): external monotonic counters
        # (SQLiteDB.txn_count, NetworkDB.wire_requests, ...) sampled at
        # snapshot time — zero hot-path cost for the owning backend.
        self._external = {}
        # Preallocated span ring: slot i%capacity holds span seq i.
        self._capacity = max(int(span_capacity), 8)
        self._ring = [None] * self._capacity
        self._seq = 0
        self._drained = 0
        # Wall anchor: ts_wall = _anchor + perf_counter reading.  Spans use
        # the monotonic clock for start/duration; the anchor puts every
        # process on one comparable wall timeline at export/merge time.
        self._anchor = time.time() - time.perf_counter()

    # --- toggling -----------------------------------------------------------
    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    # --- metrics ------------------------------------------------------------
    def count(self, name, n=1):
        """Increment counter ``name`` by ``n``."""
        if not self.enabled:
            return
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def counter_value(self, name, default=0):
        """Current value of counter ``name`` (``default`` when never
        incremented).  Reader for in-process assertions — the
        boundary-crossing tests and ``bench.py --smoke`` check
        ``jax.retraces``/``jax.prewarms`` deltas through this."""
        with self._lock:
            TSAN.read("Telemetry._metrics", self)
            return self._counters.get(name, default)

    def set_gauge(self, name, value):
        """Set gauge ``name`` to ``value`` (last write wins)."""
        if not self.enabled:
            return
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            self._gauges[name] = float(value)

    def gauge_value(self, name, default=None):
        """Current value of gauge ``name`` (``default`` when never set).
        In-process reader, companion to :meth:`counter_value` — the
        producer stamps the device-memory gauge into each round's health
        record through this, so the doctor's trend rules get a stored
        time series out of a last-write-wins gauge."""
        with self._lock:
            TSAN.read("Telemetry._metrics", self)
            return self._gauges.get(name, default)

    def observe(self, name, seconds):
        """Record one duration sample into histogram ``name``."""
        if not self.enabled:
            return
        seconds = float(seconds)
        with self._lock:
            self._observe_locked(name, seconds)

    def _observe_locked(self, name, seconds):
        """THE histogram update — callers hold the registry lock.  Shared
        by observe() and record_span() so the two sample sources can never
        drift apart."""
        TSAN.write("Telemetry._metrics", self)
        hist = self._histograms.get(name)
        if hist is None:
            hist = [[0] * N_BUCKETS, 0, 0.0, seconds, seconds]
            self._histograms[name] = hist
        hist[0][_bucket_of(seconds)] += 1
        hist[1] += 1
        hist[2] += seconds
        hist[3] = min(hist[3], seconds)
        hist[4] = max(hist[4], seconds)

    def register_external_counter(self, name, obj, attr):
        """Expose ``obj.attr`` (a monotonic int the owner already maintains,
        e.g. ``SQLiteDB.txn_count``) as counter ``name``.  Sampled lazily at
        snapshot time; held by weakref so registration never extends the
        owner's lifetime.  Multiple registrations under one name sum —
        but re-registering the SAME object+attr is a no-op, so callers
        that re-run their registration loop (the sharded router after a
        live topology change) don't double-count."""
        try:
            ref = weakref.ref(obj)
        except TypeError:  # pragma: no cover - exotic objects without weakref
            return
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            entries = self._external.setdefault(name, [])
            for existing_ref, existing_attr in entries:
                if existing_ref() is obj and existing_attr == attr:
                    return
            entries.append((ref, attr))

    def unregister_external_counter(self, name, obj):
        """Drop ``obj``'s registration under ``name`` (other objects'
        registrations under the same name stay).  The sharded router uses
        this when a live topology change REINDEXES a surviving shard —
        its counters move to the new ``s{i}`` name and must stop
        exporting under the old one."""
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            entries = self._external.get(name)
            if not entries:
                return
            kept = [e for e in entries if e[0]() is not obj]
            if kept:
                self._external[name] = kept
            else:
                self._external.pop(name, None)

    def _external_counts(self):
        out = {}
        with self._lock:
            TSAN.write("Telemetry._metrics", self)  # prunes dead registrations
            for name, entries in list(self._external.items()):
                live = [(ref, attr) for ref, attr in entries if ref() is not None]
                if not live:
                    del self._external[name]
                    continue
                self._external[name] = live
                total = 0
                for ref, attr in live:
                    owner = ref()
                    if owner is not None:
                        try:
                            total += int(getattr(owner, attr, 0))
                        except Exception:  # pragma: no cover - hostile attr
                            pass
                out[name] = total
        return out

    # --- spans --------------------------------------------------------------
    def span(self, name, args=None, root=False):
        """Context manager timing a block.  Disabled: the shared no-op
        singleton (no allocation, no clock read).  Enabled: records a span
        AND a duration sample into the histogram of the same name.
        ``root=True`` starts a NEW distributed trace for the body (the
        producer-round entry point); otherwise the span becomes a child of
        any ambient :class:`TraceContext`."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args, root=root)

    def record_span(
        self,
        name,
        start=None,
        duration=None,
        args=None,
        histogram=True,
        span_ctx=None,
        parent_ctx=None,
        links=None,
        track=None,
    ):
        """Record one finished span explicitly.

        ``start``/``duration`` are ``time.perf_counter()`` readings/deltas;
        give either or both (a missing start is back-computed from now, a
        missing duration runs to now).  Callers that already measured a
        phase (the producer's ``_record_timing``) route through here so the
        span and its histogram sample come from the same clock reading.
        ``histogram=False`` records the span only — for call sites that
        feed a differently-keyed histogram themselves (the storage layer's
        per-backend op histograms) and must not double-book the sample.

        Trace stamping: ``span_ctx`` is this span's OWN identity (its
        ``span_id``), ``parent_ctx`` its parent; pass only ``parent_ctx``
        (the adopting-server case — a context decoded off the wire) and a
        fresh ``span_id`` is minted.  With neither, the thread's ambient
        context (if sampled) parents the record.  ``links`` is a list of
        contexts/{trace_id, span_id} dicts joined non-hierarchically (the
        gateway's coalesced dispatch links every stacked tenant's request
        context).  ``track`` overrides the record's worker/track label so
        in-process servers (gateway, loopback netdb) render as their own
        Perfetto track."""
        if not self.enabled:
            return
        try:
            record, duration = self._build_span_record(
                name,
                start,
                duration,
                args,
                time.perf_counter(),
                span_ctx=span_ctx,
                parent_ctx=parent_ctx,
                links=links,
                track=track,
            )
            with self._lock:
                TSAN.write("Telemetry._ring", self)
                self._ring[self._seq % self._capacity] = record
                self._seq += 1
                if histogram:
                    self._observe_locked(name, duration)
        except Exception:  # pragma: no cover - must never raise into hot path
            pass

    def _build_span_record(
        self,
        name,
        start,
        duration,
        args,
        now,
        span_ctx=None,
        parent_ctx=None,
        links=None,
        track=None,
    ):
        """THE span-record builder — shared by :meth:`record_span` and
        :meth:`record_spans_batch` so the None-start back-computation and
        the record schema cannot drift between the per-call and batched
        paths.  Returns ``(record, duration_seconds)``."""
        if start is None:
            duration = float(duration or 0.0)
            start = now - duration
        elif duration is None:
            duration = now - start
        record = {
            "name": name,
            "ts": self._anchor + start,
            "dur": float(duration),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            # Clamp long string values (compiler-plane signatures are the
            # worst case: every static of a plan on one line) — the ring
            # holds a bounded record count, not bounded bytes, and a
            # pathological arg would bloat every export of the window.
            record["args"] = {
                k: (v[:253] + "..." if isinstance(v, str) and len(v) > 256 else v)
                for k, v in args.items()
            }
        if span_ctx is None and parent_ctx is None:
            ambient = current_trace_context()
            if ambient is not None and ambient.sampled:
                parent_ctx = ambient
        if span_ctx is not None:
            record["trace_id"] = span_ctx.trace_id
            record["span_id"] = span_ctx.span_id
            if parent_ctx is not None:
                record["parent_span_id"] = parent_ctx.span_id
        elif parent_ctx is not None and parent_ctx.sampled:
            record["trace_id"] = parent_ctx.trace_id
            record["span_id"] = os.urandom(8).hex()
            record["parent_span_id"] = parent_ctx.span_id
        if links:
            record["links"] = [
                {"trace_id": link.trace_id, "span_id": link.span_id}
                if isinstance(link, TraceContext)
                else dict(link)
                for link in links
            ]
        if track is not None:
            record["worker"] = track
        return record, float(duration)

    def record_spans_batch(self, entries):
        """Record many finished spans under ONE lock acquisition.

        ``entries`` is ``[(name, start, duration, args), ...]`` with the
        same semantics as :meth:`record_span` (``start`` a perf_counter
        reading; a None start is back-computed from ``duration`` against
        the batch's shared "now").  An optional fifth element carries the
        :class:`TraceContext` that was ambient when the sample was taken
        (``parent_ctx`` semantics — buffering must not re-read the ambient
        at flush time, which may belong to a later round).  The producer
        buffers its per-sample spans across a round and flushes them here —
        per-sample ``record_span`` calls each paid a lock round-trip and a
        clock read inside the hot loop (see ``bench.py``'s
        ``telemetry_us_saved``)."""
        if not self.enabled or not entries:
            return
        try:
            now = time.perf_counter()
            records = [
                (entry[0],)
                + self._build_span_record(
                    entry[0],
                    entry[1],
                    entry[2],
                    entry[3],
                    now,
                    parent_ctx=entry[4] if len(entry) > 4 else None,
                )
                for entry in entries
            ]
            with self._lock:
                TSAN.write("Telemetry._ring", self)
                for name, record, duration in records:
                    self._ring[self._seq % self._capacity] = record
                    self._seq += 1
                    self._observe_locked(name, duration)
        except Exception:  # pragma: no cover - must never raise into hot path
            pass

    def iter_spans(self):
        """Every span currently in the ring, oldest first (wraparound has
        dropped anything older than ``capacity`` records)."""
        with self._lock:
            TSAN.read("Telemetry._ring", self)
            start = max(0, self._seq - self._capacity)
            return [self._ring[i % self._capacity] for i in range(start, self._seq)]

    def drain_spans(self):
        """Spans recorded since the last drain (each span is returned
        exactly once — the worker flush channel).  Wraparound between
        drains loses the overwritten oldest records, by design."""
        with self._lock:
            TSAN.write("Telemetry._ring", self)  # advances the drain cursor
            start = max(self._drained, self._seq - self._capacity)
            out = [self._ring[i % self._capacity] for i in range(start, self._seq)]
            self._drained = self._seq
            return out

    # --- snapshots / merging ------------------------------------------------
    def snapshot(self):
        """One mergeable metrics snapshot: counters (external ones sampled
        now), gauges, histograms.  This is the document a worker flushes
        through ``DocumentStorage.record_metrics`` every round."""
        external = self._external_counts()
        with self._lock:
            TSAN.read("Telemetry._metrics", self)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = {
                name: {
                    "buckets": list(hist[0]),
                    "count": hist[1],
                    "sum": hist[2],
                    "min": hist[3],
                    "max": hist[4],
                }
                for name, hist in self._histograms.items()
            }
        for name, value in external.items():
            counters[name] = counters.get(name, 0) + value
        return {"counters": counters, "gauges": gauges, "histograms": histograms}

    def reset(self):
        """Drop every metric and span, INCLUDING external-counter
        registrations (test/bench isolation: a still-alive backend's
        monotonic txn/wire totals must not bleed into a fresh measurement;
        a backend created after the reset re-registers on construction)."""
        with self._lock:
            TSAN.write("Telemetry._metrics", self)
            TSAN.write("Telemetry._ring", self)
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._external.clear()
            self._ring = [None] * self._capacity
            self._seq = 0
            self._drained = 0

    # --- exporters ----------------------------------------------------------
    def export_jsonl(self, path):
        """One JSON object per line: every span in the ring, then one
        ``{"type": "metrics", ...}`` snapshot line."""
        spans = self.iter_spans()
        with open(path, "w") as handle:
            for span in spans:
                handle.write(json.dumps({"type": "span", **span}) + "\n")
            handle.write(json.dumps({"type": "metrics", **self.snapshot()}) + "\n")
        return path

    def export_chrome_trace(self, path):
        """Chrome trace-event JSON of the ring (loads in Perfetto)."""
        return write_chrome_trace(path, self.iter_spans())


def histogram_percentile(hist, p):
    """Nearest-rank percentile (seconds) from a snapshot histogram dict —
    the upper bound of the bucket holding the rank, so the report is
    conservative within one 2x bucket."""
    count = int(hist.get("count", 0))
    if count <= 0:
        return 0.0
    rank = max(1, -(-int(p * count) // 100))  # ceil(p/100 * count)
    seen = 0
    for index, n in enumerate(hist.get("buckets", ())):
        seen += n
        if seen >= rank:
            return min(bucket_upper_seconds(index), float(hist.get("max", 0.0)))
    return float(hist.get("max", 0.0))


def merge_snapshots(snapshots):
    """Aggregate worker snapshot docs into one: counters and histogram
    buckets SUM (they are per-worker monotonic totals); gauges merge by
    MAX — they are risk signals (heartbeat lag), and the worker whose
    gauge matters is exactly the stalled one that stopped flushing, so
    freshest-write-wins would mask it behind a healthy worker's ~0.
    Accepts raw ``snapshot()`` dicts or storage docs carrying extra keys
    (``experiment``/``worker``/``time``)."""
    counters = {}
    gauges = {}
    histograms = {}
    for doc in snapshots:
        for name, value in (doc.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(value)
        for name, value in (doc.get("gauges") or {}).items():
            value = float(value)
            gauges[name] = max(gauges[name], value) if name in gauges else value
        for name, hist in (doc.get("histograms") or {}).items():
            merged = histograms.get(name)
            if merged is None:
                histograms[name] = {
                    "buckets": list(hist.get("buckets") or [0] * N_BUCKETS),
                    "count": int(hist.get("count", 0)),
                    "sum": float(hist.get("sum", 0.0)),
                    "min": float(hist.get("min", 0.0)),
                    "max": float(hist.get("max", 0.0)),
                }
                continue
            buckets = hist.get("buckets") or ()
            for index, n in enumerate(buckets):
                if index < len(merged["buckets"]):
                    merged["buckets"][index] += n
            merged["count"] += int(hist.get("count", 0))
            merged["sum"] += float(hist.get("sum", 0.0))
            merged["min"] = min(merged["min"], float(hist.get("min", 0.0)))
            merged["max"] = max(merged["max"], float(hist.get("max", 0.0)))
    return {"counters": counters, "gauges": gauges, "histograms": histograms}


def chrome_trace_events(spans):
    """Span records -> Chrome trace-event dicts (complete 'X' events, µs).

    Spans may come from one process's ring or from the storage channel
    (several workers).  Tracks are keyed by the WORKER identity (host:pid
    when present — a bare OS pid collides across hosts, e.g. two
    containerized workers both running as pid 1), mapped to synthetic
    sequential pids; each track gets a process_name metadata event so
    Perfetto labels the rows.

    Distributed-trace records additionally produce Perfetto FLOW events
    (``s`` start / ``f`` finish pairs, bound by ``id``): one arrow per
    cross-track parent→child edge (a client span whose ``span_id`` a
    server span names as ``parent_span_id``), and one per recorded link
    (the gateway's coalesced dispatch → every stacked tenant's request
    context).  Each flow carries its ``trace_id`` in ``args`` so arrows
    can be grepped back to the request they belong to."""
    events = []
    tracks = {}  # worker label -> synthetic pid
    by_span_id = {}  # span_id -> its X event (for flow binding)
    traced = []  # (span record, X event) pairs carrying trace fields
    for span in spans:
        if not span:
            continue
        label = str(span.get("worker") or f"orion-tpu:{span.get('pid', 0)}")
        if label not in tracks:
            tracks[label] = len(tracks) + 1
        event = {
            "name": str(span.get("name", "?")),
            "cat": str(span.get("name", "?")).split(".", 1)[0],
            "ph": "X",
            "ts": float(span.get("ts", 0.0)) * 1e6,
            "dur": float(span.get("dur", 0.0)) * 1e6,
            "pid": tracks[label],
            "tid": int(span.get("tid", 0)),
        }
        args = span.get("args")
        if args:
            event["args"] = dict(args)
        trace_id = span.get("trace_id")
        if trace_id:
            event.setdefault("args", {})["trace_id"] = trace_id
        events.append(event)
        span_id = span.get("span_id")
        if span_id:
            by_span_id[span_id] = event
        if (trace_id and span.get("parent_span_id")) or span.get("links"):
            traced.append((span, event))
    flow_seq = 0
    for span, event in traced:
        sources = []  # (source event, trace_id the arrow belongs to)
        parent = by_span_id.get(span.get("parent_span_id"))
        # Parent arrows only across tracks: intra-track nesting is already
        # visible as slice containment, and drawing it would bury the
        # cross-process arrows the merge exists to show.
        if parent is not None and parent["pid"] != event["pid"]:
            sources.append((parent, span.get("trace_id")))
        for link in span.get("links") or ():
            target = by_span_id.get((link or {}).get("span_id"))
            if target is not None and target is not parent:
                sources.append((target, (link or {}).get("trace_id")))
        for source, flow_trace in sources:
            flow_seq += 1
            flow = {
                "name": "trace",
                "cat": "flow",
                "id": flow_seq,
                "args": {"trace_id": flow_trace},
            }
            events.append(
                {
                    **flow,
                    "ph": "s",
                    "ts": source["ts"],
                    "pid": source["pid"],
                    "tid": source["tid"],
                }
            )
            events.append(
                {
                    **flow,
                    "ph": "f",
                    "bp": "e",
                    "ts": event["ts"],
                    "pid": event["pid"],
                    "tid": event["tid"],
                }
            )
    for label, pid in tracks.items():
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": label},
            }
        )
    return events


def write_chrome_trace(path, spans):
    """Write ``spans`` as a Chrome trace-event JSON file (Perfetto-ready)."""
    payload = {
        "traceEvents": chrome_trace_events(spans),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as handle:
        json.dump(payload, handle)
    return path


#: THE process-wide registry every subsystem records into.  Enabled state
#: comes from ORION_TPU_TELEMETRY at import; the CLI layers the
#: ``telemetry:`` config key on top (cli/base.py).
TELEMETRY = Telemetry()
