"""Generic tree with traversals and recursive map.

Capability parity: reference `src/orion/core/evc/tree.py` — `TreeNode` with
parent/children management, `map(function, node)` recursive application in
either direction, pre-order and depth-first traversals, `flattened`.
"""


class TreeNode:
    def __init__(self, item, parent=None, children=()):
        self.item = item
        self._parent = None
        self._children = []
        self.set_parent(parent)
        for child in children:
            self.add_children(child)

    @property
    def parent(self):
        return self._parent

    @property
    def children(self):
        return list(self._children)

    def set_parent(self, node):
        if self._parent is node:
            return
        if self._parent is not None:
            self._parent.drop_children(self)
        self._parent = node
        if node is not None and self not in node._children:
            node._children.append(self)

    def add_children(self, *nodes):
        for node in nodes:
            if node._parent is not None and node._parent is not self:
                node._parent.drop_children(node)
            node._parent = self
            if node not in self._children:
                self._children.append(node)

    def drop_children(self, *nodes):
        for node in nodes:
            self._children.remove(node)
            node._parent = None

    @property
    def root(self):
        return self if self._parent is None else self._parent.root

    @property
    def leafs(self):
        if not self._children:
            return [self]
        out = []
        for child in self._children:
            out.extend(child.leafs)
        return out

    def map(self, function, node):
        """Apply ``function(self_item, mapped_neighbor)`` towards ``node``.

        When ``node`` is the parent, mapping ascends (the reference's
        parent-ward map used to adapt trials rootward); when it is a child
        list direction descends.
        """
        if node is None:
            return TreeNode(function(self, None))
        if node is self._parent:
            mapped_parent = node.map(function, node.parent) if node else None
            return TreeNode(function(self, mapped_parent), parent=mapped_parent)
        raise ValueError("map target must be the parent node or None")

    def __iter__(self):
        return PreOrderTraversal(self)

    @property
    def flattened(self):
        return [node.item for node in self]

    def __repr__(self):
        return f"TreeNode({self.item!r}, children={len(self._children)})"


class PreOrderTraversal:
    """Root, then each subtree left-to-right."""

    def __init__(self, node):
        self.stack = [node]

    def __iter__(self):
        return self

    def __next__(self):
        if not self.stack:
            raise StopIteration
        node = self.stack.pop(0)
        self.stack = node.children + self.stack
        return node


class DepthFirstTraversal:
    """Children before parents (post-order)."""

    def __init__(self, node):
        self.order = []
        self._build(node)

    def _build(self, node):
        for child in node.children:
            self._build(child)
        self.order.append(node)

    def __iter__(self):
        return iter(self.order)
