"""Conflict detection and resolution between experiment configurations.

Capability parity: reference `src/orion/core/evc/conflicts.py` (1638 LoC) —
when re-running an experiment whose configuration changed, every difference
becomes a typed Conflict; each conflict resolves (automatically, via cmdline
markers ``+ - >``, or interactively) into a Resolution that may carry a trial
Adapter.  Conflict classes: NewDimension (`conflicts.py:513`),
ChangedDimension (`:650`), MissingDimension (remove or rename, `:727`),
Algorithm (`:1025`), Code (`:1083`), CommandLine (`:1202`), ScriptConfig
(`:1334`), ExperimentName (version bump or rename, `:1463`).
"""

import logging

from orion_tpu.evc.adapters import (
    AlgorithmChange,
    CodeChange,
    CommandLineChange,
    DimensionAddition,
    DimensionDeletion,
    DimensionPriorChange,
    DimensionRenaming,
    ScriptConfigChange,
)
from orion_tpu.space.dims import NotSet
from orion_tpu.space.dsl import build_dimension, split_marker

log = logging.getLogger(__name__)


class Resolution:
    def __init__(self, conflict, adapter=None, **info):
        self.conflict = conflict
        self.adapter = adapter
        self.info = info

    def __repr__(self):
        return f"Resolution({type(self.conflict).__name__}, {self.info})"


class Conflict:
    """One difference between the parent and the branching configuration."""

    def __init__(self):
        self.resolution = None

    @property
    def is_resolved(self):
        return self.resolution is not None

    def try_resolve(self, **kwargs):
        raise NotImplementedError

    def diff(self):
        raise NotImplementedError

    def _resolve(self, adapter=None, **info):
        self.resolution = Resolution(self, adapter=adapter, **info)
        return self.resolution


class NewDimensionConflict(Conflict):
    """A dimension exists in the new config but not the parent."""

    def __init__(self, name, prior_expr):
        super().__init__()
        self.name = name
        marker, clean = split_marker(prior_expr)
        self.marked_add = marker == "+"
        self.prior_expr = clean
        self.dimension = build_dimension(name, clean)

    def try_resolve(self, default_value=NotSet, **_kwargs):
        if default_value is NotSet:
            default_value = self.dimension.default_value
        if default_value is NotSet:
            # No default -> parent trials cannot be mapped into the child
            # (None params would corrupt model warm-starts); refuse so the
            # user supplies `default_value=` in the prior expression.
            raise ValueError(
                f"new dimension {self.name!r} needs a default_value to branch"
            )
        return self._resolve(
            adapter=DimensionAddition(self.name, default_value),
            default_value=default_value,
        )

    def diff(self):
        return f"+ {self.name}~{self.prior_expr}"


class ChangedDimensionConflict(Conflict):
    """Same dimension name, different prior expression."""

    def __init__(self, name, old_expr, new_expr):
        super().__init__()
        self.name = name
        self.old_expr = old_expr
        _, self.new_expr = split_marker(new_expr)

    def try_resolve(self, **_kwargs):
        return self._resolve(
            adapter=DimensionPriorChange(self.name, self.old_expr, self.new_expr)
        )

    def diff(self):
        return f"~ {self.name}: {self.old_expr} -> {self.new_expr}"


class MissingDimensionConflict(Conflict):
    """A parent dimension is absent from the new config: removed or renamed."""

    def __init__(self, name, old_expr, rename_to=None, marked_remove=False):
        super().__init__()
        self.name = name
        self.old_expr = old_expr
        self.rename_to = rename_to
        self.marked_remove = marked_remove

    def try_resolve(self, rename_to=None, default_value=NotSet, **_kwargs):
        rename_to = rename_to or self.rename_to
        if rename_to:
            return self._resolve(
                adapter=DimensionRenaming(self.name, rename_to), rename_to=rename_to
            )
        if default_value is NotSet:
            dim = build_dimension(self.name, self.old_expr)
            default_value = (
                dim.default_value if dim.default_value is not NotSet else None
            )
        return self._resolve(
            adapter=DimensionDeletion(self.name, default_value),
            default_value=default_value,
        )

    def diff(self):
        if self.rename_to:
            return f"> {self.name} -> {self.rename_to}"
        return f"- {self.name}~{self.old_expr}"


class AlgorithmConflict(Conflict):
    def __init__(self, old_config, new_config):
        super().__init__()
        self.old_config = old_config
        self.new_config = new_config

    def try_resolve(self, **_kwargs):
        return self._resolve(adapter=AlgorithmChange())

    def diff(self):
        return f"algorithm: {self.old_config} -> {self.new_config}"


class _ChangeConflict(Conflict):
    adapter_cls = None
    what = ""

    def __init__(self, old, new):
        super().__init__()
        self.old = old
        self.new = new

    def try_resolve(self, change_type="unsure", **_kwargs):
        return self._resolve(
            adapter=self.adapter_cls(change_type), change_type=change_type
        )

    def diff(self):
        return f"{self.what}: {self.old!r} -> {self.new!r}"


class CodeConflict(_ChangeConflict):
    adapter_cls = CodeChange
    what = "code"


class CommandLineConflict(_ChangeConflict):
    adapter_cls = CommandLineChange
    what = "commandline"


class ScriptConfigConflict(_ChangeConflict):
    adapter_cls = ScriptConfigChange
    what = "script config"


class ExperimentNameConflict(Conflict):
    """Branching always needs a new identity: version bump or new name."""

    def __init__(self, name, version):
        super().__init__()
        self.name = name
        self.version = version

    def try_resolve(self, branch_to=None, **_kwargs):
        if branch_to and branch_to != self.name:
            return self._resolve(name=branch_to, version=1)
        return self._resolve(name=self.name, version=self.version + 1)

    def diff(self):
        return f"experiment: {self.name} v{self.version} -> branch"


class Conflicts:
    """Container with resolution bookkeeping (reference `conflicts.py:104-274`)."""

    def __init__(self, conflicts=()):
        self.conflicts = list(conflicts)

    def add(self, conflict):
        self.conflicts.append(conflict)

    def get(self, conflict_types=None):
        if conflict_types is None:
            return list(self.conflicts)
        return [c for c in self.conflicts if isinstance(c, tuple(conflict_types))]

    def get_remaining(self):
        return [c for c in self.conflicts if not c.is_resolved]

    def get_resolved(self):
        return [c for c in self.conflicts if c.is_resolved]

    @property
    def are_resolved(self):
        return not self.get_remaining()

    def try_resolve_all(self, **kwargs):
        for conflict in self.get_remaining():
            try:
                conflict.try_resolve(**kwargs)
            except Exception as exc:  # pragma: no cover - defensive
                log.warning("Could not auto-resolve %r: %s", conflict, exc)

    def get_adapters(self):
        out = []
        for conflict in self.get_resolved():
            if conflict.resolution.adapter is not None:
                out.append(conflict.resolution.adapter)
        return out

    def diffs(self):
        return [c.diff() for c in self.conflicts]


def detect_conflicts(old_config, new_config):
    """Compare parent/new experiment configs (reference `conflicts.py:94-101`).

    ``old_config`` is the stored configuration (clean priors); ``new_config``
    may carry branching markers in its prior expressions.
    """
    conflicts = Conflicts()
    old_priors = dict(old_config.get("priors", {}))
    raw_new = dict(new_config.get("priors", {}))

    renames = {}  # old_name -> new_name, from `old~>new` markers
    removed_marks = set()
    new_priors = {}
    for name, expr in raw_new.items():
        marker, clean = split_marker(expr)
        if marker == ">":
            renames[name] = clean.strip()
            continue
        if clean.strip() == "" and marker == "-":
            removed_marks.add(name)
            continue
        new_priors[name] = expr

    for name, expr in new_priors.items():
        _, clean = split_marker(expr)
        if name not in old_priors:
            if name not in renames.values():
                conflicts.add(NewDimensionConflict(name, expr))
        elif _normalized(old_priors[name]) != _normalized(clean):
            conflicts.add(ChangedDimensionConflict(name, old_priors[name], expr))

    for name, old_expr in old_priors.items():
        if name in new_priors:
            continue
        if name in renames:
            target = renames[name]
            conflict = MissingDimensionConflict(name, old_expr, rename_to=target)
            conflicts.add(conflict)
            # The renamed target may also change its prior.
            if target in new_priors:
                _, target_expr = split_marker(new_priors[target])
                if _normalized(old_expr) != _normalized(target_expr):
                    conflicts.add(
                        ChangedDimensionConflict(target, old_expr, target_expr)
                    )
        else:
            conflicts.add(
                MissingDimensionConflict(
                    name, old_expr, marked_remove=name in removed_marks
                )
            )

    old_algo = old_config.get("algorithms")
    new_algo = new_config.get("algorithms")
    if new_algo is not None and old_algo is not None and old_algo != new_algo:
        conflicts.add(AlgorithmConflict(old_algo, new_algo))

    old_meta = old_config.get("metadata", {})
    new_meta = new_config.get("metadata", {})
    old_vcs = old_meta.get("vcs") or {}
    new_vcs = new_meta.get("vcs") or {}
    # Code identity = (HEAD sha, uncommitted-diff sha): two dirty checkouts at
    # the same HEAD with different edits are different code (reference
    # `resolve_config.py:270-282`, `conflicts.py:1083`).
    old_sig = (old_vcs.get("HEAD_sha"), old_vcs.get("diff_sha"))
    new_sig = (new_vcs.get("HEAD_sha"), new_vcs.get("diff_sha"))
    if any(old_sig) and any(new_sig) and old_sig != new_sig:
        conflicts.add(CodeConflict(old_sig, new_sig))

    old_cli = _non_prior_args(old_meta.get("user_args", []))
    new_cli = _non_prior_args(new_meta.get("user_args", []))
    if new_meta.get("user_args") and old_cli != new_cli:
        conflicts.add(CommandLineConflict(old_cli, new_cli))

    old_conf = old_meta.get("script_config_hash")
    new_conf = new_meta.get("script_config_hash")
    if old_conf and new_conf and old_conf != new_conf:
        conflicts.add(ScriptConfigConflict(old_conf, new_conf))

    if conflicts.conflicts:
        conflicts.add(
            ExperimentNameConflict(
                old_config["name"], old_config.get("version", 1)
            )
        )
    return conflicts


def _normalized(expr):
    return "".join(str(expr).split())


def _non_prior_args(user_args):
    return [a for a in user_args if "~" not in a]
