"""Branch builder: drive conflict resolution and create the child experiment.

Capability parity: reference `src/orion/core/io/experiment_branch_builder.py`
+ `evc_builder.py` — automatic resolution by default (markers honored),
interactive prompt with ``--manual-resolution``, child registered with
``refers = {root_id, parent_id, adapter}`` and a DuplicateKeyError ->
RaceCondition retry (a concurrent worker may branch first; reference
`experiment.py:516-517`).
"""

import logging
import time

from orion_tpu.evc.conflicts import ExperimentNameConflict, detect_conflicts
from orion_tpu.evc.adapters import CompositeAdapter
from orion_tpu.space.dsl import split_marker
from orion_tpu.utils.exceptions import DuplicateKeyError, RaceCondition

log = logging.getLogger(__name__)


class ExperimentBranchBuilder:
    """Resolution API used programmatically and by the interactive prompt
    (reference `experiment_branch_builder.py:62-80` + per-conflict methods)."""

    def __init__(self, conflicts, manual_resolution=False, branch_to=None):
        self.conflicts = conflicts
        self.manual_resolution = manual_resolution
        self.branch_to = branch_to

    # --- per-conflict-type resolution API -----------------------------------
    def change_experiment_name(self, name):
        for conflict in self.conflicts.get([ExperimentNameConflict]):
            conflict.try_resolve(branch_to=name)

    def add_dimension(self, name, default_value=None):
        from orion_tpu.evc.conflicts import NewDimensionConflict
        from orion_tpu.space.dims import NotSet

        for conflict in self.conflicts.get([NewDimensionConflict]):
            if conflict.name == name:
                conflict.try_resolve(
                    default_value=default_value if default_value is not None else NotSet
                )

    def remove_dimension(self, name, default_value=None):
        from orion_tpu.evc.conflicts import MissingDimensionConflict
        from orion_tpu.space.dims import NotSet

        for conflict in self.conflicts.get([MissingDimensionConflict]):
            if conflict.name == name:
                conflict.try_resolve(
                    default_value=default_value if default_value is not None else NotSet
                )

    def rename_dimension(self, old_name, new_name):
        from orion_tpu.evc.conflicts import MissingDimensionConflict

        for conflict in self.conflicts.get([MissingDimensionConflict]):
            if conflict.name == old_name:
                conflict.try_resolve(rename_to=new_name)

    def set_code_change_type(self, change_type):
        from orion_tpu.evc.conflicts import CodeConflict

        for conflict in self.conflicts.get([CodeConflict]):
            conflict.try_resolve(change_type=change_type)

    def set_cli_change_type(self, change_type):
        from orion_tpu.evc.conflicts import CommandLineConflict

        for conflict in self.conflicts.get([CommandLineConflict]):
            conflict.try_resolve(change_type=change_type)

    def set_script_config_change_type(self, change_type):
        from orion_tpu.evc.conflicts import ScriptConfigConflict

        for conflict in self.conflicts.get([ScriptConfigConflict]):
            conflict.try_resolve(change_type=change_type)

    def reset(self):
        for conflict in self.conflicts.conflicts:
            conflict.resolution = None

    # --- driving -------------------------------------------------------------
    def resolve(self):
        if self.branch_to:
            self.change_experiment_name(self.branch_to)
        if self.manual_resolution:
            # The user's decisions (including leaving conflicts unresolved
            # via `abort`) are final — no automatic pass afterwards.
            from orion_tpu.evc.branching_prompt import BranchingPrompt

            BranchingPrompt(self).cmdloop()
        else:
            self.conflicts.try_resolve_all()
        return self.conflicts

    def create_adapters(self):
        return CompositeAdapter(*self.conflicts.get_adapters())


def branch_experiment(storage, parent, new_priors, branch_config=None, **config):
    """Create a child experiment from ``parent`` with the changed config."""
    from orion_tpu.core.experiment import Experiment

    branch_config = dict(branch_config or {})
    old_config = parent.configuration()
    new_config = {
        "priors": dict(new_priors),
        "algorithms": config.get("algorithms"),
        "metadata": config.get("metadata", {}),
        "name": parent.name,
    }
    conflicts = detect_conflicts(old_config, new_config)
    if not conflicts.conflicts:
        return parent

    builder = ExperimentBranchBuilder(
        conflicts,
        manual_resolution=branch_config.get("manual_resolution", False),
        branch_to=branch_config.get("branch_to"),
    )
    builder.resolve()
    remaining = conflicts.get_remaining()
    if remaining:
        raise ValueError(
            "unresolved branching conflicts: "
            + "; ".join(c.diff() for c in remaining)
            + " — add branching markers (+ - >) or default_value=..., or use "
            "--manual-resolution"
        )

    name_res = next(
        (
            c.resolution
            for c in conflicts.get([ExperimentNameConflict])
            if c.is_resolved
        ),
        None,
    )
    child_name = name_res.info["name"] if name_res else parent.name
    child_version = name_res.info["version"] if name_res else parent.version + 1

    adapter = builder.create_adapters()
    old_priors = dict(old_config.get("priors", {}))
    clean_priors = {}
    renamed_targets = {}
    for name, expr in new_priors.items():
        marker, clean = split_marker(expr)
        if marker == ">":
            # `/old~>/new`: the renamed dimension keeps its old prior unless
            # the new name is also given its own prior expression.
            renamed_targets[clean.strip()] = old_priors.get(name)
            continue
        if marker == "-" and not clean.strip():
            continue
        clean_priors[name] = clean
    for target, old_expr in renamed_targets.items():
        if target not in clean_priors and old_expr is not None:
            clean_priors[target] = old_expr
    if not clean_priors:
        raise ValueError(
            "branching produced an empty search space — a rename-only config "
            "must still leave at least one dimension"
        )

    # A branch created without a fresh command line (argless resume that hit
    # a CodeConflict) must inherit the parent's command metadata or the child
    # could never be run.
    new_meta = dict(config.get("metadata") or {})
    if not new_meta.get("user_args"):
        parent_meta = parent.metadata or {}
        for key in ("user_args", "parser_state", "user_script"):
            if parent_meta.get(key) is not None:
                new_meta[key] = parent_meta[key]
    child_config = {
        "name": child_name,
        "version": child_version,
        "priors": clean_priors,
        "metadata": {"timestamp": time.time(), **new_meta},
        "max_trials": config.get("max_trials", parent.max_trials),
        "max_broken": config.get("max_broken", parent.max_broken),
        "pool_size": config.get("pool_size", parent.pool_size),
        "working_dir": config.get("working_dir", parent.working_dir),
        "algorithms": config.get("algorithms") or parent.algo_config,
        "strategy": config.get("strategy") or parent.strategy_config,
        "refers": {
            "root_id": parent.refers.get("root_id") or parent.id,
            "parent_id": parent.id,
            "adapter": adapter.to_dict(),
        },
    }
    from orion_tpu.core.experiment import experiment_id

    child_user = child_config["metadata"].get("user")
    child_config["_id"] = experiment_id(child_name, child_version, child_user)
    for attempt in range(2):
        try:
            created = storage.create_experiment(child_config)
            log.info(
                "Branched experiment %s v%s -> %s v%s",
                parent.name, parent.version, child_name, child_version,
            )
            return Experiment(storage, created)
        except DuplicateKeyError:
            # Concurrent branch to the same (name, version): bump and retry.
            child_version += 1
            child_config["version"] = child_version
            child_config["_id"] = experiment_id(child_name, child_version, child_user)
    raise RaceCondition(
        f"lost branching race for experiment {child_name!r} twice"
    )
