"""Trial adapters: bidirectional compatibility between parent/child
experiments.

Capability parity: reference `src/orion/core/evc/adapters.py` — one adapter
per resolved conflict; ``forward(trials)`` converts parent-experiment trials
for use in the child, ``backward(trials)`` converts child trials for the
parent (reference `evc/experiment.py:190-226` applies forward on parents and
backward on children); serializable via ``to_dict``/``build_adapter``.
"""

import logging

from orion_tpu.core.trial import Trial
from orion_tpu.space.dsl import build_dimension
from orion_tpu.utils.registry import Registry

log = logging.getLogger(__name__)

adapter_registry = Registry("adapter")

#: Change severities for code/cmdline/config conflicts.
CHANGE_TYPES = ("noeffect", "unsure", "break")


class Adapter:
    """Base adapter; stateless transforms over lists of Trials."""

    def forward(self, trials):
        """Parent trials -> child experiment's space."""
        raise NotImplementedError

    def backward(self, trials):
        """Child trials -> parent experiment's space."""
        raise NotImplementedError

    def to_dict(self):
        return {"of_type": type(self).__name__.lower(), **self._config()}

    def _config(self):
        return {}


def build_adapter(config):
    """Rebuild an adapter from its to_dict form (composites recurse)."""
    config = dict(config)
    of_type = config.pop("of_type")
    if of_type == "compositeadapter":
        return adapter_registry.get(of_type)(*config.get("adapters", []))
    return adapter_registry.get(of_type)(**config)


def _clone_with_params(trial, params):
    return Trial(
        experiment=trial.experiment,
        status=trial.status,
        params=params,
        results=[r.to_dict() for r in trial.results],
        submit_time=trial.submit_time,
        start_time=trial.start_time,
        end_time=trial.end_time,
        heartbeat=trial.heartbeat,
        working_dir=trial.working_dir,
        parents=trial.parents,
    )


@adapter_registry.register("dimensionaddition")
class DimensionAddition(Adapter):
    """Child gained dimension ``name``; parent trials get ``default_value``
    (reference `adapters.py:232`: a parent trial is valid in the child iff
    the new dimension is pinned at its default)."""

    def __init__(self, name, default_value=None):
        self.name = name
        self.default_value = default_value

    def forward(self, trials):
        out = []
        for trial in trials:
            params = dict(trial.params)
            params[self.name] = self.default_value
            out.append(_clone_with_params(trial, params))
        return out

    def backward(self, trials):
        out = []
        for trial in trials:
            if trial.params.get(self.name) == self.default_value:
                params = {k: v for k, v in trial.params.items() if k != self.name}
                out.append(_clone_with_params(trial, params))
        return out

    def _config(self):
        return {"name": self.name, "default_value": self.default_value}


@adapter_registry.register("dimensiondeletion")
class DimensionDeletion(Adapter):
    """Child lost dimension ``name`` — the inverse of DimensionAddition
    (reference `adapters.py:327`)."""

    def __init__(self, name, default_value=None):
        self._inverse = DimensionAddition(name, default_value)

    @property
    def name(self):
        return self._inverse.name

    @property
    def default_value(self):
        return self._inverse.default_value

    def forward(self, trials):
        return self._inverse.backward(trials)

    def backward(self, trials):
        return self._inverse.forward(trials)

    def _config(self):
        return {"name": self.name, "default_value": self.default_value}


@adapter_registry.register("dimensionpriorchange")
class DimensionPriorChange(Adapter):
    """Prior of ``name`` changed; only trials inside the *target* prior's
    support survive the hop (reference `adapters.py:398`)."""

    def __init__(self, name, old_prior, new_prior):
        self.name = name
        self.old_prior = old_prior
        self.new_prior = new_prior
        self._old_dim = build_dimension(name, old_prior)
        self._new_dim = build_dimension(name, new_prior)

    def _filter(self, trials, dim):
        return [t for t in trials if self.name in t.params and t.params[self.name] in dim]

    def forward(self, trials):
        return self._filter(trials, self._new_dim)

    def backward(self, trials):
        return self._filter(trials, self._old_dim)

    def _config(self):
        return {
            "name": self.name,
            "old_prior": self.old_prior,
            "new_prior": self.new_prior,
        }


@adapter_registry.register("dimensionrenaming")
class DimensionRenaming(Adapter):
    """``old_name`` in the parent is ``new_name`` in the child
    (reference `adapters.py:480`)."""

    def __init__(self, old_name, new_name):
        self.old_name = old_name
        self.new_name = new_name

    def _rename(self, trials, src, dst):
        out = []
        for trial in trials:
            params = dict(trial.params)
            if src in params:
                params[dst] = params.pop(src)
            out.append(_clone_with_params(trial, params))
        return out

    def forward(self, trials):
        return self._rename(trials, self.old_name, self.new_name)

    def backward(self, trials):
        return self._rename(trials, self.new_name, self.old_name)

    def _config(self):
        return {"old_name": self.old_name, "new_name": self.new_name}


@adapter_registry.register("algorithmchange")
class AlgorithmChange(Adapter):
    """Algorithm changed: trials remain valid — pass-through
    (reference `adapters.py:557`)."""

    def forward(self, trials):
        return list(trials)

    def backward(self, trials):
        return list(trials)


class _ChangeTypeAdapter(Adapter):
    """Shared behavior for code/cmdline/script-config changes: ``break``
    drops trials across the hop, ``noeffect``/``unsure`` pass through
    (reference `adapters.py:596,677,758`)."""

    def __init__(self, change_type):
        if change_type not in CHANGE_TYPES:
            raise ValueError(
                f"change_type must be one of {CHANGE_TYPES}, got {change_type!r}"
            )
        self.change_type = change_type

    def _apply(self, trials):
        if self.change_type == "break":
            return []
        if self.change_type == "unsure":
            log.debug("%s with change_type=unsure: passing trials through",
                      type(self).__name__)
        return list(trials)

    def forward(self, trials):
        return self._apply(trials)

    def backward(self, trials):
        return self._apply(trials)

    def _config(self):
        return {"change_type": self.change_type}


@adapter_registry.register("codechange")
class CodeChange(_ChangeTypeAdapter):
    pass


@adapter_registry.register("commandlinechange")
class CommandLineChange(_ChangeTypeAdapter):
    pass


@adapter_registry.register("scriptconfigchange")
class ScriptConfigChange(_ChangeTypeAdapter):
    pass


@adapter_registry.register("compositeadapter")
class CompositeAdapter(Adapter):
    """Sequential application (reference `adapters.py:116-193`)."""

    def __init__(self, *adapters):
        self.adapters = [
            a if isinstance(a, Adapter) else build_adapter(a) for a in adapters
        ]

    def forward(self, trials):
        for adapter in self.adapters:
            trials = adapter.forward(trials)
        return trials

    def backward(self, trials):
        for adapter in reversed(self.adapters):
            trials = adapter.backward(trials)
        return trials

    def to_dict(self):
        return {
            "of_type": "compositeadapter",
            "adapters": [a.to_dict() for a in self.adapters],
        }
