"""EVC — experiment version control.

Capability parity: reference `src/orion/core/evc/` + branching builders: when
an experiment is re-run with a changed configuration, detect every conflict
between the old and new configs, resolve each into a bidirectional trial
adapter, and branch a child experiment (version bump or rename) linked
through ``refers = {root_id, parent_id, adapter}``.  Trials then flow through
the whole experiment tree, adapted in each hop.
"""

from orion_tpu.evc.adapters import Adapter, CompositeAdapter, build_adapter
from orion_tpu.evc.conflicts import detect_conflicts
from orion_tpu.evc.builder import branch_experiment

__all__ = [
    "Adapter",
    "CompositeAdapter",
    "build_adapter",
    "branch_experiment",
    "detect_conflicts",
]
