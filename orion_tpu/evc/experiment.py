"""Experiment tree: fetch trials across the whole version-control lineage.

Capability parity: reference `src/orion/core/evc/experiment.py` —
`ExperimentNode` with lazy parent/children discovery through
``refers.parent_id`` links in storage, and tree-wide trial fetching that
applies ``adapter.forward`` to parent trials and ``adapter.backward`` to
children trials on each hop (`evc/experiment.py:154-226`).
"""

import logging

from orion_tpu.evc.adapters import build_adapter
from orion_tpu.evc.tree import TreeNode

log = logging.getLogger(__name__)


class ExperimentNode(TreeNode):
    """Tree node lazily materialized from storage experiment documents."""

    def __init__(self, storage, config, parent=None, children=()):
        super().__init__(config, parent=parent, children=children)
        self.storage = storage
        self._parent_loaded = parent is not None
        self._children_loaded = False

    @property
    def config(self):
        return self.item

    @property
    def exp_id(self):
        return self.config["_id"]

    @property
    def name(self):
        return self.config["name"]

    @property
    def version(self):
        return self.config.get("version", 1)

    @property
    def adapter(self):
        spec = (self.config.get("refers") or {}).get("adapter")
        return build_adapter(spec) if spec else None

    @property
    def parent(self):
        if not self._parent_loaded:
            self._parent_loaded = True
            parent_id = (self.config.get("refers") or {}).get("parent_id")
            if parent_id:
                docs = self.storage.fetch_experiments({"_id": parent_id})
                if docs:
                    node = ExperimentNode(self.storage, docs[0])
                    self.set_parent(node)
        return self._parent

    @property
    def children(self):
        if not self._children_loaded:
            self._children_loaded = True
            docs = self.storage.fetch_experiments(
                {"refers.parent_id": self.exp_id}
            )
            for doc in docs:
                if doc["_id"] not in [c.exp_id for c in self._children]:
                    self.add_children(
                        ExperimentNode(self.storage, doc, parent=self)
                    )
        return list(self._children)

    @property
    def root(self):
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def tree_name(self):
        return f"{self.name}-v{self.version}"


def build_node(storage, experiment):
    docs = storage.fetch_experiments({"_id": experiment.id})
    if not docs:
        raise ValueError(f"experiment {experiment.id} not in storage")
    return ExperimentNode(storage, docs[0])


class TreeTrialsFetcher:
    """Incremental tree-wide trial fetch for the producer's hot loop.

    The reference re-walks the whole tree and re-adapts every ancestor /
    descendant trial list on every producer round (`evc/experiment.py:154-226`
    — quadratic-ish as rounds accumulate).  This fetcher:

    - resolves the tree topology and per-node adapter hop-chains ONCE
      (family membership is fixed for a producer's lifetime, matching the
      producer's one-shot `_has_evc_family` probe);
    - per round reads only a (status, end_time) signature projection per
      family node, re-fetching and re-adapting ONLY trials that are new or
      whose signature changed (adapters act element-wise, so per-trial
      adaptation composes into the same result as whole-list adaptation);
    - keeps the experiment's OWN trials un-cached — that collection is the
      hot one and always fetched fresh.

    Storage reads per round: 1 (own) + 1 signature read per family node,
    + 1 bulk read per node only when something actually changed.
    """

    def __init__(self, experiment):
        self.experiment = experiment
        self.storage = experiment.storage
        node = build_node(self.storage, experiment)
        self.node_id = node.exp_id
        self.root_id = (node.config.get("refers") or {}).get("root_id") or node.exp_id
        self.family = self._family_chains(node)
        self._family_ids = self._probe_family_ids()
        # exp_id -> {"sig": {trial_id: sig}, "adapted": {trial_id: [trials]}}
        self._cache = {}

    def _probe_family_ids(self):
        """Cheap membership snapshot: ids of every experiment in this tree."""
        docs = self.storage.fetch_experiments(
            {"refers.root_id": self.root_id}, projection={"_id": 1}
        )
        ids = {d["_id"] for d in docs}
        ids.add(self.root_id)
        return ids

    @staticmethod
    def _family_chains(node):
        """[(exp_id, adapter_hop_chain, direction)] for every other node."""
        chains = []
        child = node
        chain = []  # adapters from the immediate hop outward
        while child.parent is not None:
            chain.append(child.adapter)
            parent = child.parent
            chains.append((parent.exp_id, list(chain), "forward"))
            child = parent

        def walk(n, adapters):
            for ch in n.children:
                hop = adapters + [ch.adapter]
                chains.append((ch.exp_id, list(hop), "backward"))
                walk(ch, hop)

        walk(node, [])
        return chains

    def fetch(self):
        # Branches can appear mid-run (another user branching this tree):
        # one cheap projected read of the tiny experiments collection per
        # round detects membership changes and rebuilds the hop chains.
        current_ids = self._probe_family_ids()
        if current_ids != self._family_ids:
            self._family_ids = current_ids
            node = build_node(self.storage, self.experiment)
            self.family = self._family_chains(node)
            self._cache = {
                k: v for k, v in self._cache.items()
                if k in {exp_id for exp_id, _, _ in self.family}
            }
        trials = list(self.storage.fetch_trials(uid=self.node_id))
        for exp_id, chain, direction in self.family:
            trials.extend(self._fetch_node(exp_id, chain, direction))
        seen, out = set(), []
        for trial in trials:
            if trial.id not in seen:
                seen.add(trial.id)
                out.append(trial)
        return out

    def _fetch_node(self, exp_id, chain, direction):
        cache = self._cache.setdefault(exp_id, {"sig": {}, "adapted": {}})
        sig_docs = self.storage.read_trial_docs(
            exp_id,
            projection={"status": 1, "end_time": 1, "submit_time": 1},
        )
        sigs = {
            d["_id"]: (d.get("status"), d.get("end_time")) for d in sig_docs
        }
        changed = [
            tid for tid, sig in sigs.items() if cache["sig"].get(tid) != sig
        ]
        if changed:
            docs = self.storage.read_trial_docs(exp_id, ids=changed)
            from orion_tpu.core.trial import Trial

            for doc in docs:
                trial = Trial.from_dict(doc)
                adapted = [trial]
                for adapter in reversed(chain):
                    if adapter is not None:
                        if direction == "forward":
                            adapted = adapter.forward(adapted)
                        else:
                            adapted = adapter.backward(adapted)
                cache["adapted"][trial.id] = adapted
                cache["sig"][trial.id] = sigs[trial.id]
        for tid in list(cache["sig"]):
            if tid not in sigs:  # removed from storage
                cache["sig"].pop(tid)
                cache["adapted"].pop(tid, None)
        # Stable order: by (submit_time, id), matching fetch_trials sorting.
        submit_times = {d["_id"]: d.get("submit_time") or 0.0 for d in sig_docs}
        order = sorted(sigs, key=lambda tid: (submit_times[tid], str(tid)))
        out = []
        for tid in order:
            out.extend(cache["adapted"].get(tid, []))
        return out


def fetch_tree_trials(experiment):
    """One-shot tree-wide fetch (CLI status/info paths); the producer holds a
    persistent :class:`TreeTrialsFetcher` instead."""
    return TreeTrialsFetcher(experiment).fetch()
