"""Experiment tree: fetch trials across the whole version-control lineage.

Capability parity: reference `src/orion/core/evc/experiment.py` —
`ExperimentNode` with lazy parent/children discovery through
``refers.parent_id`` links in storage, and tree-wide trial fetching that
applies ``adapter.forward`` to parent trials and ``adapter.backward`` to
children trials on each hop (`evc/experiment.py:154-226`).
"""

import logging

from orion_tpu.evc.adapters import build_adapter
from orion_tpu.evc.tree import TreeNode

log = logging.getLogger(__name__)


class ExperimentNode(TreeNode):
    """Tree node lazily materialized from storage experiment documents."""

    def __init__(self, storage, config, parent=None, children=()):
        super().__init__(config, parent=parent, children=children)
        self.storage = storage
        self._parent_loaded = parent is not None
        self._children_loaded = False

    @property
    def config(self):
        return self.item

    @property
    def exp_id(self):
        return self.config["_id"]

    @property
    def name(self):
        return self.config["name"]

    @property
    def version(self):
        return self.config.get("version", 1)

    @property
    def adapter(self):
        spec = (self.config.get("refers") or {}).get("adapter")
        return build_adapter(spec) if spec else None

    @property
    def parent(self):
        if not self._parent_loaded:
            self._parent_loaded = True
            parent_id = (self.config.get("refers") or {}).get("parent_id")
            if parent_id:
                docs = self.storage.fetch_experiments({"_id": parent_id})
                if docs:
                    node = ExperimentNode(self.storage, docs[0])
                    self.set_parent(node)
        return self._parent

    @property
    def children(self):
        if not self._children_loaded:
            self._children_loaded = True
            docs = self.storage.fetch_experiments(
                {"refers.parent_id": self.exp_id}
            )
            for doc in docs:
                if doc["_id"] not in [c.exp_id for c in self._children]:
                    self.add_children(
                        ExperimentNode(self.storage, doc, parent=self)
                    )
        return list(self._children)

    @property
    def root(self):
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def tree_name(self):
        return f"{self.name}-v{self.version}"


def build_node(storage, experiment):
    docs = storage.fetch_experiments({"_id": experiment.id})
    if not docs:
        raise ValueError(f"experiment {experiment.id} not in storage")
    return ExperimentNode(storage, docs[0])


def fetch_tree_trials(experiment):
    """All trials usable by ``experiment``: its own, plus ancestors' trials
    adapted forward hop by hop, plus descendants' adapted backward."""
    storage = experiment.storage
    node = build_node(storage, experiment)

    trials = list(storage.fetch_trials(uid=node.exp_id))

    # Ancestors: walk up; each hop applies THIS child's adapter forward.
    child = node
    chain = []  # adapters from root-most hop to immediate hop
    while child.parent is not None:
        chain.append(child.adapter)
        parent = child.parent
        parent_trials = storage.fetch_trials(uid=parent.exp_id)
        # Adapt through every hop between that ancestor and `experiment`.
        for adapter in reversed(chain):
            if adapter is not None:
                parent_trials = adapter.forward(parent_trials)
        trials.extend(parent_trials)
        child = parent

    # Descendants: recursive walk down; each hop applies the CHILD's adapter
    # backward.
    def collect_descendants(n, adapters):
        for ch in n.children:
            ch_trials = storage.fetch_trials(uid=ch.exp_id)
            hop = adapters + [ch.adapter]
            adapted = ch_trials
            for adapter in reversed(hop):
                if adapter is not None:
                    adapted = adapter.backward(adapted)
            trials.extend(adapted)
            collect_descendants(ch, hop)

    collect_descendants(node, [])

    # Dedup by id, own-experiment trials first.
    seen, out = set(), []
    for trial in trials:
        if trial.id not in seen:
            seen.add(trial.id)
            out.append(trial)
    return out
