"""Interactive conflict-resolution shell.

Capability parity: reference
`src/orion/core/io/interactive_commands/branching_prompt.py` — a `cmd.Cmd`
session offering name/add/remove/rename/code/commandline/config/algo/status/
diff/reset/auto commands with tab completion over conflicting dimension
names; `commit` exits once everything is resolved.
"""

import cmd

from orion_tpu.evc import conflicts as C


class BranchingPrompt(cmd.Cmd):
    intro = (
        "Experiment configuration conflicts detected.\n"
        "Type 'status' to list them, 'help' for commands, 'auto' to resolve "
        "automatically, 'commit' when done."
    )
    prompt = "(branch) "

    def __init__(self, builder):
        super().__init__()
        self.builder = builder

    # --- inspection -----------------------------------------------------------
    def do_status(self, _line):
        """List conflicts and their resolution state."""
        from orion_tpu.utils.diff import colorize_diff_line

        for conflict in self.builder.conflicts.conflicts:
            mark = "resolved" if conflict.is_resolved else "PENDING "
            print(f"  [{mark}] {colorize_diff_line(conflict.diff())}")

    def do_diff(self, _line):
        """Print the configuration diff (colored on a TTY)."""
        from orion_tpu.utils.diff import colorize_diff_line

        for line in self.builder.conflicts.diffs():
            print(" ", colorize_diff_line(line))

    # --- resolutions ----------------------------------------------------------
    def do_name(self, line):
        """name <new_experiment_name> — branch under a different name."""
        self.builder.change_experiment_name(line.strip())

    def do_add(self, line):
        """add <dim> [default] — resolve a new dimension with a default."""
        parts = line.split()
        default = _literal(parts[1]) if len(parts) > 1 else None
        try:
            self.builder.add_dimension(parts[0], default)
        except ValueError as exc:
            # e.g. no default available — report, keep the session (and every
            # resolution already entered) alive.
            print(f"cannot resolve: {exc}")

    def do_remove(self, line):
        """remove <dim> [default] — drop a missing dimension."""
        parts = line.split()
        default = _literal(parts[1]) if len(parts) > 1 else None
        try:
            self.builder.remove_dimension(parts[0], default)
        except ValueError as exc:
            print(f"cannot resolve: {exc}")

    def do_rename(self, line):
        """rename <old> <new> — resolve a missing dimension as renamed."""
        old, new = line.split()
        self.builder.rename_dimension(old, new)

    def do_code(self, line):
        """code <noeffect|unsure|break> — classify the code change."""
        self.builder.set_code_change_type(line.strip())

    def do_commandline(self, line):
        """commandline <noeffect|unsure|break> — classify the cmdline change."""
        self.builder.set_cli_change_type(line.strip())

    def do_config(self, line):
        """config <noeffect|unsure|break> — classify the script-config change."""
        self.builder.set_script_config_change_type(line.strip())

    def do_algo(self, _line):
        """algo — accept the algorithm change."""
        for conflict in self.builder.conflicts.get([C.AlgorithmConflict]):
            conflict.try_resolve()

    def do_auto(self, _line):
        """auto — resolve everything automatically."""
        self.builder.conflicts.try_resolve_all()
        self.do_status("")

    def do_reset(self, _line):
        """reset — clear all resolutions."""
        self.builder.reset()

    # --- exit -----------------------------------------------------------------
    def do_commit(self, _line):
        """commit — finish (requires every conflict resolved)."""
        if self.builder.conflicts.are_resolved:
            return True
        print("Unresolved conflicts remain:")
        self.do_status("")
        return False

    def do_abort(self, _line):
        """abort — leave conflicts unresolved (branching will fail)."""
        return True

    def do_EOF(self, _line):
        """End of input: commit if everything is resolved, else abort —
        looping back to the prompt would spin forever on closed stdin."""
        if self.builder.conflicts.are_resolved:
            return True
        print("EOF with unresolved conflicts; aborting branch.")
        return True

    # --- completion -----------------------------------------------------------
    # Per-command candidates (reference branching_prompt.py:77-485 ships
    # complete_* methods per command): each command completes only the names
    # it can actually act on, so tab after `remove ` never offers a NEW
    # dimension it would reject.

    _CHANGE_TYPES = ("noeffect", "unsure", "break")

    def _conflict_names(self, *types):
        names = []
        for conflict in self.builder.conflicts.get(list(types) or None):
            if hasattr(conflict, "name") and not conflict.is_resolved:
                names.append(conflict.name)
        return names

    @staticmethod
    def _match(candidates, text):
        return [c for c in candidates if c.startswith(text)]

    def complete_add(self, text, _line, _begidx, _endidx):
        return self._match(self._conflict_names(C.NewDimensionConflict), text)

    def complete_remove(self, text, _line, _begidx, _endidx):
        return self._match(self._conflict_names(C.MissingDimensionConflict), text)

    def complete_rename(self, text, line, _begidx, _endidx):
        # First argument: the missing (old) name; second: the new name.
        n_args = len(line.split())
        if n_args > 2 or (n_args == 2 and not text):
            source = self._conflict_names(C.NewDimensionConflict)
        else:
            source = self._conflict_names(C.MissingDimensionConflict)
        return self._match(source, text)

    def complete_code(self, text, _line, _begidx, _endidx):
        return self._match(self._CHANGE_TYPES, text)

    complete_commandline = complete_code
    complete_config = complete_code

    def completedefault(self, text, _line, _begidx, _endidx):
        return self._match(self._conflict_names(), text)


def _literal(token):
    import ast

    try:
        return ast.literal_eval(token)
    except (ValueError, SyntaxError):
        return token
