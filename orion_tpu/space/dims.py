"""Search-space dimensions, designed device-first.

Capability parity: reference `src/orion/algo/space.py` (Dimension/Real/Integer/
Categorical/Fidelity/Space, ~880 LoC of scipy.stats wrappers with host-side
rejection sampling).  Redesign for TPU: every dimension is a static spec that
lowers to a **unit-cube column codec** — a pair of pure jnp maps

    decode: [0,1]^m -> value domain      (prior inverse-CDF)
    encode: value domain -> [0,1]^m      (prior CDF)

so that (a) sampling the prior == sampling U(0,1) and decoding, (b) the whole
space flattens to a shape-static ``(n, D)`` array algorithms can jit/vmap over,
and (c) no rejection loops are needed (truncated distributions use analytic
CDF renormalization instead of the reference's x4 rejection sampling at
`space.py:371-391`).

Host-side semantics kept from the reference: name-sorted spaces, point
membership, interval, defaults, prior-string identity for EVC equality
(`space.py:144-158`).
"""

import math
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np
from jax.scipy.special import ndtr, ndtri

_EPS = 1e-7


class _NotSet:
    def __repr__(self):
        return "<NotSet>"


NotSet = _NotSet()


def _size(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


@dataclass(frozen=True)
class Dimension:
    """Base spec for one named dimension.

    ``prior_expr`` is the canonical DSL string (e.g. ``uniform(-3, 5)``); it is
    the identity used by experiment version control to compare spaces.
    """

    name: str
    prior_expr: str
    shape: tuple = ()
    default_value: object = field(default=NotSet)

    # --- static structure -------------------------------------------------
    @property
    def size(self):
        return _size(self.shape)

    @property
    def n_cols(self):
        """Number of unit-cube columns this dimension occupies."""
        return self.size

    @property
    def type(self):
        return type(self).__name__.lower()

    def get_prior_string(self):
        return self.prior_expr

    def get_string(self):
        return f"{self.name}~{self.prior_expr}"

    # --- device codec -----------------------------------------------------
    def decode(self, u):
        """Map ``u`` in [0,1]^(n, size) to values, as a pure jnp op."""
        raise NotImplementedError

    def encode(self, x):
        """Inverse of :meth:`decode` (values -> unit cube)."""
        raise NotImplementedError

    # --- host codec mirror --------------------------------------------------
    # Numpy twins of decode/encode for the host side of the suggest/observe
    # boundary.  A (q, D) cube is transferred from device ONCE and decoded
    # host-side; per-dimension device decode would cost one ~ms host<->device
    # round trip per dimension.  Subclasses override with pure numpy; the
    # fallback routes through the device codec.
    def decode_np(self, u):
        return np.asarray(self.decode(jnp.asarray(u)))

    def encode_np(self, x):
        return np.asarray(self.encode(jnp.asarray(x)))

    # --- host semantics ---------------------------------------------------
    def interval(self):
        raise NotImplementedError

    def cast(self, value):
        raise NotImplementedError

    def cast_decoded(self, value):
        """Cast for values coming out of the codec (subclasses may clamp
        f32 rounding back into bounds; user-input `cast` never clamps)."""
        return self.cast(value)

    def __contains__(self, value):
        raise NotImplementedError

    def _shaped(self, value):
        """Validate/broadcast a scalar-or-array value to this dim's shape."""
        arr = np.asarray(value)
        if arr.shape != self.shape:
            raise ValueError(
                f"Dimension {self.name}: value shape {arr.shape} != {self.shape}"
            )
        return arr

    def sample_host(self, rng, n=1):
        """Host-side numpy sampling (used by CLI validation paths)."""
        u = rng.uniform(size=(n, self.size))
        vals = np.asarray(self.decode(jnp.asarray(u)))
        return vals.reshape((n,) + self.shape)

    def __repr__(self):
        return (f"{type(self).__name__}(name={self.name}, "
                f"prior={self.prior_expr}, shape={self.shape})")


@dataclass(frozen=True, repr=False)
class Real(Dimension):
    """Continuous dimension with a named prior.

    Supported priors (``dist``): ``uniform(low, high)``, ``loguniform(low,
    high)``, ``normal(loc, scale)`` and ``normal`` truncated to [low, high]
    when explicit bounds are given.
    """

    dist: str = "uniform"
    low: float = 0.0
    high: float = 1.0
    loc: float = 0.0
    scale: float = 1.0
    precision: int = 0  # significant digits to round to on cast; 0 = off

    def interval(self):
        return (self.low, self.high)

    def decode(self, u):
        u = jnp.clip(u, _EPS, 1.0 - _EPS)
        if self.dist == "uniform":
            return self.low + u * (self.high - self.low)
        if self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high)
            return jnp.exp(lo + u * (hi - lo))
        if self.dist == "normal":
            if math.isfinite(self.low) or math.isfinite(self.high):
                # Truncated normal via CDF renormalization — no rejection loop.
                a = ndtr((self.low - self.loc) / self.scale)
                b = ndtr((self.high - self.loc) / self.scale)
                u = a + u * (b - a)
                u = jnp.clip(u, _EPS, 1.0 - _EPS)
            return self.loc + self.scale * ndtri(u)
        raise NotImplementedError(f"prior {self.dist!r}")

    def encode(self, x):
        x = jnp.asarray(x, dtype=jnp.float32)
        if self.dist == "uniform":
            u = (x - self.low) / (self.high - self.low)
        elif self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high)
            u = (jnp.log(x) - lo) / (hi - lo)
        elif self.dist == "normal":
            u = ndtr((x - self.loc) / self.scale)
            if math.isfinite(self.low) or math.isfinite(self.high):
                a = ndtr((self.low - self.loc) / self.scale)
                b = ndtr((self.high - self.loc) / self.scale)
                u = (u - a) / (b - a)
        else:
            raise NotImplementedError(f"prior {self.dist!r}")
        return jnp.clip(u, 0.0, 1.0)

    def decode_np(self, u):
        from scipy.special import ndtr as _ndtr, ndtri as _ndtri

        u = np.clip(np.asarray(u, dtype=np.float64), _EPS, 1.0 - _EPS)
        if self.dist == "uniform":
            return self.low + u * (self.high - self.low)
        if self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high)
            return np.exp(lo + u * (hi - lo))
        if self.dist == "normal":
            if math.isfinite(self.low) or math.isfinite(self.high):
                a = _ndtr((self.low - self.loc) / self.scale)
                b = _ndtr((self.high - self.loc) / self.scale)
                u = np.clip(a + u * (b - a), _EPS, 1.0 - _EPS)
            return self.loc + self.scale * _ndtri(u)
        raise NotImplementedError(f"prior {self.dist!r}")

    def encode_np(self, x):
        from scipy.special import ndtr as _ndtr

        x = np.asarray(x, dtype=np.float64)
        if self.dist == "uniform":
            u = (x - self.low) / (self.high - self.low)
        elif self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high)
            u = (np.log(x) - lo) / (hi - lo)
        elif self.dist == "normal":
            u = _ndtr((x - self.loc) / self.scale)
            if math.isfinite(self.low) or math.isfinite(self.high):
                a = _ndtr((self.low - self.loc) / self.scale)
                b = _ndtr((self.high - self.loc) / self.scale)
                u = (u - a) / (b - a)
        else:
            raise NotImplementedError(f"prior {self.dist!r}")
        return np.clip(u, 0.0, 1.0)

    def cast(self, value):
        arr = self._cast_arr(value)
        return arr.reshape(self.shape) if self.shape else float(arr)

    def _cast_arr(self, value):
        arr = np.asarray(value, dtype=float)
        if self.precision:
            with np.errstate(divide="ignore"):
                mag = np.where(arr != 0, np.floor(np.log10(np.abs(arr))), 0.0)
            factor = 10.0 ** (self.precision - 1 - mag)
            arr = np.round(arr * factor) / factor
        return arr

    def cast_decoded(self, value):
        """Cast for DECODED values only: additionally clamps to the bounds.

        Device decodes run in f32: when a bound is not f32-representable,
        lo + u*span at u->1 can land epsilon past the f64 bound and the
        sampled point would fail its own space's containment check.  The
        user-input `cast` must NOT clamp — an out-of-range insert has to
        fail validation, not be silently moved to the bound."""
        return np.clip(self._cast_arr(value), self.low, self.high)

    def cast_column(self, col):
        """Vectorized decoded-cast of a length-n column -> python list.

        One numpy pass per column instead of a python-level cast call per
        value — this is on the q=1024 suggest hot path (arrays_to_params)."""
        return self.cast_decoded(col).tolist()

    def __contains__(self, value):
        try:
            arr = self._shaped(np.asarray(value, dtype=float))
        except (TypeError, ValueError):
            return False
        lo, hi = self.interval()
        return bool(np.all(arr >= lo) and np.all(arr <= hi))


@dataclass(frozen=True, repr=False)
class Integer(Real):
    """Integer dimension = floor discretization of the underlying prior.

    Matches the reference convention (`space.py:408-497`): ``uniform(low,
    high, discrete=True)`` covers the inclusive integer range [low, high].
    """

    def decode(self, u):
        u = jnp.clip(u, _EPS, 1.0 - _EPS)
        if self.dist == "uniform":
            span = self.high - self.low + 1
            x = jnp.floor(self.low + u * span)
        elif self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high + 1)
            x = jnp.floor(jnp.exp(lo + u * (hi - lo)))
        else:
            x = jnp.floor(super().decode(u))
        return jnp.clip(x, self.low, self.high).astype(jnp.int32)

    def encode(self, x):
        x = jnp.asarray(x, dtype=jnp.float32)
        if self.dist == "uniform":
            span = self.high - self.low + 1
            u = (x - self.low + 0.5) / span
        elif self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high + 1)
            u = (jnp.log(x + 0.5) - lo) / (hi - lo)
        else:
            u = super().encode(x + 0.5)
        return jnp.clip(u, 0.0, 1.0)

    def cast(self, value):
        arr = np.floor(np.asarray(value, dtype=float)).astype(int)
        return arr.reshape(self.shape) if self.shape else int(arr)

    def cast_column(self, col):
        return np.floor(np.asarray(col, dtype=float)).astype(int).tolist()

    def decode_np(self, u):
        u = np.clip(np.asarray(u, dtype=np.float64), _EPS, 1.0 - _EPS)
        if self.dist == "uniform":
            span = self.high - self.low + 1
            x = np.floor(self.low + u * span)
        elif self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high + 1)
            x = np.floor(np.exp(lo + u * (hi - lo)))
        else:
            x = np.floor(super().decode_np(u))
        return np.clip(x, self.low, self.high).astype(np.int32)

    def encode_np(self, x):
        x = np.asarray(x, dtype=np.float64)
        if self.dist == "uniform":
            span = self.high - self.low + 1
            u = (x - self.low + 0.5) / span
        elif self.dist == "loguniform":
            lo, hi = math.log(self.low), math.log(self.high + 1)
            u = (np.log(x + 0.5) - lo) / (hi - lo)
        else:
            u = super().encode_np(x + 0.5)
        return np.clip(u, 0.0, 1.0)

    def __contains__(self, value):
        try:
            arr = np.asarray(self._shaped(value), dtype=float)
        except (TypeError, ValueError):
            return False
        if not np.all(np.equal(np.mod(arr, 1), 0)):
            return False
        return bool(np.all(arr >= self.low) and np.all(arr <= self.high))


@dataclass(frozen=True, repr=False)
class Categorical(Dimension):
    """Categorical dimension over arbitrary python objects.

    Device representation is the integer index; the category vocabulary lives
    host-side (reference keeps object dtype arrays, `space.py:500-647`, which
    cannot exist on device).  The codec maps a unit-cube column through the
    categorical CDF, so prior probabilities are honored by uniform sampling.
    """

    categories: tuple = ()
    probs: tuple = ()

    def __post_init__(self):
        if not self.probs:
            k = len(self.categories)
            object.__setattr__(self, "probs", tuple([1.0 / k] * k))

    @property
    def n_choices(self):
        return len(self.categories)

    def interval(self):
        return tuple(self.categories)

    def _cum(self):
        return jnp.cumsum(jnp.asarray(self.probs, dtype=jnp.float32))

    def decode(self, u):
        u = jnp.clip(u, _EPS, 1.0 - _EPS)
        idx = jnp.searchsorted(self._cum(), u)
        return jnp.clip(idx, 0, self.n_choices - 1).astype(jnp.int32)

    def encode(self, idx):
        cum = np.concatenate([[0.0], np.cumsum(np.asarray(self.probs))])
        mid = jnp.asarray((cum[:-1] + cum[1:]) / 2.0, dtype=jnp.float32)
        return mid[jnp.asarray(idx, dtype=jnp.int32)]

    def decode_np(self, u):
        u = np.clip(np.asarray(u, dtype=np.float64), _EPS, 1.0 - _EPS)
        cum = np.cumsum(np.asarray(self.probs, dtype=np.float64))
        idx = np.searchsorted(cum, u)
        return np.clip(idx, 0, self.n_choices - 1).astype(np.int32)

    def encode_np(self, idx):
        cum = np.concatenate([[0.0], np.cumsum(np.asarray(self.probs))])
        mid = (cum[:-1] + cum[1:]) / 2.0
        return mid[np.asarray(idx, dtype=np.int32)]

    def to_index(self, value):
        """Host: category object -> index."""
        arr = np.asarray(value)
        if arr.shape == self.shape and self.shape:
            return np.vectorize(lambda v: self.categories.index(v))(arr)
        return self.categories.index(value if not isinstance(value, np.generic) else value.item())

    def from_index(self, idx):
        """Host: index -> category object."""
        arr = np.asarray(idx)
        if self.shape:
            flat = [self.categories[int(i)] for i in arr.reshape(-1)]
            return np.asarray(flat, dtype=object).reshape(self.shape)
        return self.categories[int(arr)]

    # --- vectorized column codec (the q-batch hot path) ---------------------
    # One lookup-table pass per COLUMN instead of a python ``to_index``/
    # ``from_index`` call per value.  Bit-identical to the per-value loops
    # (tests/unit/test_space_codec_diff.py pins it): the index map is
    # first-occurrence-wins like ``list.index`` (categories 1 and 1.0 are
    # == and would otherwise collapse to the LAST entry under plain dict
    # insertion), and the value table hands out the SAME category objects.

    def _index_lut(self):
        lut = self.__dict__.get("_index_lut_cache")
        if lut is None:
            lut = {}
            for i, cat in enumerate(self.categories):
                lut.setdefault(cat, i)  # first occurrence wins (== list.index)
            object.__setattr__(self, "_index_lut_cache", lut)
        return lut

    def _category_array(self):
        arr = self.__dict__.get("_category_array_cache")
        if arr is None:
            # np.asarray(categories) would coerce tuple/list categories
            # into extra array dimensions; fill an object array instead.
            arr = np.empty(len(self.categories), dtype=object)
            arr[:] = list(self.categories)
            object.__setattr__(self, "_category_array_cache", arr)
        return arr

    def to_index_column(self, values):
        """Vectorized ``[to_index(v) for v in values]`` for scalar dims."""
        lut = self._index_lut()
        out = []
        for value in values:
            try:
                out.append(lut[value])
            except (KeyError, TypeError):
                # Unhashable or unknown value: the reference path both
                # resolves == matches list.index-style and raises the
                # canonical ValueError for genuinely unknown categories.
                out.append(self.to_index(value))
        return out

    def from_index_column(self, col):
        """Vectorized ``[from_index(i)...]`` over an index column.

        Scalar dims get a list of category objects (identical objects to
        the per-value path); shaped dims a list of ``shape``-shaped object
        arrays, matching ``from_index``'s row output."""
        table = self._category_array()
        if self.shape:
            n = np.asarray(col).shape[0]
            block = table[np.asarray(col, dtype=np.intp).reshape(n, -1)]
            return [row.reshape(self.shape) for row in block]
        return table[np.asarray(col, dtype=np.intp)].tolist()

    def cast(self, value):
        # Accept either a category literal or its string form.
        if value in self.categories:
            return value
        by_str = {str(c): c for c in self.categories}
        if str(value) in by_str:
            return by_str[str(value)]
        raise ValueError(f"{value!r} is not a category of {self.name}")

    def __contains__(self, value):
        if self.shape:
            arr = np.asarray(value, dtype=object)
            if arr.shape != self.shape:
                return False
            return all(v in self.categories for v in arr.reshape(-1))
        try:
            self.cast(value)
            return True
        except (ValueError, TypeError):
            return False

    def sample_host(self, rng, n=1):
        u = rng.uniform(size=(n, self.size))
        idx = np.asarray(self.decode(jnp.asarray(u)))
        if self.shape:
            return np.asarray(
                [self.from_index(row.reshape(self.shape)) for row in idx], dtype=object
            )
        return np.asarray([self.from_index(i) for i in idx[:, 0]], dtype=object)


@dataclass(frozen=True, repr=False)
class Fidelity(Dimension):
    """Budget dimension — never optimized, assigned by multi-fidelity algos.

    Parity: reference `space.py:650-729`.  Contributes **zero** unit-cube
    columns; the fidelity value rides host-side in the trial params, set by
    the algorithm (max budget by default, rung budgets under ASHA).
    """

    low: int = 1
    high: int = 1
    base: int = 2

    @property
    def n_cols(self):
        return 0

    def interval(self):
        return (self.low, self.high)

    def budgets(self):
        """Geometric rung budgets low * base^k capped at high (ASHA rungs)."""
        if self.base < 2:
            return [int(self.low), int(self.high)] if self.low < self.high else [int(self.high)]
        out = []
        b = self.low
        while b < self.high:
            out.append(int(b))
            b *= self.base
        out.append(int(self.high))
        return out

    def decode(self, u):  # pragma: no cover - zero columns
        return u

    def encode(self, x):  # pragma: no cover - zero columns
        return x

    def cast(self, value):
        return int(value)

    def __contains__(self, value):
        try:
            v = float(value)
        except (TypeError, ValueError):
            return False
        return self.low <= v <= self.high
