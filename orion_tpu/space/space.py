"""The Space container and its device-flat codec.

Capability parity: reference ``Space`` (`src/orion/algo/space.py:732-858`) —
name-sorted dict of dimensions with sample/interval/contains — fused with the
reference's transformer pipeline (`src/orion/core/worker/transformer.py`):
instead of per-point python transform objects, the space exposes one
shape-static codec between structured params and a flat ``(n, D)`` unit-cube
array, which is what jitted algorithms operate on.
"""

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.space.dims import Categorical, Dimension, Fidelity, NotSet
from orion_tpu.space.params import ParamBatch


class Space:
    """Ordered (name-sorted) collection of dimensions."""

    def __init__(self, dims=()):
        self._dims = {}
        self._decode_jit = None
        for dim in dims:
            self.register(dim)

    # --- container protocol ----------------------------------------------
    def register(self, dim):
        if not isinstance(dim, Dimension):
            raise TypeError(f"Expected Dimension, got {type(dim)}")
        if dim.name in self._dims:
            raise ValueError(f"Duplicate dimension name {dim.name!r}")
        self._dims[dim.name] = dim
        self._dims = dict(sorted(self._dims.items()))
        self._decode_jit = None

    def __iter__(self):
        return iter(self._dims.values())

    def __len__(self):
        return len(self._dims)

    def __getitem__(self, key):
        if isinstance(key, int):
            return list(self._dims.values())[key]
        return self._dims[key]

    def __contains__(self, key):
        if isinstance(key, str):
            return key in self._dims
        return self.contains_point(key)

    def keys(self):
        return list(self._dims.keys())

    def values(self):
        return list(self._dims.values())

    def items(self):
        return list(self._dims.items())

    # --- semantics --------------------------------------------------------
    @property
    def fidelity(self):
        """The fidelity dimension if any (at most one is supported)."""
        for dim in self:
            if isinstance(dim, Fidelity):
                return dim
        return None

    @property
    def opt_dims(self):
        """Dimensions that algorithms actually optimize (fidelity excluded)."""
        return [d for d in self if not isinstance(d, Fidelity)]

    @property
    def n_cols(self):
        """Total flat unit-cube columns."""
        return sum(d.n_cols for d in self)

    def interval(self):
        return [d.interval() for d in self.opt_dims]

    def contains_point(self, params):
        """Host membership test of a params dict (fidelity included if present)."""
        if set(params) != set(self._dims):
            return False
        return all(params[name] in dim for name, dim in self._dims.items())

    def cast(self, params):
        return {name: self._dims[name].cast(value) for name, value in params.items()}

    def defaults(self):
        return {
            d.name: d.default_value for d in self if d.default_value is not NotSet
        }

    def configuration(self):
        """Prior-string form — the identity used by EVC comparisons."""
        return {d.name: d.get_prior_string() for d in self}

    def __repr__(self):
        inner = ", ".join(d.get_string() for d in self)
        return f"Space([{inner}])"

    def __eq__(self, other):
        return isinstance(other, Space) and self.configuration() == other.configuration()

    # --- device codec ------------------------------------------------------
    def _col_slices(self):
        out, start = {}, 0
        for dim in self:
            out[dim.name] = (start, start + dim.n_cols)
            start += dim.n_cols
        return out

    def decode_flat(self, u):
        """(n, D) unit cube -> dict of per-dim device arrays.

        Categorical values are integer indices; fidelity dims are absent.
        Jitted as one compiled function per input shape: the per-dim codec is
        ~5 small ops per dimension and dispatch latency would otherwise
        dominate the q=1024 suggest path.
        """
        if self.n_cols == 0:
            return {}
        if self._decode_jit is None:
            self._decode_jit = jax.jit(self._decode_flat_impl)
        return self._decode_jit(u)

    def _decode_flat_impl(self, u):
        slices = self._col_slices()
        out = {}
        for dim in self:
            if dim.n_cols == 0:
                continue
            a, b = slices[dim.name]
            vals = dim.decode(u[:, a:b])
            if dim.shape:
                vals = vals.reshape((u.shape[0],) + dim.shape)
            else:
                vals = vals[:, 0]
            out[dim.name] = vals
        return out

    def encode_flat(self, arrays):
        """Inverse of :meth:`decode_flat`: dict of arrays -> (n, D) cube."""
        cols = []
        n = None
        for dim in self:
            if dim.n_cols == 0:
                continue
            vals = jnp.asarray(arrays[dim.name])
            n = vals.shape[0]
            cols.append(dim.encode(vals.reshape(n, dim.size)))
        if not cols:
            return jnp.zeros((0, 0))
        return jnp.concatenate(cols, axis=1)

    # --- host codec mirror --------------------------------------------------
    # Numpy twins of decode_flat/encode_flat for the host side of the
    # suggest/observe boundary (one bulk device transfer + cheap host math
    # instead of per-dimension device dispatches — see Dimension.decode_np).
    def decode_flat_np(self, u):
        u = np.asarray(u)
        slices = self._col_slices()
        out = {}
        for dim in self:
            if dim.n_cols == 0:
                continue
            a, b = slices[dim.name]
            vals = dim.decode_np(u[:, a:b])
            if dim.shape:
                vals = vals.reshape((u.shape[0],) + dim.shape)
            else:
                vals = vals[:, 0]
            out[dim.name] = vals
        return out

    def encode_flat_np(self, arrays):
        cols = []
        for dim in self:
            if dim.n_cols == 0:
                continue
            vals = np.asarray(arrays[dim.name])
            cols.append(
                dim.encode_np(vals.reshape(vals.shape[0], dim.size)).astype(
                    np.float32
                )
            )
        if not cols:
            return np.zeros((0, 0), dtype=np.float32)
        return np.concatenate(cols, axis=1)

    def sample_flat(self, key, n):
        """Prior sampling = uniform cube (encode is each prior's CDF)."""
        return jax.random.uniform(key, (n, self.n_cols))

    # --- host <-> device boundary ------------------------------------------
    def arrays_to_params(self, arrays, fidelity_value=None):
        """Device arrays -> :class:`~orion_tpu.space.params.ParamBatch`
        (a lazy columnar sequence of structured param dicts).

        Categorical indices become category objects; a fidelity value (or the
        dim's high) is attached when the space has a fidelity dimension.
        Columns are built eagerly in one vectorized pass per dimension
        (clamp/cast semantics must be fixed at decode time); the per-trial
        dicts materialize lazily at the plugin-compat boundary — the
        steady-state producer round never builds them at all
        (``arrays_to_params_reference`` keeps the eager loop as the pinned
        equivalence reference).
        """
        host = {k: np.asarray(v) for k, v in arrays.items()}
        n = next(iter(host.values())).shape[0] if host else 0
        names, columns = [], []
        for dim in self:
            names.append(dim.name)
            if isinstance(dim, Fidelity):
                fv = int(fidelity_value if fidelity_value is not None else dim.high)
                columns.append([fv] * n)
                continue
            col = host[dim.name]
            if isinstance(dim, Categorical):
                # Lookup-table pass (dims.from_index_column): no python
                # from_index/int() call per value.
                columns.append(dim.from_index_column(col))
            elif dim.shape:
                # cast_decoded is elementwise (round-to-precision + clamp):
                # one call over the whole (n, *shape) block, then split
                # into the per-trial rows the dict view hands out.
                columns.append(list(dim.cast_decoded(col)))
            else:
                columns.append(dim.cast_column(col))
        return ParamBatch(names, columns)

    def arrays_to_params_reference(self, arrays, fidelity_value=None):
        """The retained pre-vectorization loop: per-value ``from_index`` /
        ``cast_decoded`` and an eager ``dict(zip(...))`` per trial.  NOT a
        hot path — it exists as the differential anchor
        (tests/unit/test_space_codec_diff.py) pinning
        :meth:`arrays_to_params` bit-identical to the original semantics.
        """
        host = {k: np.asarray(v) for k, v in arrays.items()}
        n = next(iter(host.values())).shape[0] if host else 0
        names, columns = [], []
        for dim in self:
            names.append(dim.name)
            if isinstance(dim, Fidelity):
                fv = int(fidelity_value if fidelity_value is not None else dim.high)
                columns.append([fv] * n)
                continue
            col = host[dim.name]
            if isinstance(dim, Categorical):
                if dim.shape:
                    columns.append([dim.from_index(row) for row in col])
                else:
                    cats = dim.categories
                    columns.append([cats[int(i)] for i in col.tolist()])
            elif dim.shape:
                columns.append([dim.cast_decoded(row) for row in col])
            else:
                columns.append(dim.cast_column(col))
        return [dict(zip(names, row)) for row in zip(*columns)] if names else []

    def params_to_cube(self, params_list):
        """Param dicts (list or :class:`ParamBatch`) -> (n, D) float32
        unit-cube rows.

        THE canonical dict->cube pipeline (``params_to_arrays`` +
        ``encode_flat_np``), factored so every observe-side caller — the
        algorithm base class, the producer's columnar cache, the
        multi-fidelity algorithms — produces bit-identical rows for the
        same params.  The columnar fast path's equivalence guarantee
        (docs/algorithms.md) leans on this single definition.
        """
        return self.encode_flat_np(self.params_to_arrays(params_list))

    def params_to_cube_reference(self, params_list):
        """Retained reference loop for :meth:`params_to_cube` (differential
        anchor; see :meth:`params_to_arrays_reference`)."""
        return self.encode_flat_np(self.params_to_arrays_reference(params_list))

    def params_to_arrays(self, params_list):
        """Param dicts -> dict of host numpy arrays (device-ready:
        jnp.asarray is a cheap upload when a jitted consumer wants them).

        Columnar fast path: a :class:`ParamBatch` input hands its columns
        over directly — zero per-trial work.  A plain list of dicts (the
        plugin-compat boundary) pays one gather pass per dimension, with
        categorical values resolved through the per-dim lookup table
        (``dims.to_index_column``) instead of a ``list.index`` per value."""
        columnar = isinstance(params_list, ParamBatch)
        out = {}
        for dim in self:
            if isinstance(dim, Fidelity):
                continue
            if columnar and params_list.has_column(dim.name):
                col = params_list.column(dim.name)
            else:
                # lint: disable=PERF001 -- plugin-compat boundary: a plain
                # dict list has no columns to pull; one gather per dim.
                col = [p[dim.name] for p in params_list]
            if isinstance(dim, Categorical):
                vals = np.asarray(dim.to_index_column(col))
            else:
                vals = np.asarray(col, dtype=float)
            out[dim.name] = vals
        return out

    def params_to_arrays_reference(self, params_list):
        """Retained pre-vectorization loop (per-value ``to_index``, one
        comprehension per dim over the dict list) — the differential anchor
        for :meth:`params_to_arrays`."""
        out = {}
        for dim in self:
            if isinstance(dim, Fidelity):
                continue
            if isinstance(dim, Categorical):
                vals = np.asarray([dim.to_index(p[dim.name]) for p in params_list])
            else:
                vals = np.asarray([p[dim.name] for p in params_list], dtype=float)
            out[dim.name] = vals
        return out

    def sample(self, key_or_seed, n=1, fidelity_value=None):
        """End-to-end prior sampling returning structured params (host list)."""
        if isinstance(key_or_seed, int):
            key = jax.random.PRNGKey(key_or_seed)
        else:
            key = key_or_seed
        u = self.sample_flat(key, n)
        return self.arrays_to_params(self.decode_flat(u), fidelity_value=fidelity_value)
