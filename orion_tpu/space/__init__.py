"""Search-space layer: dimensions, Space container, prior DSL.

Reference parity: `src/orion/algo/space.py`, `src/orion/core/io/space_builder.py`,
`src/orion/core/worker/transformer.py` (the flat codec subsumes the transformer
pipeline — see `orion_tpu/space/space.py`).
"""

from orion_tpu.space.dims import (
    Categorical,
    Dimension,
    Fidelity,
    Integer,
    NotSet,
    Real,
)
from orion_tpu.space.dsl import DSLError, build_dimension, build_space, split_marker
from orion_tpu.space.space import Space

__all__ = [
    "Categorical",
    "Dimension",
    "DSLError",
    "Fidelity",
    "Integer",
    "NotSet",
    "Real",
    "Space",
    "build_dimension",
    "build_space",
    "split_marker",
]
