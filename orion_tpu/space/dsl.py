"""Prior-expression DSL: ``name~'uniform(-3, 5)'``.

Capability parity: reference `src/orion/core/io/space_builder.py` — same
grammar (uniform/loguniform/gaussian/normal/choices/fidelity, ``discrete=``,
``shape=``, ``default_value=``, ``precision=``, branching markers ``+ - >``)
— but parsed with the ``ast`` module instead of the reference's restricted
``eval`` (`space_builder.py:53-56`), so arbitrary code can never execute.

Deviation (documented): the reference falls back to *any* ``scipy.stats``
distribution name (`space_builder.py:204-212`).  On device we support the
named priors below plus common scipy aliases; exotic scipy distributions
raise with a clear message instead of silently running on host.
"""

import ast
import re

from orion_tpu.space.dims import Categorical, Fidelity, Integer, NotSet, Real
from orion_tpu.space.space import Space

# Reference marker regex: `orion_cmdline_parser.py:88`
MARKER_RE = re.compile(r"^([\+\-\>]?)(.*)$", re.DOTALL)

_ALIASES = {
    "gaussian": "normal",
    "norm": "normal",
    "reciprocal": "loguniform",
    "log_uniform": "loguniform",
}


class DSLError(ValueError):
    """Malformed prior expression."""


def _literal(node, expr):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError) as exc:
        raise DSLError(f"Non-literal argument in prior expression {expr!r}") from exc


def _parse_call(expr):
    try:
        tree = ast.parse(expr.strip(), mode="eval")
    except SyntaxError as exc:
        raise DSLError(f"Cannot parse prior expression {expr!r}") from exc
    call = tree.body
    if not isinstance(call, ast.Call) or not isinstance(call.func, ast.Name):
        raise DSLError(f"Prior expression must be a call, got {expr!r}")
    name = call.func.id.lower()
    args = [_literal(a, expr) for a in call.args]
    kwargs = {kw.arg: _literal(kw.value, expr) for kw in call.keywords if kw.arg}
    return name, args, kwargs


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def build_dimension(name, expr):
    """Build one Dimension from ``expr`` (no branching marker)."""
    dist, args, kwargs = _parse_call(expr)
    dist = _ALIASES.get(dist, dist)
    shape = _shape_tuple(kwargs.pop("shape", None))
    default = kwargs.pop("default_value", NotSet)
    discrete = bool(kwargs.pop("discrete", False))
    precision = int(kwargs.pop("precision", 0) or 0)

    if dist == "fidelity":
        if shape:
            raise DSLError("fidelity dimensions must be scalar")
        low, high = int(args[0]), int(args[1])
        base = int(args[2]) if len(args) > 2 else int(kwargs.pop("base", 2))
        if kwargs:
            raise DSLError(f"Unknown fidelity kwargs {sorted(kwargs)}")
        if not (1 <= low <= high):
            raise DSLError(f"fidelity needs 1 <= low <= high, got ({low}, {high})")
        if base < 1:
            raise DSLError(f"fidelity base must be >= 1, got {base}")
        return Fidelity(name=name, prior_expr=expr.strip(), low=low, high=high, base=base)

    if dist == "choices":
        if len(args) == 1 and isinstance(args[0], dict):
            categories = tuple(args[0].keys())
            probs = tuple(float(p) for p in args[0].values())
            if abs(sum(probs) - 1.0) > 1e-6:
                raise DSLError(f"choices probabilities must sum to 1, got {sum(probs)}")
        elif len(args) == 1 and isinstance(args[0], (list, tuple)):
            categories, probs = tuple(args[0]), ()
        else:
            categories, probs = tuple(args), ()
        if not categories:
            raise DSLError("choices requires at least one category")
        return Categorical(
            name=name,
            prior_expr=expr.strip(),
            shape=shape,
            default_value=default,
            categories=categories,
            probs=probs,
        )

    cls = Integer if discrete else Real
    common = dict(
        name=name,
        prior_expr=expr.strip(),
        shape=shape,
        default_value=default,
        precision=precision,
    )

    if dist in ("uniform", "loguniform", "randint") and len(args) < 2:
        raise DSLError(f"{dist} requires (low, high), got {expr!r}")
    if dist == "uniform":
        low, high = float(args[0]), float(args[1])
        if low >= high:
            raise DSLError(f"uniform needs low < high, got ({low}, {high})")
        return cls(dist="uniform", low=low, high=high, **common)
    if dist == "loguniform":
        low, high = float(args[0]), float(args[1])
        if not (0 < low < high):
            raise DSLError(f"loguniform needs 0 < low < high, got ({low}, {high})")
        return cls(dist="loguniform", low=low, high=high, **common)
    if dist == "normal":
        loc = float(args[0]) if args else float(kwargs.pop("loc", 0.0))
        scale = float(args[1]) if len(args) > 1 else float(kwargs.pop("scale", 1.0))
        low = float(kwargs.pop("low", float("-inf")))
        high = float(kwargs.pop("high", float("inf")))
        if scale <= 0:
            raise DSLError(f"normal needs scale > 0, got {scale}")
        return cls(dist="normal", loc=loc, scale=scale, low=low, high=high, **common)
    if dist == "randint":
        low, high = int(args[0]), int(args[1])
        if low >= high:
            raise DSLError(f"randint needs low < high, got ({low}, {high})")
        return Integer(dist="uniform", low=low, high=high - 1, **common)

    raise DSLError(
        f"Unknown prior {dist!r} in {expr!r}. Supported: uniform, loguniform, "
        "normal/gaussian, choices, fidelity, randint (+ discrete=True variants). "
        "Arbitrary scipy.stats distributions are not supported on device."
    )


def split_marker(expr):
    """Strip a leading EVC branching marker (+ add, - remove, > rename)."""
    marker, rest = MARKER_RE.match(expr.strip()).groups()
    return marker, rest


def build_space(priors):
    """Build a Space from a {name: prior_expr} mapping (markers stripped)."""
    space = Space()
    for name, expr in priors.items():
        marker, clean = split_marker(expr)
        if marker == ">":
            # rename marker `old~>new` — handled by EVC, not a prior
            continue
        space.register(build_dimension(name, clean))
    return space
