"""ParamBatch: a lazy columnar view of a suggestion round's param dicts.

The steady-state round never needs per-trial ``{name: value}`` dicts: the
producer registers trials from columns (``core.trial.TrialBatch`` builds the
storage documents in one pass) and the observe side re-encodes through
``Space.params_to_cube``, which pulls columns straight out of this view.
Per-trial dicts exist only for the *plugin-compat boundary* — third-party
algorithms with ``observe(params_list, ...)`` overrides, ``register_suggestion``
hooks, user scripts indexing ``suggest()`` results — and materialize lazily,
one row at a time, exactly when that boundary touches them.

Equivalence contract: ``list(batch)`` is bit-identical to the eager
``[dict(zip(names, row)) for row in zip(*columns)]`` build the pre-columnar
``Space.arrays_to_params`` performed (same column values, same key order),
pinned by tests/unit/test_space_codec_diff.py.
"""

from collections.abc import Sequence


class ParamBatch(Sequence):
    """``n`` param dicts stored as per-dimension columns.

    ``names`` is the dict key order (the Space's name-sorted dimension
    order); ``columns`` one python list per name, all of length ``n``.
    Row dicts are built on demand and cached, so repeated boundary access
    (a plugin observing the same batch twice) pays the build once.
    """

    __slots__ = ("names", "columns", "_n", "_rows")

    def __init__(self, names, columns):
        self.names = tuple(names)
        self.columns = list(columns)
        self._n = len(self.columns[0]) if self.columns else 0
        self._rows = {}

    # --- columnar surface ---------------------------------------------------
    def column(self, name):
        """The raw column for dimension ``name`` (the codec fast path —
        ``Space.params_to_arrays`` pulls these instead of probing n dicts)."""
        return self.columns[self.names.index(name)]

    def has_column(self, name):
        return name in self.names

    # --- sequence-of-dicts surface (plugin-compat boundary) -----------------
    def __len__(self):
        return self._n

    def __getitem__(self, index):
        if isinstance(index, slice):
            return ParamBatch(
                self.names, [col[index] for col in self.columns]
            )
        if index < 0:
            index += self._n
        if not 0 <= index < self._n:
            raise IndexError(index)
        row = self._rows.get(index)
        if row is None:
            row = dict(zip(self.names, (col[index] for col in self.columns)))
            self._rows[index] = row
        return row

    def __iter__(self):
        for i in range(self._n):
            yield self[i]

    def materialize(self):
        """Eager list of per-trial dicts — the explicit plugin-compat exit.
        Wire layers (the serve gateway's JSON replies) and pre-columnar
        plugins call this; everything framework-internal stays columnar."""
        return list(self)

    def __add__(self, other):
        """List concat compat (plugin code does ``[seed_point] + batch``):
        concatenation is a materializing boundary by definition."""
        if isinstance(other, (list, tuple, ParamBatch)):
            return self.materialize() + list(other)
        return NotImplemented

    def __radd__(self, other):
        if isinstance(other, (list, tuple)):
            return list(other) + self.materialize()
        return NotImplemented

    def __eq__(self, other):
        if isinstance(other, ParamBatch):
            return self.names == other.names and self.columns == other.columns
        if isinstance(other, (list, tuple)):
            return len(other) == self._n and all(
                self[i] == other[i] for i in range(self._n)
            )
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __repr__(self):
        return f"ParamBatch(n={self._n}, names={list(self.names)})"
