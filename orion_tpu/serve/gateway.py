"""The suggest gateway: one long-lived process, one device, N experiments.

A :class:`GatewayServer` owns the algorithm instances for every attached
tenant and runs a **coalescing dispatcher**: suggest requests arriving
within a small window (or already queued) whose fused-step signatures
match are stacked along the tenant axis and dispatched as ONE device call
(``orion_tpu.serve.coalesce``), then demultiplexed back to per-tenant
replies — host orchestration and device dispatch are amortized across
tenants instead of being paid per experiment (ROADMAP items 2 and 4).

Discipline reused from ``storage/netdb.py``'s server: a
``ThreadingTCPServer`` whose handler threads speak the newline-framed JSON
wire (one request line, one reply line, torn lines dropped), plus an
optional rate-limited persist snapshot (atomic tempfile + rename) so a
restarted gateway resumes its tenants — here the snapshot is the tenants'
``state_dict``s, which restore history, trust-region box AND the RNG
stream, so persisted restarts keep suggestion streams intact.  Without
persist, a restart surfaces as ``UnknownTenant`` and the client-side
adapter re-attaches and replays.

**Tenancy**: per-tenant quotas (``max_inflight`` concurrent suggests,
``max_q`` rows per ask), fair-share interleaving inside the coalescer
(round-robin across tenants, so one chatty tenant cannot monopolize a
dispatch), and backpressure — a bounded admission queue and quota refusals
answer with a structured RETRY-AFTER reply the client's retry policy backs
off on.  Tenant eviction (LRU-idle, on attach overflow) and backpressure
are flight-recorder events.

**Observability**: ``serve.*`` counters/gauges/histograms through the
process-wide telemetry registry (``serve.coalesce.width``,
``serve.queue_depth``, per-tenant request latency histograms), and every
suggest reply carries a health record (tenant algorithm health + serve
fields) the client-side adapter hands to its producer's health channel —
gateway rounds thereby show up in ``orion-tpu top``/``info`` with no
storage access from the gateway itself.
"""

import base64
import copy
import logging
import os
import pickle
import queue
import socket
import socketserver
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, create_algo
from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.algo.history import _next_pow2
from orion_tpu.algo.prewarm import BucketPrewarmer
from orion_tpu.algo.tpu_bo import run_fused_plan
from orion_tpu.health import FLIGHT
from orion_tpu.serve.coalesce import (
    LAST_STACK_PLACEMENT,
    prewarm_stacked,
    run_coalesced_plans,
)
from orion_tpu.serve.fleet import (
    HANDOFF_TTL_S,
    FleetState,
    TenantStore,
    ring_key,
)
from orion_tpu.serve.protocol import (
    GATEWAY_OPS,
    GatewayError,
    dumps_line,
    error_reply,
    ok_reply,
    read_line,
)
from orion_tpu.space.dsl import build_space
from orion_tpu.storage.backends import atomic_pickle_dump
from orion_tpu.storage.netdb import ServerHandshake, _derive_key
from orion_tpu.telemetry import TELEMETRY, TraceContext

log = logging.getLogger(__name__)

#: Per-tenant ledgers are bounded: applied-id memory (observe/register
#: dedup) and the suggest reply cache only need to cover the client's
#: retry horizon, not the experiment's lifetime.
APPLIED_IDS_CAP = 4096
REPLY_CACHE_CAP = 32


class _Tenant:
    """One hosted experiment: its algorithm, quotas, ledgers, counters."""

    def __init__(self, name, space, priors, algo_config, seed, algo,
                 max_inflight, max_q):
        self.name = name
        self.space = space
        self.priors = dict(priors)
        self.algo_config = algo_config
        self.seed = seed
        self.algo = algo
        self.max_inflight = max_inflight
        self.max_q = max_q
        self.created_at = time.time()
        self.last_active = time.monotonic()
        self.inflight = 0  # mutated under the gateway lock only
        # Handoff fence (fleet mode): monotonic fence time while this
        # tenant's state is in flight to another member.  A fenced tenant
        # answers RETRY-AFTER — never a second suggestion stream.
        self.fenced = None
        self.naive_algo = None
        self.naive_epoch = None
        self.reply_cache = OrderedDict()
        self.applied_ids = set()
        self.applied_order = deque()
        self.suggests = 0
        self.observes = 0
        # Computed ONCE so the per-request hot path books its latency
        # histogram without building a metric name per call.
        self.metric_request = f"serve.tenant.{name}.request"
        # Whether register_suggestion forwarding is worth the wire bytes:
        # only algorithms that actually override the hook want it.
        self.wants_register = (
            type(algo).register_suggestion
            is not BaseAlgorithm.register_suggestion
        )

    def remember_applied(self, applied_id):
        TSAN.write("GatewayServer.tenant_ledgers", self)
        self.applied_ids.add(applied_id)
        self.applied_order.append(applied_id)
        while len(self.applied_order) > APPLIED_IDS_CAP:
            self.applied_ids.discard(self.applied_order.popleft())

    def cache_reply(self, req_id, reply):
        if not req_id:
            return
        TSAN.write("GatewayServer.tenant_ledgers", self)
        self.reply_cache[req_id] = reply
        while len(self.reply_cache) > REPLY_CACHE_CAP:
            self.reply_cache.popitem(last=False)

    def state_snapshot(self):
        """Persistable description (config + ``state_dict``): restoring it
        rebuilds the algorithm with history, box and RNG stream intact.
        The applied-id ledger rides along — a client replaying its log
        against a restored-but-stale tenant must have the already-
        snapshotted batches dedup, not double-observe.  So does the
        suggest reply cache: a client whose reply was lost to the CRASH
        re-asks the restored tenant with the same req_id and must get the
        SAME rows back, not a second RNG draw — the fleet failover's
        bit-identity hinges on it."""
        TSAN.read("GatewayServer.tenant_ledgers", self)
        return {
            "priors": dict(self.priors),
            "algo_config": self.algo_config,
            "seed": self.seed,
            "max_inflight": self.max_inflight,
            "max_q": self.max_q,
            "state": self.algo.state_dict(),
            "applied_ids": list(self.applied_order),
            "reply_cache": list(self.reply_cache.items()),
        }


class _WorkItem:
    """One queued request: payload in, reply out, a handler thread parked
    on ``done`` in between."""

    __slots__ = ("op", "tenant_name", "payload", "reply", "done", "counted",
                 "enqueued_at", "ctx")

    def __init__(self, op, payload):
        self.op = op
        self.tenant_name = str(payload.get("tenant") or "")
        self.payload = payload
        self.reply = None
        self.done = threading.Event()
        self.counted = False  # holds an inflight-quota slot
        self.enqueued_at = time.perf_counter()
        # Distributed-trace adoption: the client's injected context (only
        # present when the CLIENT ran with telemetry on) parents this
        # request's gateway-side spans and is what the coalesced dispatch
        # span links back to.  Absent/malformed -> None, zero cost.
        self.ctx = TraceContext.from_wire(payload.get("ctx"))


def _encode_snapshot(snapshot):
    """Tenant snapshot -> JSON-safe string for the handoff wire.  Pickle
    is acceptable HERE because the surface is gateway→gateway inside one
    authenticated credential domain (the mutual-HMAC handshake gates it)
    — it is never fed client input."""
    return base64.b64encode(
        pickle.dumps(snapshot, protocol=pickle.HIGHEST_PROTOCOL)
    ).decode("ascii")


def _decode_snapshot(encoded):
    return pickle.loads(base64.b64decode(str(encoded)))


#: Sentinel reply meaning "hang up instead of answering": a stopping
#: gateway must CLOSE the connection, not send an error — the client's
#: reconnect then lands on whatever replaced this gateway on the address
#: (the restart-transparency contract).
_CLOSE = object()


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        # Per-connection mutual-HMAC handshake state — the SAME
        # PBKDF2/HMAC-SHA256 challenge-response the netdb wire runs
        # (storage/netdb.py), so the two surfaces cannot drift on the
        # credential contract.  ping stays open for health probes.
        auth = ServerHandshake(self.server.auth_key)
        while True:
            try:
                request = read_line(self.rfile)
            except (ValueError, OSError) as exc:
                log.warning(
                    "bad gateway request from %s: %s", self.client_address, exc
                )
                return
            if request is None:
                return
            op = request.get("op")
            if op in ServerHandshake.AUTH_OPS:
                reply = auth.step(request)
            elif not auth.authenticated and op != "ping":
                reply = error_reply(
                    "AuthenticationError",
                    "authentication required (gateway started with a secret)",
                )
            else:
                reply = self.server.handle_request(request)
            if reply is _CLOSE:
                return
            self.wfile.write(dumps_line(reply))
            if auth.hangup:
                # Failed credential check: force a reconnect (and a fresh
                # nonce) per guess — brute force pays a TCP handshake each.
                return


class GatewayServer(socketserver.ThreadingTCPServer):
    """Serve suggest/observe traffic for many experiments over one device.

    Knobs (constructor args = `orion-tpu serve` flags = ``serve:`` config):

    - ``window``: seconds the dispatcher waits after the first queued
      suggest for more same-signature traffic to coalesce with;
    - ``max_width``: widest single coalesced dispatch (the tenant axis is
      pow-2 padded, so widths compile per bucket, not per count);
    - ``max_tenants`` / ``max_inflight`` / ``max_q`` / ``pending_limit``:
      the tenancy quotas (see module docstring);
    - ``persist`` / ``persist_interval``: optional tenant-state snapshot.
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(
        self,
        host="127.0.0.1",
        port=0,
        window=0.004,
        max_width=8,
        max_tenants=256,
        max_inflight=4,
        max_q=4096,
        pending_limit=256,
        request_timeout=120.0,
        persist=None,
        persist_interval=5.0,
        metrics_port=None,
        secret=None,
        fleet=None,
        advertise=None,
        handoff_ttl=HANDOFF_TTL_S,
    ):
        # Shared-secret authentication, reusing the netdb wire's PBKDF2
        # key stretch + mutual HMAC handshake.  None = open gateway
        # (localhost development, --no-auth).
        self.secret = secret
        self.auth_key = _derive_key(secret) if secret is not None else None
        self.window = float(window)
        self.max_width = max(1, int(max_width))
        self.max_tenants = int(max_tenants)
        self.max_inflight = int(max_inflight)
        self.max_q = int(max_q)
        self.pending_limit = int(pending_limit)
        self.request_timeout = float(request_timeout)
        self.persist = persist
        self.persist_interval = float(persist_interval)
        self._tenants = {}
        self._lock = threading.Lock()
        self._queue = queue.Queue()
        self._stop = threading.Event()
        self._dirty = False  # persist snapshot pending (dispatcher-owned)
        self._last_persist = 0.0
        self._prewarmer = BucketPrewarmer()
        self._stats = {
            "suggests": 0,
            "observes": 0,
            "dispatches": 0,
            "coalesced_dispatches": 0,
            "coalesced_suggests": 0,
            "backpressure": 0,
            "evictions": 0,
            "max_width": 0,
            "widths": {},
            "handoffs": 0,
            "handoff_failures": 0,
            "imports": 0,
            "wrong_gateway": 0,
        }
        # --- fleet mode ---------------------------------------------------
        # ``fleet`` is the member address list (this gateway included,
        # identified by ``advertise``); placement is the shared hash ring
        # (fleet.FleetState) every client computes identically.  In fleet
        # mode ``persist`` is a DIRECTORY of per-tenant snapshots
        # (TenantStore) and persistence turns SYNCHRONOUS: the round's
        # dirty tenants are written before the round's replies are
        # released, so a kill -9 can lose a reply but never an
        # acknowledged observation or a cached suggest draw.
        self.handoff_ttl = float(handoff_ttl)
        self.advertise = None
        self._fleet = None
        self._store = None
        self._moved = OrderedDict()  # tenant -> destination tombstone
        self._dirty_tenants = set()  # sync-persist worklist (dispatcher)
        self._deferred = None  # reply-release buffer while sync persisting
        self._peers = {}  # member address -> GatewayClient (handoff push)
        if fleet:
            if advertise is None:
                raise GatewayError(
                    "fleet mode needs --advertise (this gateway's own "
                    "address as the OTHER members and clients dial it)"
                )
            self._fleet = FleetState(fleet)
            try:
                self.advertise = self._fleet.addresses[
                    self._fleet.index_of(advertise)
                ]
            except ValueError:
                raise GatewayError(
                    f"advertise address {advertise!r} is not in the fleet "
                    f"member list {list(self._fleet.addresses)}"
                )
            if persist:
                self._store = TenantStore(persist)
        # Track label for this gateway's own spans: a distinct Perfetto
        # track even when the gateway runs in-process with its clients.
        self._span_track = f"gateway:{socket.gethostname()}:{os.getpid()}"
        if self._store is not None:
            self._restore_store()
        elif persist and os.path.exists(persist):
            self._restore(persist)
        super().__init__((host, int(port)), _Handler)
        # Optional pull-based metrics plane: /metrics (Prometheus text
        # exposition of the process registry) + /healthz (queue depth,
        # tenant count) on a stdlib http.server daemon thread.  A bind
        # failure fails the CONSTRUCTOR (the operator explicitly asked for
        # a scrape endpoint; a gateway silently missing its monitoring is
        # worse than one that won't start) — but never leaks the already-
        # bound gateway socket.
        self._metrics_server = None
        if metrics_port is not None:
            from orion_tpu.metrics import MetricsServer

            try:
                self._metrics_server = MetricsServer(
                    port=int(metrics_port), healthz=self._healthz_snapshot
                )
            except OSError:
                self.server_close()
                raise
            self._metrics_server.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="orion-tpu-gateway", daemon=True
        )
        self._dispatcher.start()

    def _healthz_snapshot(self):
        """The /healthz payload: liveness plus the two saturation signals
        an external prober needs (bounded queue depth, hosted tenants),
        plus the DOCTOR summary block (orion_tpu.diagnosis — a fresh pass
        over this process's registry: queue saturation, backpressure,
        retrace storms all read from local counters) so k8s-style probes
        key off diagnosis, not bare socket liveness.  Runs on the metrics
        server's handler threads — the tenant-table read rides the
        gateway lock like every other cross-thread read."""
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            tenants = len(self._tenants)
        from orion_tpu.diagnosis import doctor_summary

        return {
            "ok": True,
            "queue_depth": self._queue.qsize(),
            "tenants": tenants,
            "stopping": self._stop.is_set(),
            "doctor": doctor_summary(),
        }

    # --- lifecycle -----------------------------------------------------------
    @property
    def address(self):
        return self.server_address[:2]

    def serve_background(self):
        """Start accepting on a daemon thread; returns (host, port)."""
        thread = threading.Thread(target=self.serve_forever, daemon=True)
        thread.start()
        return self.address

    def shutdown(self):
        self._stop.set()
        super().shutdown()
        self._dispatcher.join(timeout=5.0)
        if self._metrics_server is not None:
            self._metrics_server.stop()
        for peer in self._peers.values():
            peer.close()
        # Final durable snapshot — same exit discipline as DBServer.
        if self._store is not None:
            self._persist_dirty_tenants()
        elif self.persist and self._dirty:
            self._write_snapshot()

    def kill(self):
        """Simulated crash (tests/bench): stop serving WITHOUT the final
        snapshot or any orderly reply drain — in-flight requests see their
        connections die exactly as a ``kill -9`` would leave them.  What
        survives is whatever the sync-persist discipline already put on
        disk, which is precisely the fleet's failover contract."""
        self._stop.set()
        super().shutdown()
        self.server_close()
        self._dispatcher.join(timeout=5.0)
        if self._metrics_server is not None:
            self._metrics_server.stop()
        for peer in self._peers.values():
            peer.close()

    def _tenant_from_snapshot(self, name, saved):
        """Rebuild one tenant from a persisted ``state_snapshot()`` — the
        shared restore path for boot-time snapshots, lazy store restores
        and handoff imports.  ``set_state`` reinstates history, box AND
        the RNG stream, so the rebuilt tenant's next draw is the exact
        draw the snapshotted one would have made."""
        space = build_space(saved["priors"])
        algo = create_algo(space, saved["algo_config"], seed=saved.get("seed"))
        algo.set_state(saved["state"])
        tenant = _Tenant(
            name,
            space,
            saved["priors"],
            saved["algo_config"],
            saved.get("seed"),
            algo,
            saved.get("max_inflight", self.max_inflight),
            saved.get("max_q", self.max_q),
        )
        for applied_id in saved.get("applied_ids") or ():
            tenant.remember_applied(applied_id)
        for req_id, reply in saved.get("reply_cache") or ():
            tenant.cache_reply(req_id, reply)
        return tenant

    def _restore(self, path):
        try:
            with open(path, "rb") as handle:
                snapshot = pickle.load(handle)
        except Exception:
            log.exception("could not restore gateway snapshot %s", path)
            return
        for name, saved in (snapshot.get("tenants") or {}).items():
            try:
                tenant = self._tenant_from_snapshot(name, saved)
                # _restore runs from __init__ (pre-thread), but tenant-map
                # writes stay under the lock everywhere for one invariant.
                with self._lock:
                    TSAN.write("GatewayServer._tenants", self)
                    self._tenants[name] = tenant
            except Exception:
                log.exception("could not restore tenant %r", name)
        if self._tenants:
            log.info(
                "gateway restored %d tenant(s) from %s", len(self._tenants),
                path,
            )

    def _restore_store(self):
        """Boot-time fleet restore: adopt the store's tenants THIS member
        owns per the ring.  Foreign tenants stay on disk — their owners
        restore them lazily on first touch, and eagerly adopting them
        here would fork tenants the rest of the fleet is still serving."""
        restored = 0
        for name, saved in self._store.items():
            if self._fleet.owner(ring_key(name)) != self.advertise:
                continue
            try:
                tenant = self._tenant_from_snapshot(name, saved)
            except Exception:
                log.exception("could not restore tenant %r", name)
                continue
            with self._lock:
                TSAN.write("GatewayServer._tenants", self)
                self._tenants[name] = tenant
            restored += 1
        if restored:
            log.info(
                "gateway %s restored %d owned tenant(s) from %s",
                self.advertise, restored, self._store.root,
            )

    def _write_snapshot(self):
        """Build + write the tenant snapshot atomically.  The build holds
        the gateway lock: the dispatcher owns it in steady state, but
        ``shutdown()`` (and tests) call it from OTHER threads, and the
        sanitizer flagged the bare tenant-table/ledger reads racing the
        dispatcher's mutations.  The rate limit keeps the O(history)
        ``state_dict`` walk off every round; the file write stays outside
        the lock."""
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            snapshot = {
                "tenants": {
                    name: tenant.state_snapshot()
                    for name, tenant in self._tenants.items()
                }
            }
        atomic_pickle_dump(self.persist, snapshot)
        self._dirty = False
        self._last_persist = time.monotonic()

    def _mark_dirty(self, tenant_name=None):
        """Persist bookkeeping: the legacy whole-snapshot flag plus the
        fleet store's per-tenant worklist (the sync-persist set drained
        before the cycle's replies release)."""
        self._dirty = True
        if self._store is not None and tenant_name:
            self._dirty_tenants.add(tenant_name)

    def _persist_dirty_tenants(self):
        """Write every dirty tenant's snapshot file (fleet store mode).
        Runs on the dispatcher between processing a cycle and releasing
        its replies — the write happening BEFORE the release is the whole
        durability contract: an acknowledged observation or a delivered
        suggest draw is always on disk before any client can act on it."""
        dirty, self._dirty_tenants = self._dirty_tenants, set()
        if not dirty:
            return
        for name in dirty:
            with self._lock:
                TSAN.read("GatewayServer._tenants", self)
                tenant = self._tenants.get(name)
                snapshot = tenant.state_snapshot() if tenant else None
            if snapshot is not None:
                self._store.save(name, snapshot)
        self._dirty = False
        self._last_persist = time.monotonic()

    def _maybe_persist(self):
        if self._store is not None:
            # Fleet mode persists synchronously per cycle; nothing rides
            # the rate-limited path.
            return
        if not (self.persist and self._dirty):
            return
        if time.monotonic() - self._last_persist < self.persist_interval:
            return
        self._write_snapshot()

    # --- request admission (handler threads) ---------------------------------
    def handle_request(self, request):
        if self._stop.is_set():
            # A stopping gateway hangs up rather than queueing work its
            # dispatcher will never run — the client reconnects and finds
            # the restarted gateway on this address.
            return _CLOSE
        op = request.get("op")
        if op not in GATEWAY_OPS:
            return error_reply("GatewayError", f"bad op {op!r}")
        if op == "ping":
            return ok_reply("pong")
        if op == "stats":
            return ok_reply(self.stats_snapshot())
        if op == "fleet":
            # Membership/occupancy probe: answered inline like stats (the
            # `top --all` header and the router bootstrap read it — a
            # probe must not queue behind the dispatch backlog it is
            # trying to measure).
            return ok_reply(self.fleet_snapshot())
        item = _WorkItem(op, request)
        refused = self._admit(item)
        if refused is not None:
            return refused
        if not item.done.wait(self.request_timeout):
            # A backlog the dispatcher could not drain in time is OVERLOAD,
            # not a protocol failure: answer transiently (RetryAfter) so
            # the client backs off instead of crashing its worker.  The
            # orphaned item still executes when the dispatcher reaches it
            # — safe by the same id-dedup contracts a lost reply rides:
            # the re-asked suggest hits the req_id reply cache, a re-sent
            # observe/register dedups on its minted id.
            return self._retry_after_reply(
                f"gateway did not answer {op!r} within "
                f"{self.request_timeout}s (dispatcher backlog)"
            )
        return item.reply

    def _retry_after_reply(self, message):
        delay = round(max(4 * self.window, 0.02), 3)
        if FLIGHT.enabled:
            FLIGHT.record("serve.backpressure", args={"message": message})
        TELEMETRY.count("serve.backpressure")
        return error_reply(
            "RetryAfter", message, retry_after=delay
        )

    def _admit(self, item):
        """Admission control, under the gateway lock: bounded queue +
        per-tenant inflight quota.  Returns a refusal reply, or None when
        the item was queued."""
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            if self._queue.qsize() >= self.pending_limit:
                self._stats["backpressure"] += 1
                refused = True
                message = (
                    f"gateway queue full ({self.pending_limit} pending)"
                )
            else:
                refused = False
                if item.op == "suggest":
                    tenant = self._tenants.get(item.tenant_name)
                    if tenant is not None:
                        TSAN.write("GatewayServer.tenant_counters", self)
                        if tenant.inflight >= tenant.max_inflight:
                            self._stats["backpressure"] += 1
                            refused = True
                            message = (
                                f"tenant {item.tenant_name!r} already has "
                                f"{tenant.inflight} suggest(s) in flight"
                            )
                        else:
                            tenant.inflight += 1
                            item.counted = True
                if not refused:
                    self._queue.put(item)
        if refused:
            return self._retry_after_reply(message)
        return None

    # --- the coalescing dispatcher -------------------------------------------
    def _dispatch_loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if self._stop.is_set():
                self._queue.put(first)
                break
            batch = [first]
            while True:  # opportunistic drain of everything already queued
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            if any(item.op == "suggest" for item in batch):
                # Coalescing window: wait a beat for other tenants' suggest
                # traffic to arrive so it can ride THIS device dispatch.
                deadline = time.monotonic() + self.window
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break
            TELEMETRY.set_gauge("serve.queue_depth", self._queue.qsize())
            if self._store is not None:
                # Sync-persist cycle: replies computed below are BUFFERED
                # (``_finish`` parks them on ``_deferred``) and released
                # only after the dirty tenants' snapshots hit disk.
                self._deferred = []
            try:
                self._process(batch)
            except Exception:  # pragma: no cover - per-item paths catch first
                log.exception("gateway dispatch cycle failed")
                for item in batch:
                    if not item.done.is_set():
                        self._finish(
                            item,
                            error_reply(
                                "GatewayError", "internal dispatch failure"
                            ),
                        )
            if self._deferred is not None:
                deferred, self._deferred = self._deferred, None
                try:
                    self._persist_dirty_tenants()
                finally:
                    for item in deferred:
                        item.done.set()
            self._maybe_persist()
            self._publish_fleet_gauges()
        # Stopping: anything still queued gets the hang-up sentinel so its
        # handler closes the connection and the client re-asks elsewhere.
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            self._finish(item, _CLOSE)

    def _finish(self, item, reply):
        if item.counted:
            with self._lock:
                TSAN.write("GatewayServer.tenant_counters", self)
                tenant = self._tenants.get(item.tenant_name)
                if tenant is not None:
                    tenant.inflight = max(0, tenant.inflight - 1)
            item.counted = False
        item.reply = reply
        if self._deferred is not None and reply is not _CLOSE:
            # Sync-persist deferral: the handler thread stays parked until
            # the cycle's snapshots are durable (_dispatch_loop releases).
            self._deferred.append(item)
        else:
            item.done.set()
        if TELEMETRY.enabled and item.ctx is not None:
            # The gateway-side half of the request's distributed trace:
            # queue wait + execution, parented at the client's injected
            # context, on this gateway's own track.  histogram=False — the
            # observe() below is the sample's one histogram home.
            TELEMETRY.record_span(
                "serve.request",
                start=item.enqueued_at,
                args={"op": item.op},
                parent_ctx=item.ctx,
                track=self._span_track,
                histogram=False,
            )
        TELEMETRY.observe(
            "serve.request", time.perf_counter() - item.enqueued_at
        )

    def _process(self, batch):
        suggests = []
        for item in batch:
            if item.op == "suggest":
                suggests.append(item)
                continue
            try:
                reply = self._apply(item)
            except GatewayError as exc:
                reply = error_reply(type(exc).__name__, str(exc))
            except Exception as exc:
                log.exception("gateway op %r failed", item.op)
                reply = error_reply(type(exc).__name__, str(exc))
            self._finish(item, reply)
        if suggests:
            self._run_suggests(suggests)

    # --- non-suggest ops ------------------------------------------------------
    def _apply(self, item):
        payload = item.payload
        if item.op == "fleet_set":
            return self._fleet_set(payload)
        if item.op == "handoff_import":
            return self._handoff_import(payload)
        if item.op == "attach":
            return self._attach(payload)
        if item.op == "detach":
            with self._lock:
                TSAN.write("GatewayServer._tenants", self)
                self._tenants.pop(item.tenant_name, None)
                self._moved.pop(item.tenant_name, None)
            if self._store is not None:
                self._store.delete(item.tenant_name)
            self._dirty = True
            return ok_reply({"detached": True})
        tenant, refusal = self._route(item.tenant_name, payload)
        if refusal is not None:
            return refusal
        if tenant is None:
            return error_reply(
                "UnknownTenant", f"no tenant {item.tenant_name!r} attached"
            )
        tenant.last_active = time.monotonic()
        if item.op == "observe":
            return self._observe(tenant, payload)
        if item.op == "register":
            return self._register(tenant, payload)
        return error_reply("GatewayError", f"bad op {item.op!r}")

    def _wrong_gateway_reply(self, name, owner):
        """The structured off-ring refusal: carries the authoritative
        membership + epoch so one bounce teaches the client the fleet."""
        with self._lock:
            TSAN.write("GatewayServer.tenant_counters", self)
            self._stats["wrong_gateway"] += 1
        TELEMETRY.count("serve.fleet.wrong_gateway")
        return error_reply(
            "WrongGateway",
            f"tenant {name!r} belongs to gateway {owner} "
            f"(fleet epoch {self._fleet.epoch})",
            owner=owner,
            addresses=list(self._fleet.addresses),
            epoch=self._fleet.epoch,
        )

    def _restore_tenant_from_store(self, name):
        """Lazy store restore (fleet mode): first touch of a tenant this
        member owns whose state a previous owner (or a previous life of
        this one) persisted.  Returns the installed tenant or None."""
        if self._store is None:
            return None
        saved = self._store.load(name)
        if saved is None:
            return None
        try:
            tenant = self._tenant_from_snapshot(name, saved)
        except Exception:
            log.exception("could not restore tenant %r from store", name)
            return None
        with self._lock:
            TSAN.write("GatewayServer._tenants", self)
            self._tenants[name] = tenant
            self._moved.pop(name, None)  # we hold it again: drop the tombstone
        TELEMETRY.count("serve.fleet.store_restores")
        log.info(
            "gateway %s restored tenant %r from the fleet store "
            "(n_observed=%d)", self.advertise, name, int(tenant.algo.n_observed),
        )
        return tenant

    def _route(self, name, payload):
        """Fleet-aware tenant resolution: ``(tenant, refusal_reply)``.

        The who-wins ladder (see docs/serving.md):

        1. A member HOLDING the tenant serves it whatever the ring says
           (pinned — the holder's state is the live stream), unless the
           tenant is fenced mid-handoff (RETRY-AFTER: the state is in
           flight, answering would fork the stream).
        2. A moved tombstone, or ring ownership elsewhere, answers
           ``WrongGateway`` with the authoritative membership — except
           when the client declared a ``takeover`` (its router marked the
           ring owner down; refusing would bounce the pair forever).
        3. Owned-but-absent falls through to the lazy store restore, then
           to the caller's UnknownTenant / create path."""
        TSAN.read("GatewayServer._tenants", self)
        tenant = self._tenants.get(name)
        if self._fleet is None:
            return tenant, None
        if tenant is not None:
            if tenant.fenced is not None:
                return None, self._retry_after_reply(
                    f"tenant {name!r} is fenced for a handoff"
                )
            return tenant, None
        takeover = bool(payload.get("takeover"))
        dest = self._moved.get(name)
        if dest is not None and not takeover:
            return None, self._wrong_gateway_reply(name, dest)
        owner = self._fleet.owner(ring_key(name))
        if owner != self.advertise and not takeover:
            return None, self._wrong_gateway_reply(name, owner)
        return self._restore_tenant_from_store(name), None

    def _attach(self, payload):
        name = str(payload.get("tenant") or "")
        if not name:
            return error_reply("GatewayError", "attach requires a tenant name")
        tenant, refusal = self._route(name, payload)
        if refusal is not None:
            return refusal
        if tenant is not None:
            tenant.last_active = time.monotonic()
            return ok_reply(
                {
                    "created": False,
                    "n_observed": int(tenant.algo.n_observed),
                    "wants_register": tenant.wants_register,
                }
            )
        TSAN.read("GatewayServer._tenants", self)
        if len(self._tenants) >= self.max_tenants:
            evicted = self._evict_idle()
            if not evicted:
                return self._retry_after_reply(
                    f"gateway at max_tenants={self.max_tenants} with every "
                    "tenant busy"
                )
        priors = dict(payload.get("priors") or {})
        if not priors:
            return error_reply("GatewayError", "attach requires priors")
        quotas = dict(payload.get("quotas") or {})
        space = build_space(priors)
        algo = create_algo(space, payload.get("algo"), seed=payload.get("seed"))
        tenant = _Tenant(
            name,
            space,
            priors,
            payload.get("algo"),
            payload.get("seed"),
            algo,
            # Client quotas may only tighten the server caps, never raise
            # them — the caps are the operator's protection.
            min(self.max_inflight, int(quotas.get("max_inflight") or self.max_inflight)),
            min(self.max_q, int(quotas.get("max_q") or self.max_q)),
        )
        with self._lock:
            TSAN.write("GatewayServer._tenants", self)
            self._tenants[name] = tenant
            self._moved.pop(name, None)
        self._mark_dirty(name)
        TELEMETRY.count("serve.attaches")
        log.info("gateway attached tenant %r (%s)", name, payload.get("algo"))
        return ok_reply(
            {
                "created": True,
                "n_observed": 0,
                "wants_register": tenant.wants_register,
            }
        )

    def _evict_idle(self):
        """Drop the least-recently-active tenant with nothing in flight.
        Its durable truth lives in the experiment's storage and the
        client-side replay log — eviction costs a re-attach + replay, not
        data."""
        with self._lock:
            TSAN.write("GatewayServer._tenants", self)
            idle = [t for t in self._tenants.values() if t.inflight == 0]
            if not idle:
                return None
            victim = min(idle, key=lambda t: t.last_active)
            del self._tenants[victim.name]
            self._stats["evictions"] += 1
        if self._store is not None:
            # Fleet mode: write-through before forgetting, so the next
            # touch lazily restores the full state instead of costing the
            # client a replay.
            self._store.save(victim.name, victim.state_snapshot())
        self._dirty = True
        TELEMETRY.count("serve.evictions")
        if FLIGHT.enabled:
            FLIGHT.record("serve.evict", args={"tenant": victim.name})
        log.info("gateway evicted idle tenant %r", victim.name)
        return victim

    def _observe(self, tenant, payload):
        obs_id = payload.get("obs_id")
        TSAN.read("GatewayServer.tenant_ledgers", tenant)
        if obs_id is not None and obs_id in tenant.applied_ids:
            # Applied-and-reply-lost resend: ack without re-feeding the
            # algorithm — THE convergence contract mode="always" rides on.
            return ok_reply(
                {"applied": False, "n_observed": int(tenant.algo.n_observed)}
            )
        params = payload.get("params") or []
        objectives = payload.get("objectives") or []
        if len(params) != len(objectives):
            raise GatewayError(
                f"observe carries {len(params)} params for "
                f"{len(objectives)} objectives"
            )
        results = [{"objective": float(v)} for v in objectives]
        cube = payload.get("cube")
        cube_rows = (
            np.asarray(cube, dtype=np.float32) if cube is not None else None
        )
        tenant.algo.observe(params, results, cube=cube_rows)
        # Under the gateway lock: stats_snapshot reads the counters from
        # handler threads and _write_snapshot reads the applied ledger from
        # the shutdown thread — the bare mutations were sanitizer-found
        # data races.
        with self._lock:
            if obs_id is not None:
                tenant.remember_applied(obs_id)
            TSAN.write("GatewayServer.tenant_counters", self)
            tenant.observes += 1
            self._stats["observes"] += 1
        self._mark_dirty(tenant.name)
        TELEMETRY.count("serve.observes")
        return ok_reply(
            {"applied": True, "n_observed": int(tenant.algo.n_observed)}
        )

    def _register(self, tenant, payload):
        reg_id = payload.get("reg_id")
        TSAN.read("GatewayServer.tenant_ledgers", tenant)
        if reg_id is not None and reg_id in tenant.applied_ids:
            return ok_reply({"applied": False})
        for params in payload.get("params") or []:
            tenant.algo.register_suggestion(params)
        if reg_id is not None:
            # Ledger writes ride the gateway lock (see _observe).
            with self._lock:
                tenant.remember_applied(reg_id)
        self._mark_dirty(tenant.name)
        return ok_reply({"applied": True})

    # --- fleet membership + handoff ------------------------------------------
    def fleet_snapshot(self):
        """The ``fleet`` op payload: membership, epoch, and this member's
        occupancy — what `top --all` probes once per frame and what a
        router bootstraps its ring from.  A single (non-fleet) gateway
        answers a one-member fleet so the probe path never branches."""
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            tenants = len(self._tenants)
            fenced = [t.fenced for t in self._tenants.values()
                      if t.fenced is not None]
            moved = len(self._moved)
        now = time.monotonic()
        fenced_age = max((now - f for f in fenced), default=0.0)
        if self._fleet is None:
            member = f"{self.address[0]}:{self.address[1]}"
            addresses, epoch = [member], 0
        else:
            member = self.advertise
            addresses = list(self._fleet.addresses)
            epoch = self._fleet.epoch
        return {
            "fleet": self._fleet is not None,
            "self": member,
            "addresses": addresses,
            "epoch": epoch,
            "tenants": tenants,
            "queue_depth": self._queue.qsize(),
            "fenced": len(fenced),
            "fenced_age_s": round(fenced_age, 3),
            "moved": moved,
            "handoffs": self._stats["handoffs"],
            "handoff_failures": self._stats["handoff_failures"],
        }

    def _publish_fleet_gauges(self):
        """The fleet's doctor surface: this member's tenant count under
        its stable ring index (``serve.fleet.tenants.g{i}`` — DX007 reads
        the spread) and the oldest fence age (``serve.fleet.fenced_age_s``
        — DX008's handoff-stuck signal)."""
        if self._fleet is None or not TELEMETRY.enabled:
            return
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            tenants = len(self._tenants)
            fenced = [t.fenced for t in self._tenants.values()
                      if t.fenced is not None]
        now = time.monotonic()
        index = self._fleet.index_of(self.advertise)
        TELEMETRY.set_gauge(f"serve.fleet.tenants.g{index}", float(tenants))
        TELEMETRY.set_gauge("serve.fleet.members", float(len(self._fleet.addresses)))
        TELEMETRY.set_gauge("serve.fleet.epoch", float(self._fleet.epoch))
        TELEMETRY.set_gauge(
            "serve.fleet.fenced_age_s",
            round(max((now - f for f in fenced), default=0.0), 3),
        )

    def _peer_client(self, address):
        """The gateway→gateway client for handoff pushes: one cached
        connection per peer, the SAME shared secret (a fleet is one
        credential domain), and a tight policy — a push that cannot land
        inside it unfences the tenant and keeps serving locally."""
        client = self._peers.get(address)
        if client is None:
            from orion_tpu.serve.client import GatewayClient, parse_address

            host, port = parse_address(address)
            client = GatewayClient(
                host=host, port=port, timeout=30.0, secret=self.secret,
                retry={"max_attempts": 3, "deadline": 15.0, "base_delay": 0.1},
            )
            self._peers[address] = client
        return client

    def _fleet_set(self, payload):
        """Operator membership change (`orion-tpu serve` peers / bench):
        adopt the new epoch, then hand off every held tenant the new ring
        assigns elsewhere.  Runs on the dispatcher — membership flips and
        handoffs are serialized against the request stream, so no suggest
        can interleave with a tenant's fence→export→flip."""
        if self._fleet is None:
            return error_reply(
                "GatewayError",
                "this gateway was not started in fleet mode (--fleet)",
            )
        addresses = payload.get("addresses") or []
        if not addresses:
            return error_reply("GatewayError", "fleet_set requires addresses")
        old_epoch = self._fleet.epoch if self._fleet is not None else 0
        epoch = int(payload.get("epoch") or old_epoch + 1)
        if epoch <= old_epoch and self._fleet is not None:
            return error_reply(
                "GatewayError",
                f"fleet_set epoch {epoch} is not newer than {old_epoch}",
            )
        fleet = FleetState(addresses, epoch=epoch)
        leaving = self.advertise not in fleet.addresses
        self._fleet = fleet
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            held = list(self._tenants)
        moves = []
        for name in held:
            owner = fleet.owner(ring_key(name))
            if leaving or owner != self.advertise:
                moves.append((name, owner))
        failed = []
        for name, owner in moves:
            if not self._handoff(name, owner):
                failed.append(name)
        self._publish_fleet_gauges()
        log.info(
            "gateway %s adopted fleet epoch %d (%d member(s), %d handoff(s)"
            ", %d failed)", self.advertise, epoch, len(fleet.addresses),
            len(moves), len(failed),
        )
        return ok_reply(
            {
                "epoch": epoch,
                "addresses": list(fleet.addresses),
                "moved": len(moves) - len(failed),
                "failed": failed,
                "leaving": leaving,
            }
        )

    def _handoff(self, name, destination):
        """One tenant's pinned→fenced→moved migration (the PR 13 phase
        discipline on tenant state): fence (RETRY-AFTER, the stream
        freezes), export the snapshot, push it into the destination, then
        flip (drop locally, leave a moved-tombstone answering
        ``WrongGateway``).  Any push failure unfences and keeps serving —
        the failure mode is a stale placement, never a fork."""
        with self._lock:
            TSAN.write("GatewayServer._tenants", self)
            tenant = self._tenants.get(name)
            if tenant is None:
                return True
            tenant.fenced = time.monotonic()
        snapshot = tenant.state_snapshot()
        try:
            encoded = _encode_snapshot(snapshot)
            self._peer_client(destination).request(
                "handoff_import",
                {"tenant": name, "snapshot": encoded,
                 "epoch": self._fleet.epoch},
            )
        except Exception:
            log.exception(
                "handoff of %r to %s failed; unfencing", name, destination
            )
            with self._lock:
                TSAN.write("GatewayServer._tenants", self)
                if self._tenants.get(name) is tenant:
                    tenant.fenced = None
                self._stats["handoff_failures"] += 1
            TELEMETRY.count("serve.fleet.handoff_failures")
            return False
        with self._lock:
            TSAN.write("GatewayServer._tenants", self)
            self._tenants.pop(name, None)
            self._moved[name] = destination
            while len(self._moved) > APPLIED_IDS_CAP:
                self._moved.popitem(last=False)
            self._stats["handoffs"] += 1
        self._dirty_tenants.discard(name)
        TELEMETRY.count("serve.fleet.handoffs")
        if FLIGHT.enabled:
            FLIGHT.record(
                "serve.handoff", args={"tenant": name, "to": destination}
            )
        log.info("gateway %s handed tenant %r to %s",
                 self.advertise, name, destination)
        return True

    def _handoff_import(self, payload):
        """Destination side of a handoff: rebuild the tenant from the
        pushed snapshot and make it durable HERE before acking — the
        source drops its copy on this ack, so the ack must mean 'I can
        survive my own kill with it'.  An import overwrites any local
        copy: the source's state is the authoritative stream (a racing
        fresh attach here was a client ahead of the migration; its
        observations replay and dedup against the imported ledger)."""
        name = str(payload.get("tenant") or "")
        if not name:
            return error_reply("GatewayError", "handoff_import needs a tenant")
        try:
            snapshot = _decode_snapshot(payload.get("snapshot"))
            tenant = self._tenant_from_snapshot(name, snapshot)
        except Exception as exc:
            log.exception("could not import handed-off tenant %r", name)
            return error_reply(type(exc).__name__, str(exc))
        if self._store is not None:
            self._store.save(name, snapshot)
        with self._lock:
            TSAN.write("GatewayServer._tenants", self)
            self._tenants[name] = tenant
            self._moved.pop(name, None)
            self._stats["imports"] += 1
        TELEMETRY.count("serve.fleet.imports")
        self._publish_fleet_gauges()
        log.info(
            "gateway %s imported tenant %r (n_observed=%d)",
            self.advertise or self.address, name,
            int(tenant.algo.n_observed),
        )
        return ok_reply(
            {"imported": True, "n_observed": int(tenant.algo.n_observed)}
        )

    # --- suggest execution ----------------------------------------------------
    def _run_suggests(self, items):
        """Resolve, group, coalesce, dispatch, demultiplex."""
        jobs = []
        in_cycle = {}  # (tenant, req_id) -> True: originals in THIS cycle
        deferred = []  # re-asks of an in-cycle original: answer from cache
        for item in items:
            payload = item.payload
            tenant, refusal = self._route(item.tenant_name, payload)
            if refusal is not None:
                self._finish(item, refusal)
                continue
            if tenant is None:
                self._finish(
                    item,
                    error_reply(
                        "UnknownTenant",
                        f"no tenant {item.tenant_name!r} attached",
                    ),
                )
                continue
            tenant.last_active = time.monotonic()
            req_id = payload.get("req_id")
            TSAN.read("GatewayServer.tenant_ledgers", tenant)
            cached = tenant.reply_cache.get(req_id) if req_id else None
            if cached is not None:
                # Idempotent re-ask after a lost reply: hand back the SAME
                # suggestions — no second RNG draw, no forked stream.
                with self._lock:
                    TSAN.write("GatewayServer.tenant_counters", self)
                    tenant.suggests += 1
                    self._stats["suggests"] += 1
                self._finish(item, cached)
                continue
            if req_id and in_cycle.get((tenant.name, req_id)):
                # The ORIGINAL of this re-ask is queued in this very cycle
                # (a timed-out-then-retried request): executing both would
                # draw twice.  Answer from the reply cache after the
                # original dispatches.
                deferred.append((item, tenant, req_id))
                continue
            num = int(payload.get("num", 1))
            if num > tenant.max_q:
                self._finish(
                    item,
                    error_reply(
                        "GatewayError",
                        f"suggest num={num} exceeds tenant max_q="
                        f"{tenant.max_q}",
                    ),
                )
                continue
            try:
                exec_algo = self._resolve_exec_algo(tenant, payload)
                plan_fn = getattr(exec_algo, "fused_step_plan", None)
                plan = plan_fn(num) if plan_fn is not None else None
            except Exception as exc:
                log.exception("suggest prep failed for %r", tenant.name)
                self._finish(item, error_reply(type(exc).__name__, str(exc)))
                continue
            if req_id:
                in_cycle[(tenant.name, req_id)] = True
            jobs.append(_SuggestJob(item, tenant, exec_algo, plan, num))
        fused = [job for job in jobs if job.plan is not None]
        plain = [job for job in jobs if job.plan is None]
        groups = OrderedDict()
        for job in fused:
            groups.setdefault(job.plan.signature, []).append(job)
        for group in groups.values():
            for chunk in _fair_chunks(group, self.max_width):
                self._dispatch_chunk(chunk)
        for job in plain:
            self._dispatch_plain(job)
        for item, tenant, req_id in deferred:
            reply = tenant.reply_cache.get(req_id)
            if reply is None:
                # The original errored/opted out and cached nothing: back
                # the re-ask off rather than minting a second draw here.
                reply = self._retry_after_reply(
                    f"original of re-asked suggest {req_id!r} cached no reply"
                )
            else:
                with self._lock:
                    TSAN.write("GatewayServer.tenant_counters", self)
                    tenant.suggests += 1
                    self._stats["suggests"] += 1
            self._finish(item, reply)

    def _resolve_exec_algo(self, tenant, payload):
        """The instance this suggest runs on: the real tenant algorithm, or
        — for a producer's naive round — a server-side clone rebuilt once
        per clone epoch with the round's constant-liar lies observed, so N
        suggests within one producer round share one conditioned copy
        exactly as they do locally."""
        if not payload.get("naive"):
            return tenant.algo
        epoch = int(payload.get("epoch", 0))
        if tenant.naive_algo is None or tenant.naive_epoch != epoch:
            tenant.naive_algo = copy.deepcopy(tenant.algo)
            tenant.naive_epoch = epoch
            for lie in payload.get("lies") or []:
                results = [
                    {"objective": float(v)} for v in lie.get("objectives", [])
                ]
                cube = lie.get("cube")
                cube_rows = (
                    np.asarray(cube, dtype=np.float32)
                    if cube is not None
                    else None
                )
                tenant.naive_algo.observe(
                    lie.get("params") or [], results, cube=cube_rows
                )
        return tenant.naive_algo

    def _dispatch_chunk(self, chunk):
        """One coalesced (or singleton) fused dispatch + demux."""
        width = len(chunk)
        t0 = time.perf_counter() if TELEMETRY.enabled else None
        try:
            if width == 1:
                job = chunk[0]
                # Scope retrace detection to the tenant's OWN prewarmer —
                # exactly what its _suggest_cube would pass locally; the
                # process-global fallback would let an unrelated tenant's
                # (or the stacked-axis) warm mask a genuine retrace.
                rows, state = run_fused_plan(
                    job.plan,
                    prewarmer=getattr(job.exec_algo, "_prewarmer", None),
                )
                results = [(rows, state)]
            else:
                results = run_coalesced_plans([job.plan for job in chunk])
        except Exception as exc:
            log.exception("coalesced dispatch of width %d failed", width)
            for job in chunk:
                self._finish(
                    job.item, error_reply(type(exc).__name__, str(exc))
                )
            return
        if t0 is not None:
            # The shared stacked-step dispatch belongs to EVERY coalesced
            # tenant's trace at once — it records LINKS to each request's
            # context instead of a single parent, and the trace exporter
            # draws one flow arrow per link.
            links = [job.item.ctx for job in chunk if job.item.ctx is not None]
            TELEMETRY.record_span(
                "serve.dispatch",
                start=t0,
                args={"width": width},
                links=links or None,
                track=self._span_track,
            )
        self._book_dispatch(width)
        self._maybe_prewarm_width(chunk[0], width)
        for job, (rows, state) in zip(chunk, results):
            job.exec_algo.consume_fused_step(state)
            finish = getattr(job.exec_algo, "finish_fused_rows", None)
            if finish is not None:
                # Multi-fidelity algorithms (asha_bo): raw cube rows would
                # bypass fidelity assignment and rung pre-registration —
                # the hook runs the algorithm's own point-assignment path
                # and the reply carries full params, exactly as the plain
                # dispatch would have.
                try:
                    params = finish(np.asarray(rows))
                except Exception as exc:
                    log.exception(
                        "finish_fused_rows failed for %r", job.tenant.name
                    )
                    self._finish(
                        job.item, error_reply(type(exc).__name__, str(exc))
                    )
                    continue
                self._finish_suggest(job, params=params)
            else:
                self._finish_suggest(job, cube=np.asarray(rows))

    def _dispatch_plain(self, job):
        """Non-fused suggest (random-init phase, host-scheduled algorithms,
        plugins): the universal ``suggest_batch`` entry, one tenant per
        dispatch."""
        try:
            batch = job.exec_algo.suggest_batch(job.num)
        except Exception as exc:
            log.exception("suggest failed for %r", job.tenant.name)
            self._finish(job.item, error_reply(type(exc).__name__, str(exc)))
            return
        if batch is None:
            self._finish_suggest(job, optout=True)
            return
        self._book_dispatch(1)
        if batch.cube is not None:
            self._finish_suggest(job, cube=np.asarray(batch.cube)[: job.num])
        else:
            # The wire boundary: replies are JSON, so a lazy ParamBatch
            # materializes its dicts here (list() is a no-op for the
            # host-scheduled algorithms that already produced a list).
            self._finish_suggest(job, params=list(batch.params[: job.num]))

    def _book_dispatch(self, width):
        with self._lock:
            TSAN.write("GatewayServer.tenant_counters", self)
            self._stats["dispatches"] += 1
            if width > 1:
                self._stats["coalesced_dispatches"] += 1
                self._stats["coalesced_suggests"] += width
            self._stats["max_width"] = max(self._stats["max_width"], width)
            key = str(width)
            self._stats["widths"][key] = self._stats["widths"].get(key, 0) + 1
        TELEMETRY.count("serve.dispatches")
        TELEMETRY.observe("serve.coalesce.width", width)

    def _maybe_prewarm_width(self, job, width):
        """PR-4 discipline on the tenant axis: when a dispatch fills its
        pow-2 width bucket and headroom remains, background-compile the
        next bucket so a growing coalesce width crosses on a cache hit."""
        t_pad = _next_pow2(width, floor=1)
        next_bucket = 2 * t_pad
        if width == t_pad and next_bucket <= _next_pow2(self.max_width, floor=1):
            self._prewarmer.maybe_start(
                ("stacked", next_bucket) + job.plan.signature,
                prewarm_stacked(job.plan, next_bucket),
            )

    def _finish_suggest(self, job, cube=None, params=None, optout=False):
        tenant, payload = job.tenant, job.item.payload
        if payload.get("naive"):
            # Mirror Producer._produce: the real stream advances to the
            # naive copy's — the next clone epoch must not replay keys the
            # clone already drew.
            tenant.algo.rng_key = job.exec_algo.rng_key
        result = {"optout": True} if optout else {}
        if cube is not None:
            result["cube"] = np.asarray(cube, dtype=np.float32).tolist()
        if params is not None:
            result["params"] = params
        result["health"] = self._health_fields(job)
        reply = ok_reply(result)
        with self._lock:
            if not optout:
                # Opt-outs are NOT cached: the producer's re-ask after a
                # backoff is a genuinely new question against fresher
                # state.  Cached under the gateway lock: _write_snapshot
                # reads the reply ledger from the shutdown thread.
                tenant.cache_reply(payload.get("req_id"), reply)
            TSAN.write("GatewayServer.tenant_counters", self)
            tenant.suggests += 1
            self._stats["suggests"] += 1
        TELEMETRY.count("serve.suggests")
        if TELEMETRY.enabled:
            TELEMETRY.observe(
                tenant.metric_request,
                time.perf_counter() - job.item.enqueued_at,
            )
        self._mark_dirty(tenant.name)
        self._finish(job.item, reply)

    def _health_fields(self, job):
        """Tenant-algorithm health + the serve layer's own fields — the
        record the client-side adapter surfaces through its producer's
        health channel (``orion-tpu top``/``info``)."""
        try:
            health = dict(job.exec_algo.health_record() or {})
        except Exception:  # pragma: no cover - observability never breaks serve
            health = {}
        TSAN.read("GatewayServer._tenants", self)
        health["serve_width"] = job.width
        health["serve_queue_depth"] = self._queue.qsize()
        health["serve_tenants"] = len(self._tenants)
        # Sharded-dispatch placement (serve_width-style: the serve layer's
        # own view).  Only present after a mesh-mode coalesced dispatch —
        # single-device serving keeps the record exactly as before.
        if LAST_STACK_PLACEMENT:
            health["serve_mesh_devices"] = LAST_STACK_PLACEMENT.get("devices")
            if "util_min_frac" in LAST_STACK_PLACEMENT:
                health["serve_mesh_util_min_frac"] = LAST_STACK_PLACEMENT[
                    "util_min_frac"
                ]
                health["serve_mesh_util_max_frac"] = LAST_STACK_PLACEMENT[
                    "util_max_frac"
                ]
        return health

    # --- stats ----------------------------------------------------------------
    def stats_snapshot(self):
        with self._lock:
            TSAN.read("GatewayServer._tenants", self)
            TSAN.read("GatewayServer.tenant_counters", self)
            stats = {
                key: (dict(value) if isinstance(value, dict) else value)
                for key, value in self._stats.items()
            }
            stats["tenants"] = len(self._tenants)
            stats["queue_depth"] = self._queue.qsize()
            stats["per_tenant"] = {
                name: {
                    "suggests": tenant.suggests,
                    "observes": tenant.observes,
                    "inflight": tenant.inflight,
                    "n_observed": int(tenant.algo.n_observed),
                }
                for name, tenant in self._tenants.items()
            }
        if stats["suggests"]:
            stats["dispatches_per_suggest"] = round(
                stats["dispatches"] / stats["suggests"], 4
            )
        else:
            stats["dispatches_per_suggest"] = None
        if self._fleet is not None:
            stats["fleet"] = self.fleet_snapshot()
        return stats


class _SuggestJob:
    __slots__ = ("item", "tenant", "exec_algo", "plan", "num", "width")

    def __init__(self, item, tenant, exec_algo, plan, num):
        self.item = item
        self.tenant = tenant
        self.exec_algo = exec_algo
        self.plan = plan
        self.num = num
        self.width = 1


def _fair_chunks(group, max_width):
    """Fair-share interleave: round-robin across tenants (arrival order
    within each tenant) before slicing into ``max_width`` dispatches, so a
    tenant with a deep backlog cannot push other tenants' single requests
    out of the first (widest) dispatch."""
    per_tenant = OrderedDict()
    for job in group:
        per_tenant.setdefault(job.tenant.name, deque()).append(job)
    ordered = []
    while per_tenant:
        for name in list(per_tenant):
            ordered.append(per_tenant[name].popleft())
            if not per_tenant[name]:
                del per_tenant[name]
    chunks = [
        ordered[i : i + max_width] for i in range(0, len(ordered), max_width)
    ]
    for chunk in chunks:
        for job in chunk:
            job.width = len(chunk)
    return chunks


def serve(  # pragma: no cover - CLI entry
    host="127.0.0.1", port=8777, **knobs
):
    """Blocking gateway entry point (`orion-tpu serve`)."""
    server = GatewayServer(host=host, port=port, **knobs)
    log.info("serving orion-tpu suggest gateway on %s:%s", *server.address)
    print(
        f"orion-tpu suggest gateway listening on "
        f"{server.address[0]}:{server.address[1]} "
        f"(window={server.window * 1e3:g}ms, max_width={server.max_width})"
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
