"""Cross-tenant coalescing of fused suggest steps.

Tenants whose :class:`~orion_tpu.algo.tpu_bo.FusedPlan` signatures match
(same fit-buffer pow-2 bucket, same q bucket, same static args — exactly
the key ``prewarm.plan_fused_step_bucket``'s machinery buckets on) are
stacked along a leading tenant axis and dispatched as ONE device call.

**Bit-identity is the design constraint**, not a nice-to-have: a tenant
must get the same suggestion stream whether it is served alone or coalesced
with strangers, or the gateway silently changes every hosted experiment's
trajectory.  The stacked step therefore runs ``jax.lax.map`` (a scan whose
body is the SAME per-element computation graph as the standalone jitted
call, each lane independent) — ``jax.vmap`` is deliberately NOT used
because batched linalg primitives (matmul/Cholesky over a batch axis) may
lower to different reduction orders and break float equality; measured on
CPU: ``lax.map`` is bit-identical to the standalone call, ``vmap`` is not.
The differential test (``tests/unit/test_serve.py``) pins this.

The tenant axis is padded to a pow-2 bucket (lane 0 repeated) so the
stacked jit compiles once per ``(t_pad, signature)`` instead of once per
group width — and :func:`prewarm_stacked` hands the NEXT width bucket's
compile to a :class:`~orion_tpu.algo.prewarm.BucketPrewarmer` so growing
coalesce widths hit the cache, the same discipline PR 4 built for history
buckets.  Padding lanes are discarded un-read; their computation cannot
influence real lanes (scan lanes are independent).
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec

from orion_tpu.algo.history import _next_pow2
from orion_tpu.algo.prewarm import completed_prewarm_count
from orion_tpu.algo.sharding import (
    TENANT_AXIS,
    get_mesh,
    mesh_fingerprint,
    mesh_utilization,
    tenant_spec,
)
from orion_tpu.algo.tpu_bo import _suggest_step
from orion_tpu.compiler_plane import (
    COMPILE_REGISTRY,
    fields_from_plan_signature,
    jit_cache_size,
    lowered_analysis_fn,
)
from orion_tpu.telemetry import TELEMETRY

#: Static-arg names of the stacked step — exactly ``_suggest_step``'s, so a
#: FusedPlan's ``statics`` dict splats into either entry unchanged.
_STACK_STATICS = (
    "q",
    "n_candidates",
    "kernel",
    "acq",
    "fit_steps",
    "local_frac",
    "local_sigma",
    "beta",
    "trust_region",
    "tr_perturb_dims",
    "y_transform",
    "fixed_tail_cols",
    "mesh",
)


@partial(jax.jit, static_argnames=_STACK_STATICS)
def _stacked_suggest_step(stacked, **statics):
    """T same-signature fused steps as ONE compiled computation.

    ``stacked`` is the tuple of ``_suggest_step``'s traced args, each with
    a leading tenant axis.  ``lax.map`` keeps every lane's computation
    graph identical to the standalone call — the bit-identity contract."""
    return jax.lax.map(lambda args: _suggest_step(*args, **statics), stacked)


@partial(jax.jit, static_argnames=_STACK_STATICS + ("tenant_mesh",))
def _tenant_parallel_suggest_step(stacked, *, tenant_mesh, **statics):
    """The stacked step with the TENANT axis as a mesh axis: ``shard_map``
    partitions the lanes over the devices, so one coalesced dispatch runs
    T/n lanes PER CHIP concurrently instead of scanning T lanes on one.

    Each device's local computation is ``lax.map`` over its own lanes with
    the exact standalone per-lane graph — the solve-only fit and the
    candidate scoring are the graph class the parity pins prove bit-stable
    across module variants, so the bit-identity contract holds here too
    (pinned by the sharded legs of ``tests/unit/test_sharded_parity.py``).
    ``statics['mesh']`` is None inside: a lane's candidate axis cannot also
    shard once its lane owns a single device, and XLA rejects nested
    sharding constraints under a manual (shard_map) subgroup.
    """

    def per_shard(shard):
        return jax.lax.map(lambda args: _suggest_step(*args, **statics), shard)

    return shard_map(
        per_shard,
        mesh=tenant_mesh,
        in_specs=PartitionSpec(TENANT_AXIS),
        out_specs=PartitionSpec(TENANT_AXIS),
        check_rep=False,
    )(stacked)


#: Placement of the most recent mesh-mode coalesced dispatch (metadata-only
#: reads — no transfers): the gateway's health records and the sharded
#: bench read these to surface per-device utilization (doctor rule DX006
#: fires when one device silently ends up doing all the work).
LAST_STACK_PLACEMENT = {}


def _stacked_fields(signature, t_pad, tenant_mesh):
    """Compiler-plane signature fields of one stacked dispatch: the shared
    per-lane plan signature plus the tenant-axis statics that fork the
    stacked jit's own cache (``t_pad`` bucket, tenant-parallel mode).
    Shared by the dispatch bracket and :func:`prewarm_stacked` so a warm
    and the retrace it should have covered can never disagree."""
    fields = fields_from_plan_signature(signature)
    fields["t_pad"] = int(t_pad)
    fields["tenant_mesh"] = mesh_fingerprint(tenant_mesh)
    return fields


def stack_plans(plans, t_pad=None):
    """Stack same-signature plans' input arrays along a new leading tenant
    axis, padded to ``t_pad`` (default: the pow-2 bucket of ``len(plans)``)
    by repeating lane 0 — padding lanes compile-shape filler only."""
    t_pad = t_pad or _next_pow2(len(plans), floor=1)
    lanes = [p.arrays for p in plans]
    lanes += [plans[0].arrays] * (t_pad - len(lanes))
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *lanes)


def _tenant_mesh_for(mesh, t_pad):
    """The tenant-axis mesh a ``t_pad``-wide stack dispatches over, or None
    when lanes should NOT become a mesh axis.  Only a stack wide enough to
    give every chip at least one whole lane goes tenant-parallel; a narrow
    stack keeps the plans' own 1-D candidate mesh (each lane's candidate
    work sharded over ALL devices, lanes scanned by ``lax.map`` — the
    bit-stable per-lane module, measured: a 2-D (tenants, candidates)
    compute mesh re-partitions the per-lane graph and drifts by ulps)."""
    if mesh is None or mesh.devices.size <= 1:
        return None
    if t_pad >= mesh.devices.size:
        return get_mesh(int(mesh.devices.size), TENANT_AXIS)
    return None


def run_coalesced_plans(plans, t_pad=None):
    """Dispatch same-signature :class:`FusedPlan`s as ONE device call.

    Returns ``[(rows, state), ...]`` aligned with ``plans`` — each entry
    exactly what :func:`~orion_tpu.algo.tpu_bo.run_fused_plan` would have
    returned for that plan alone (rows sliced to the plan's ``num``, the
    lane's GPState ready for ``consume_fused_step``).

    When the plans carry a multi-device mesh, the stacked tenant axis
    becomes a SECOND mesh axis: the stacked inputs lay out over it, and
    with at least one lane per device the lanes themselves execute in
    parallel via :func:`_tenant_parallel_suggest_step` — same outputs,
    bit for bit, as serving each tenant alone.
    """
    signature = plans[0].signature
    for plan in plans[1:]:
        if plan.signature != signature:
            raise ValueError(
                "cannot coalesce plans with differing fused-step signatures"
            )
    t_pad = t_pad or _next_pow2(len(plans), floor=1)
    stacked = stack_plans(plans, t_pad=t_pad)
    mesh = plans[0].statics.get("mesh")
    tenant_mesh = _tenant_mesh_for(mesh, t_pad)
    if tenant_mesh is not None:
        # One lane (or more) per device: lanes run concurrently, each on
        # its own chip with the single-device per-lane graph.
        stacked = jax.device_put(stacked, tenant_spec(tenant_mesh))
        lo, hi = mesh_utilization(tenant_mesh, *stacked[:4])
        LAST_STACK_PLACEMENT.update(
            devices=int(tenant_mesh.devices.size),
            t_pad=int(t_pad),
            tenant_parallel=True,
            util_min_frac=lo,
            util_max_frac=hi,
        )
        step_fn = _tenant_parallel_suggest_step
        dispatch_statics = dict(
            plans[0].statics, mesh=None, tenant_mesh=tenant_mesh
        )
    else:
        # No mesh, or a stack too narrow to give every chip a lane: the
        # scanned stacked step — with a mesh, each lane still shards its
        # candidate axis over ALL devices via the in-step constraints.
        if mesh is not None and mesh.devices.size > 1:
            LAST_STACK_PLACEMENT.update(
                devices=int(mesh.devices.size),
                t_pad=int(t_pad),
                tenant_parallel=False,
            )
            LAST_STACK_PLACEMENT.pop("util_min_frac", None)
            LAST_STACK_PLACEMENT.pop("util_max_frac", None)
        step_fn = _stacked_suggest_step
        dispatch_statics = dict(plans[0].statics)
    # Retrace bracket — the stacked twin of run_fused_plan's: jit cache
    # growth during the call with no prewarm completing in the window is a
    # synchronous compile THIS dispatch paid, attributed by the compiler
    # plane against the nearest prior stacked signature.
    tel_t0 = tel_before = None
    if TELEMETRY.enabled:
        tel_before = jit_cache_size(step_fn)
        tel_prewarms_before = completed_prewarm_count()
        tel_t0 = time.perf_counter()
    rows, states = step_fn(stacked, **dispatch_statics)
    if tel_t0 is not None:
        after = jit_cache_size(step_fn)
        retraced = (
            tel_before is not None
            and after is not None
            and after > tel_before
            # A prewarm completing mid-window explains the growth —
            # classify as a cached dispatch (same conservative call as
            # run_fused_plan: a coinciding genuine retrace goes uncounted
            # rather than a cache hit being booked as a stall).
            and completed_prewarm_count() == tel_prewarms_before
        )
        TELEMETRY.record_span(
            "jax.stacked.compile" if retraced else "jax.stacked.dispatch",
            start=tel_t0,
            args={"t_pad": int(t_pad), "lanes": len(plans)},
        )
        if retraced:
            TELEMETRY.count("jax.retraces")
            COMPILE_REGISTRY.record_retrace(
                "stacked",
                _stacked_fields(signature, t_pad, tenant_mesh),
                seconds=time.perf_counter() - tel_t0,
                analysis_fn=lowered_analysis_fn(
                    step_fn, stacked, dispatch_statics
                ),
            )
    out = []
    for lane, plan in enumerate(plans):
        lane_state = jax.tree.map(lambda leaf, lane=lane: leaf[lane], states)
        out.append((rows[lane][: plan.num], lane_state))
    return out


def prewarm_stacked(sample_plan, t_pad):
    """Zero-dummy compile closure for the stacked step at tenant-axis
    bucket ``t_pad`` and ``sample_plan``'s signature — hand it to a
    :class:`~orion_tpu.algo.prewarm.BucketPrewarmer` keyed by
    ``("stacked", t_pad) + sample_plan.signature`` so a growing coalesce
    width crosses its pow-2 bucket on a jit-cache hit, never a synchronous
    stall in the middle of a dispatch cycle.  Mirrors the dispatch-mode
    choice in :func:`run_coalesced_plans` so it warms the entry the real
    dispatch will hit."""
    dummies = jax.tree.map(
        lambda leaf: jnp.zeros((t_pad,) + leaf.shape, leaf.dtype),
        sample_plan.arrays,
    )
    statics = dict(sample_plan.statics)
    tenant_mesh = _tenant_mesh_for(statics.get("mesh"), t_pad)
    signature = sample_plan.signature

    def compile_fn():
        t0 = time.perf_counter()
        if tenant_mesh is None:
            _stacked_suggest_step(dummies, **statics)
        else:
            placed = jax.device_put(dummies, tenant_spec(tenant_mesh))
            _tenant_parallel_suggest_step(
                placed, tenant_mesh=tenant_mesh, **dict(statics, mesh=None)
            )
        if TELEMETRY.enabled:
            # Book the warmed signature: a later retrace at EXACTLY these
            # fields is a prewarm bug (doctor rule DX052), not a missing
            # prewarm — the fields must match the dispatch bracket's, which
            # is why both go through _stacked_fields.
            COMPILE_REGISTRY.record_prewarm(
                "stacked",
                _stacked_fields(signature, t_pad, tenant_mesh),
                seconds=time.perf_counter() - t0,
            )

    return compile_fn
