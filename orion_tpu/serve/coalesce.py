"""Cross-tenant coalescing of fused suggest steps.

Tenants whose :class:`~orion_tpu.algo.tpu_bo.FusedPlan` signatures match
(same fit-buffer pow-2 bucket, same q bucket, same static args — exactly
the key ``prewarm.plan_fused_step_bucket``'s machinery buckets on) are
stacked along a leading tenant axis and dispatched as ONE device call.

**Bit-identity is the design constraint**, not a nice-to-have: a tenant
must get the same suggestion stream whether it is served alone or coalesced
with strangers, or the gateway silently changes every hosted experiment's
trajectory.  The stacked step therefore runs ``jax.lax.map`` (a scan whose
body is the SAME per-element computation graph as the standalone jitted
call, each lane independent) — ``jax.vmap`` is deliberately NOT used
because batched linalg primitives (matmul/Cholesky over a batch axis) may
lower to different reduction orders and break float equality; measured on
CPU: ``lax.map`` is bit-identical to the standalone call, ``vmap`` is not.
The differential test (``tests/unit/test_serve.py``) pins this.

The tenant axis is padded to a pow-2 bucket (lane 0 repeated) so the
stacked jit compiles once per ``(t_pad, signature)`` instead of once per
group width — and :func:`prewarm_stacked` hands the NEXT width bucket's
compile to a :class:`~orion_tpu.algo.prewarm.BucketPrewarmer` so growing
coalesce widths hit the cache, the same discipline PR 4 built for history
buckets.  Padding lanes are discarded un-read; their computation cannot
influence real lanes (scan lanes are independent).
"""

from functools import partial

import jax
import jax.numpy as jnp

from orion_tpu.algo.history import _next_pow2
from orion_tpu.algo.tpu_bo import _suggest_step

#: Static-arg names of the stacked step — exactly ``_suggest_step``'s, so a
#: FusedPlan's ``statics`` dict splats into either entry unchanged.
_STACK_STATICS = (
    "q",
    "n_candidates",
    "kernel",
    "acq",
    "fit_steps",
    "local_frac",
    "local_sigma",
    "beta",
    "trust_region",
    "tr_perturb_dims",
    "y_transform",
    "fixed_tail_cols",
    "mesh",
)


@partial(jax.jit, static_argnames=_STACK_STATICS)
def _stacked_suggest_step(stacked, **statics):
    """T same-signature fused steps as ONE compiled computation.

    ``stacked`` is the tuple of ``_suggest_step``'s traced args, each with
    a leading tenant axis.  ``lax.map`` keeps every lane's computation
    graph identical to the standalone call — the bit-identity contract."""
    return jax.lax.map(lambda args: _suggest_step(*args, **statics), stacked)


def stack_plans(plans, t_pad=None):
    """Stack same-signature plans' input arrays along a new leading tenant
    axis, padded to ``t_pad`` (default: the pow-2 bucket of ``len(plans)``)
    by repeating lane 0 — padding lanes compile-shape filler only."""
    t_pad = t_pad or _next_pow2(len(plans), floor=1)
    lanes = [p.arrays for p in plans]
    lanes += [plans[0].arrays] * (t_pad - len(lanes))
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *lanes)


def run_coalesced_plans(plans, t_pad=None):
    """Dispatch same-signature :class:`FusedPlan`s as ONE device call.

    Returns ``[(rows, state), ...]`` aligned with ``plans`` — each entry
    exactly what :func:`~orion_tpu.algo.tpu_bo.run_fused_plan` would have
    returned for that plan alone (rows sliced to the plan's ``num``, the
    lane's GPState ready for ``consume_fused_step``).
    """
    signature = plans[0].signature
    for plan in plans[1:]:
        if plan.signature != signature:
            raise ValueError(
                "cannot coalesce plans with differing fused-step signatures"
            )
    stacked = stack_plans(plans, t_pad=t_pad)
    rows, states = _stacked_suggest_step(stacked, **plans[0].statics)
    out = []
    for lane, plan in enumerate(plans):
        lane_state = jax.tree.map(lambda leaf, lane=lane: leaf[lane], states)
        out.append((rows[lane][: plan.num], lane_state))
    return out


def prewarm_stacked(sample_plan, t_pad):
    """Zero-dummy compile closure for the stacked step at tenant-axis
    bucket ``t_pad`` and ``sample_plan``'s signature — hand it to a
    :class:`~orion_tpu.algo.prewarm.BucketPrewarmer` keyed by
    ``("stacked", t_pad) + sample_plan.signature`` so a growing coalesce
    width crosses its pow-2 bucket on a jit-cache hit, never a synchronous
    stall in the middle of a dispatch cycle."""
    dummies = jax.tree.map(
        lambda leaf: jnp.zeros((t_pad,) + leaf.shape, leaf.dtype),
        sample_plan.arrays,
    )
    statics = dict(sample_plan.statics)

    def compile_fn():
        _stacked_suggest_step(dummies, **statics)

    return compile_fn
