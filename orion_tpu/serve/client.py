"""Gateway client: wire driver + the ``BaseAlgorithm`` adapter.

:class:`GatewayClient` owns one socket to a gateway (same discipline as
``storage/netdb.py``'s driver: lock-guarded persistent connection,
idle-probe before reuse, send-phase reconnect-and-resend, read-phase loss
marked ``maybe_applied``) and runs every request under the unified
:class:`~orion_tpu.storage.retry.RetryPolicy`.

The per-op retry modes are all ``"always"`` — by construction, not by
optimism:

- **suggest** is an idempotent re-ask: the request carries a client-minted
  ``req_id`` and the gateway caches the computed reply per tenant, so a
  resend after a lost reply returns the SAME suggestions instead of
  burning a second RNG draw (and the worker registers exactly one set of
  trials).
- **observe**/**register** converge on client-minted ids: the gateway
  keeps a per-tenant applied-id ledger and acks a duplicate without
  re-feeding the algorithm, so an applied-but-reply-lost resend cannot
  double-observe.
- **attach** is a natural upsert.

:class:`RemoteAlgorithm` implements the ``BaseAlgorithm`` suggest/observe
surface over that wire, so ``Producer``/``workon`` drive a gateway tenant
transparently (config ``serve: {address: host:port}``).  Producer
semantics are mirrored exactly: its per-round deepcopy becomes a
lightweight *naive* clone that buffers constant-liar lies client-side and
ships them with the round's suggest; the gateway rebuilds its server-side
naive copy once per clone epoch, suggests from it, and syncs the RNG
stream back to the real tenant — the same sequence ``Producer._produce``
runs locally.  A gateway restart surfaces as ``UnknownTenant``; the
adapter re-attaches and replays its client-side observation log.
"""

import json
import logging
import socket
import threading
import time
import uuid
from collections import deque

import numpy as np

from orion_tpu.algo.base import BaseAlgorithm
from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.serve.fleet import (
    FLEET_MAX_HOPS,
    FLEET_RETRY_DEFAULTS,
    FleetRouter,
    parse_serve_addresses,
    ring_key,
)
from orion_tpu.serve.protocol import (
    GatewayError,
    RetryAfterError,
    UnknownTenantError,
    WrongGatewayError,
    dumps_line,
    read_line,
)
from orion_tpu.storage.netdb import perform_client_handshake
from orion_tpu.storage.retry import MODE_ALWAYS, create_retry_policy
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import AuthenticationError, DatabaseError

log = logging.getLogger(__name__)

#: Per-op client span names, precomputed so the request hot path never
#: builds a metric key — these are the CLIENT half of a distributed trace
#: hop: the gateway adopts the injected context as the parent of its
#: ``serve.request`` span, so wire time = client span − gateway span.
_CLIENT_SPAN_NAMES = {
    "suggest": "serve.client.suggest",
    "observe": "serve.client.observe",
    "register": "serve.client.register",
    "attach": "serve.client.attach",
}

#: Replay-log bound (observe/register batches, not rows).  Far beyond any
#: normal run's round count; hitting it degrades the GATEWAY-LOSS recovery
#: to the most recent batches (with a warning) — normal operation, worker
#: restarts (fresh tenant, producer re-feeds from storage) and persisted
#: gateway restarts are unaffected.
OBS_LOG_CAP = 4096


class GatewayClient:
    """Thread-safe wire client for a :class:`GatewayServer`.

    ``retry`` takes the same knobs as the ``storage.retry`` config section
    (``create_retry_policy``); the default policy is widened (more
    attempts, longer deadline) because riding out a gateway restart is a
    first-class path here, not an edge case.
    """

    def __init__(
        self, host="127.0.0.1", port=8777, timeout=60.0, idle_probe=1.0,
        retry=None, secret=None,
    ):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.idle_probe = idle_probe
        #: Shared secret for the mutual HMAC handshake (netdb discipline:
        #: runs on every fresh connection, reconnects redo it; a no-auth
        #: gateway is refused when a secret is configured — no downgrade).
        self.secret = secret
        if retry is None:
            retry = {"max_attempts": 8, "deadline": 60.0, "base_delay": 0.05}
        self._policy = create_retry_policy(retry)
        self._lock = threading.Lock()
        self._sock = None
        self._file = None
        self._last_used = 0.0
        self._ever_connected = False
        #: Socket request/response cycles + re-established connections —
        #: the same first-symptom counters the netdb driver exports.
        self.round_trips = 0
        self.reconnects = 0
        #: Backpressure refusals honored (each slept the gateway's
        #: retry_after hint before the policy re-asked).
        self.backpressure_honored = 0

    # --- wire ----------------------------------------------------------------
    def _connect(self):
        TSAN.write("GatewayClient._conn", self)
        self._close()
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        if self._ever_connected:
            self.reconnects += 1
        self._ever_connected = True
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._file = sock.makefile("rb")
        self._last_used = time.monotonic()
        if self.secret is not None:
            try:
                perform_client_handshake(
                    self._handshake_exchange, self.secret,
                    f"{self.host}:{self.port}",
                )
            except AuthenticationError:
                self._close()
                raise

    def _handshake_exchange(self, payload):
        """One raw request/response for the handshake (pre-protocol: no
        retry, no translation — a torn line is a dead connection)."""
        self._sock.sendall(payload)
        response = read_line(self._file)
        if response is None:
            raise ConnectionError("gateway closed the connection")
        return response

    def _close(self):
        TSAN.write("GatewayClient._conn", self)
        for closer in (self._file, self._sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:  # pragma: no cover
                    pass
        self._sock = self._file = None

    def close(self):
        with self._lock:
            self._close()

    def _probe_idle_connection(self):
        """Ping a connection that sat idle so a request never rides a
        half-open socket from a restarted gateway (netdb's idle-probe
        discipline — shrinks the applied-or-not window to genuinely
        in-flight losses)."""
        if self._sock is None:
            return
        if time.monotonic() - self._last_used <= self.idle_probe:
            return
        try:
            self._sock.sendall(dumps_line({"op": "ping"}))
            if read_line(self._file) is None:
                raise ConnectionError("gateway closed the connection")
            self._last_used = time.monotonic()
        except (OSError, ConnectionError, json.JSONDecodeError):
            self._close()  # the request path below reconnects fresh

    def _exchange_once(self, op, line):
        """One request/response cycle.  A send-phase failure reconnects and
        resends ONCE (the request line never fully reached the gateway — a
        torn line is dropped by its readline, so nothing was applied); a
        read-phase failure is the genuinely ambiguous in-flight loss and
        carries ``maybe_applied`` for the retry policy."""
        for attempt in range(2):
            try:
                TSAN.write("GatewayClient._conn", self)
                self._probe_idle_connection()
                if self._sock is None:
                    self._connect()
                self._sock.sendall(line)
            except (OSError, ConnectionError) as exc:
                self._close()
                if attempt:
                    error = DatabaseError(
                        f"cannot send {op!r} to gateway "
                        f"{self.host}:{self.port}: {exc}"
                    )
                    # Send phase: nothing was applied; resends are safe in
                    # every retry mode.
                    error.maybe_applied = False
                    raise error from exc
                continue
            try:
                response = read_line(self._file)
                if response is None:
                    raise ConnectionError("gateway closed the connection")
            except (OSError, ConnectionError, json.JSONDecodeError) as exc:
                self._close()
                error = DatabaseError(
                    f"connection to gateway {self.host}:{self.port} lost "
                    f"during {op!r}: {exc}"
                )
                # Read phase: the gateway may or may not have applied the
                # request — the op-level id dedup (req_id/obs_id) is what
                # makes the policy's re-ask converge.
                error.maybe_applied = True
                raise error from exc
            self._last_used = time.monotonic()
            self.round_trips += 1
            return response

    def _translate(self, op, response):
        if response.get("ok"):
            return response.get("result")
        error = response.get("error")
        message = response.get("message", "")
        if error == "RetryAfter":
            delay = float(response.get("retry_after", 0.05))
            # Under the client lock: _translate runs after request()
            # released it, and the counter is shared client state (the
            # bare increment was a sanitizer-found lost-update race).
            with self._lock:
                TSAN.write("GatewayClient._conn", self)
                self.backpressure_honored += 1
            TELEMETRY.count("serve.client.backpressure")
            # Honor the gateway's pacing hint BEFORE surfacing the
            # transient refusal — the retry policy then adds its own
            # jittered backoff on top, so a saturated gateway sees the
            # fleet thin out instead of stampede.
            time.sleep(delay)
            raise RetryAfterError(
                f"gateway backpressure on {op!r}: {message}", retry_after=delay
            )
        if error == "UnknownTenant":
            raise UnknownTenantError(message)
        if error == "WrongGateway":
            # Fleet placement refusal: fatal to the retry policy (this
            # member will keep refusing), handled by the router one level
            # up — the reply carries the authoritative membership.
            raise WrongGatewayError(
                message,
                owner=response.get("owner"),
                addresses=response.get("addresses"),
                epoch=response.get("epoch"),
            )
        if error == "AuthenticationError":
            # Fatal to the retry policy — re-sending the same credentials
            # can only repeat the refusal.
            raise AuthenticationError(message)
        raise GatewayError(f"{error}: {message}")

    def request(self, op, payload=None, mode=MODE_ALWAYS):
        """One gateway op under the retry policy.  ``mode`` declares the
        applied-or-not contract exactly as the storage layer's decorators
        do; every current op is ``"always"`` because each carries a
        client-minted id the gateway dedups on (see module docstring).

        Each attempt (including re-asks) runs as its own ``serve.client.*``
        span and injects that span's :class:`TraceContext` as the request's
        optional ``ctx`` field — the gateway adopts it, so the distributed
        merge draws client request -> gateway -> coalesced dispatch.
        Pre-upgrade gateways ignore the key."""
        body = dict(payload or {})
        body["op"] = op
        span_name = _CLIENT_SPAN_NAMES.get(op, "serve.client.request")

        def call():
            if TELEMETRY.enabled:
                with TELEMETRY.span(span_name) as span:
                    # span.ctx is None when no ambient trace exists (a bare
                    # client outside a producer round): nothing to inject.
                    ctx = span.ctx
                    if ctx is not None and ctx.sampled:
                        body["ctx"] = ctx.to_wire()
                    line = dumps_line(body)
                    with self._lock:
                        response = self._exchange_once(op, line)
            else:
                line = dumps_line(body)
                with self._lock:
                    response = self._exchange_once(op, line)
            return self._translate(op, response)

        if self._policy is None:
            return call()
        return self._policy.run(call, op=f"serve.{op}", mode=mode)

    @property
    def address(self):
        return f"{self.host}:{self.port}"

    def ping(self):
        return self.request("ping") == "pong"

    def stats(self):
        return self.request("stats")

    def fleet(self):
        return self.request("fleet")


class RemoteAlgorithm(BaseAlgorithm):
    """``BaseAlgorithm`` adapter for a gateway tenant.

    The real instance forwards observes (with replayable client-side
    logging) and re-asks suggests idempotently; the producer's per-round
    deepcopy yields a *naive clone* that buffers lies and ships them with
    its suggest requests (``naive=True`` + a clone-epoch counter, so the
    gateway rebuilds its server-side naive copy exactly once per producer
    round no matter how many suggests the round issues).
    """

    supports_async_suggest = False
    speculation_safe = False
    uses_observe_cube = True

    def __init__(
        self, space, priors, algo_config, client, tenant, seed=None,
        quotas=None, router=None,
    ):
        super().__init__(space, seed=seed)
        self._priors = dict(priors)
        self._algo_config = algo_config
        self._client = client
        self._tenant = tenant
        self._quotas = dict(quotas or {})
        # Fleet routing (None = single gateway, the pre-fleet path
        # verbatim): the router owns one client + one retry policy per
        # member; ``_resolve`` re-points ``_client`` at the ring owner
        # before every logical op, and ``_rpc``'s hop loop handles
        # WrongGateway adoption and dead-member failover.
        self._router = router
        self._ring_key = ring_key(tenant)
        self._takeover = False
        self._naive = False
        self._naive_epoch = 0
        self._lies = []
        # Shared BY REFERENCE with every naive clone: one client-side
        # ledger per tenant, whatever instance is doing the talking.
        # obs_log is the replay source for gateway restarts/evictions —
        # bounded (the gateway's ledgers are too), entries stored WITHOUT
        # their cube rows (the replay recomputes them through the same
        # Space codec, bit-identically, instead of duplicating the whole
        # observed history in RAM for the run's lifetime).
        self._shared = {
            "uid": uuid.uuid4().hex[:12],  # req_id namespace per process
            "epoch": 0,
            "seq": 0,
            "obs_log": deque(maxlen=OBS_LOG_CAP),
            "obs_log_truncated": False,
            "health": None,  # last gateway-reported health record
            "attached": False,
            "wants_register": False,
            "gateway": getattr(client, "address", None),
        }

    # --- naive-clone protocol ----------------------------------------------
    def __deepcopy__(self, memo):
        # Producer's per-round naive copy: share the wire client and the
        # durable ledgers by reference, buffer lies locally, and mint a
        # fresh clone epoch — the gateway key for "rebuild your server-side
        # naive copy from the real tenant now".
        clone = type(self).__new__(type(self))
        clone.__dict__.update(self.__dict__)
        clone._naive = True
        clone._lies = []
        self._shared["epoch"] += 1
        clone._naive_epoch = self._shared["epoch"]
        memo[id(self)] = clone
        return clone

    def _next_seq(self):
        self._shared["seq"] += 1
        return self._shared["seq"]

    # --- wire plumbing -------------------------------------------------------
    def _resolve(self):
        """Point ``_client`` at the ring owner (fleet mode).  Sets the
        takeover flag when the owner is only reachable off-ring (the real
        owner is marked down) — the next attach must declare it."""
        if self._router is None:
            return
        address, takeover = self._router.resolve(self._ring_key)
        self._takeover = takeover
        client = self._router.client(address)
        if client is not self._client:
            self._client = client
            self._shared["gateway"] = address
            self._shared["attached"] = False

    def _rpc(self, op, payload, mode=MODE_ALWAYS):
        """One logical op.  Single-gateway mode is the original PR 8
        contract: UnknownTenant -> re-attach + replay + one re-ask.  Fleet
        mode wraps that in a bounded re-resolve loop: ``WrongGateway``
        adopts the reply's membership and re-routes; a transport failure
        that exhausted the member's own retry policy marks it down and
        fails over to the ring's survivor (re-attaching there restores
        the persisted snapshot or replays the log — ledger dedup makes
        either path convergent)."""
        payload = dict(payload, tenant=self._tenant)
        hops = FLEET_MAX_HOPS if self._router is not None else 1
        last_error = None
        for _ in range(hops):
            self._resolve()
            try:
                self._ensure_attached()
                try:
                    return self._client.request(op, payload, mode=mode)
                except UnknownTenantError:
                    # Gateway restarted without persist (or evicted this
                    # tenant): re-attach and replay the client-side
                    # observation log, then re-ask the original op once.
                    log.info(
                        "gateway lost tenant %r; re-attaching and replaying "
                        "%d observation batches",
                        self._tenant,
                        len(self._shared["obs_log"]),
                    )
                    self._attach(replay=True)
                    return self._client.request(op, payload, mode=mode)
            except WrongGatewayError as exc:
                if self._router is None:
                    raise
                self._router.adopt(exc.addresses, exc.epoch)
                if exc.owner:
                    # The refusing member vouches for the owner: clear any
                    # stale down-mark so the re-resolve can reach it.
                    self._router.mark_up(exc.owner)
                self._shared["attached"] = False
                last_error = exc
                continue
            except RetryAfterError:
                # Saturation/fence backpressure that outlived the member's
                # whole policy is not death — surface it, don't fail over
                # (the tenant's state is THERE; a takeover would fork it).
                raise
            except AuthenticationError:
                raise
            except DatabaseError as exc:
                # Transport failure after the member's own policy gave up:
                # mark it down and fail over to the ring's survivor.
                if self._router is None:
                    raise
                self._router.mark_down(self._client.address)
                self._shared["attached"] = False
                TELEMETRY.count("serve.client.failovers")
                log.warning(
                    "gateway %s unreachable for tenant %r (%s); failing "
                    "over", self._client.address, self._tenant, exc,
                )
                last_error = exc
                continue
        raise last_error

    def _ensure_attached(self):
        if not self._shared["attached"]:
            self._attach(replay=bool(self._shared["obs_log"]))

    def _attach(self, replay=False):
        payload = {
            "tenant": self._tenant,
            "algo": self._algo_config,
            "priors": self._priors,
            "seed": self._seed,
            "quotas": self._quotas,
        }
        if self._takeover:
            # The ring owner is marked down and this member is the
            # live-ring fallback: declare the off-ring attach explicitly,
            # or the member (which may still believe the owner alive)
            # would answer WrongGateway and the pair would bounce.
            payload["takeover"] = True
        result = self._client.request("attach", payload, mode=MODE_ALWAYS)
        self._shared["wants_register"] = bool(result.get("wants_register"))
        behind = int(result.get("n_observed", 0)) < self._logged_observations()
        if replay and (result.get("created") or behind):
            # The gateway-side tenant is missing history (fresh after a
            # restart/eviction, or a PREVIOUS replay died partway): replay
            # every logged batch in order.  Each entry keeps its original
            # minted id, so a batch the gateway DID see (persisted ahead
            # of the log, or applied by the partial replay) dedups instead
            # of double-observing — replaying the whole log is always
            # convergent.
            for entry in self._shared["obs_log"]:
                self._client.request(
                    entry["_op"],
                    {k: v for k, v in entry.items() if k != "_op"}
                    | {"tenant": self._tenant},
                    mode=MODE_ALWAYS,
                )
        # Only a COMPLETED attach+replay counts: marking earlier would let
        # a mid-replay failure strand the tenant on truncated history (the
        # next op would skip the replay it still needs).
        self._shared["attached"] = True

    def _logged_observations(self):
        """Rows the replay log would feed a fresh tenant — the client-side
        truth the attach reply's ``n_observed`` is compared against."""
        return sum(
            len(entry["params"])
            for entry in self._shared["obs_log"]
            if entry["_op"] == "observe"
        )

    # --- BaseAlgorithm surface ----------------------------------------------
    def suggest(self, num=1):
        payload = {
            "num": int(num),
            "req_id": f"{self._shared['uid']}:{self._next_seq()}",
        }
        if self._naive:
            payload["naive"] = True
            payload["epoch"] = self._naive_epoch
            payload["lies"] = self._lies
        result = self._rpc("suggest", payload, mode=MODE_ALWAYS)
        self._shared["health"] = result.get("health")
        if result.get("optout"):
            return None
        cube = result.get("cube")
        if cube is not None:
            # Decode client-side through the SAME Space codec a standalone
            # run uses — float32 rows round-trip JSON exactly, so params
            # are bit-identical to the standalone decode.
            return self._materialize_batch(
                np.asarray(cube, dtype=np.float32)
            ).params
        return result.get("params")

    def observe(self, params_list, results, cube=None):
        if not params_list:
            return
        if cube is None:
            cube = self.space.params_to_cube(params_list)
        cube_rows = np.asarray(cube, dtype=np.float32).tolist()
        objectives = [float(r["objective"]) for r in results]
        if self._naive:
            # Constant-liar fantasies: buffered on the clone and shipped
            # with its suggest requests; the real tenant never sees them.
            self._lies.append(
                {
                    "params": [dict(p) for p in params_list],
                    "objectives": objectives,
                    "cube": cube_rows,
                }
            )
            return
        entry = {
            "_op": "observe",
            "obs_id": f"{self._shared['uid']}:{self._next_seq()}",
            "params": [dict(p) for p in params_list],
            "objectives": objectives,
        }
        self._log_entry(entry)
        self._rpc(
            "observe",
            # The wire carries the producer's already-encoded cube rows;
            # the LOG does not — a replay omits them and the gateway
            # re-encodes through the same codec, bit-identically.
            {k: v for k, v in entry.items() if k != "_op"} | {"cube": cube_rows},
            mode=MODE_ALWAYS,
        )
        self._n_observed += len(params_list)

    def _log_entry(self, entry):
        obs_log = self._shared["obs_log"]
        if len(obs_log) == obs_log.maxlen and not self._shared["obs_log_truncated"]:
            self._shared["obs_log_truncated"] = True
            log.warning(
                "tenant %r replay log reached its %d-batch cap; recovery "
                "from an UNPERSISTED gateway loss would resume with the "
                "most recent batches only",
                self._tenant,
                obs_log.maxlen,
            )
        obs_log.append(entry)

    def register_suggestion(self, params):
        # Only forwarded for algorithms that actually override the hook
        # (the gateway reports that at attach): for the fused GP family it
        # is a no-op, and shipping q param dicts per round for a no-op
        # would tax the exact hot path the gateway exists to amortize.
        if self._naive or not self._shared["wants_register"]:
            return
        entry = {
            "_op": "register",
            "reg_id": f"{self._shared['uid']}:{self._next_seq()}",
            "params": [dict(params)],
        }
        self._log_entry(entry)
        self._rpc(
            "register",
            {k: v for k, v in entry.items() if k != "_op"},
            mode=MODE_ALWAYS,
        )

    def health_record(self):
        """The gateway-reported record from the last suggest reply: the
        tenant algorithm's own health fields plus the serve-layer ones
        (``serve_width``, ``serve_queue_depth``, ``serve_tenants``) — the
        channel that makes gateway rounds visible in ``orion-tpu top`` and
        ``info`` without the gateway needing the experiment's storage."""
        health = self._shared.get("health")
        return dict(health) if health else None

    def placement(self):
        """The fleet-placement record (None in single-gateway mode): the
        gateway currently serving this tenant, the membership epoch, and
        the failover/adoption counters — the producer mirrors these into
        ``serve.client.*`` gauges so `orion-tpu top` shows where each
        experiment's tenant lives."""
        if self._router is None:
            return None
        return {
            "gateway": self._shared.get("gateway"),
            "epoch": self._router.epoch,
            "members": len(self._router.addresses),
            "failovers": self._router.failovers,
            "adoptions": self._router.adoptions,
        }

    def detach(self):
        """Explicitly release the gateway-side tenant (tests/shutdown)."""
        if self._shared["attached"]:
            self._rpc("detach", {}, mode=MODE_ALWAYS)
            self._shared["attached"] = False


def parse_address(address):
    """``host[:port]`` -> (host, port); the gateway default port is 8777."""
    host, _, port = str(address).partition(":")
    return host or "127.0.0.1", int(port) if port else 8777


def connect_remote_algorithm(
    space, priors, algo_config, serve_config, tenant, seed=None
):
    """Build a :class:`RemoteAlgorithm` from a ``serve:`` config section
    ({"address": "host:port", "retry": {...}, "quotas": {...}, "timeout":
    s, "secret"/"secret_file": ...}) and attach it eagerly so a bad
    address (or refused credential) fails at instantiation, not
    mid-hunt.  The ORION_SERVE_SECRET / ORION_SERVE_SECRET_FILE env vars
    carry the secret when the config omits it.

    A multi-member ``addresses`` list (or the ORION_SERVE_ADDRESSES env,
    comma-separated) builds the FLEET path instead: a
    :class:`~orion_tpu.serve.fleet.FleetRouter` with one client + one
    retry policy per member and consistent-hash tenant placement — the
    tenant attaches on its ring-designated gateway."""
    from orion_tpu.storage.base import resolve_wire_secret

    addresses = parse_serve_addresses(serve_config)
    secret = resolve_wire_secret(
        serve_config, env_prefix="ORION_SERVE", what="serve gateway"
    )
    timeout = float(serve_config.get("timeout", 60.0))
    router = None
    if len(addresses) > 1:
        retry = serve_config.get("retry") or dict(FLEET_RETRY_DEFAULTS)

        def factory(address):
            host, port = parse_address(address)
            return GatewayClient(
                host=host, port=port, timeout=timeout, retry=dict(retry),
                secret=secret,
            )

        router = FleetRouter(addresses, factory)
        client = router.client(router.resolve(ring_key(tenant))[0])
    else:
        host, port = parse_address(addresses[0])
        client = GatewayClient(
            host=host,
            port=port,
            timeout=timeout,
            retry=serve_config.get("retry"),
            secret=secret,
        )
    algo = RemoteAlgorithm(
        space,
        priors,
        algo_config,
        client,
        tenant,
        seed=seed,
        quotas=serve_config.get("quotas"),
        router=router,
    )
    algo._ensure_attached()
    return algo
