"""Multi-tenant suggest gateway: one device, thousands of live experiments.

ROADMAP item 4 — the serving front over the columnar suggest/observe
boundary (PR 1), the one-round-trip wire discipline (PR 2), and the pow-2
bucket machinery (PR 4).  A long-lived :class:`GatewayServer` owns the
device and the algorithm instances for N experiments; workers talk to it
through :class:`GatewayClient` / :class:`RemoteAlgorithm` (the
``BaseAlgorithm`` adapter the producer drives transparently via the
``serve: {address: ...}`` config), and concurrent suggest traffic from
tenants sharing a fused-step signature is stacked along a leading tenant
axis and dispatched as ONE device call (``orion_tpu.serve.coalesce``),
bit-identical per tenant to a standalone run.  See ``docs/serving.md``.
"""

from orion_tpu.serve.client import (  # noqa: F401
    GatewayClient,
    RemoteAlgorithm,
    connect_remote_algorithm,
)
from orion_tpu.serve.fleet import (  # noqa: F401
    FleetRouter,
    FleetState,
    TenantStore,
    parse_serve_addresses,
    ring_key,
)
from orion_tpu.serve.gateway import GatewayServer  # noqa: F401
from orion_tpu.serve.protocol import (  # noqa: F401
    GatewayError,
    RetryAfterError,
    UnknownTenantError,
    WrongGatewayError,
)
