"""Gateway wire protocol: framing, ops, and error taxonomy.

The gateway speaks the same newline-delimited JSON framing as the netdb
wire (one request line, one response line, 64MB line cap, torn lines
dropped rather than dispatched) — the framing helpers are shared with
``storage/netdb.py`` so the two wire surfaces cannot drift on the
truncation contract the client-side resend logic depends on.

Response envelope: ``{"ok": true, "result": ...}`` on success;
``{"ok": false, "error": NAME, "message": ...}`` on refusal, with two
structured refusals the client handles specially:

- ``RetryAfter`` (+ ``retry_after`` seconds): backpressure — the bounded
  queue or a per-tenant quota refused admission; NOTHING ran.  The client
  honors the hint, then surfaces a transient :class:`RetryAfterError` so
  the unified retry policy re-asks with its own jittered backoff on top.
- ``UnknownTenant``: the gateway does not know this tenant (restart
  without ``--persist``, or an eviction).  Fatal to the retry policy —
  blind resends can never converge — and handled one level up:
  :class:`~orion_tpu.serve.client.RemoteAlgorithm` re-attaches and
  replays its client-side observation log, then re-asks.
"""

# Shared framing (deliberately the netdb helpers, not a copy): newline
# framing + the torn-line-is-dropped rule are load-bearing for the
# send-phase resend contract on BOTH wire surfaces.
from orion_tpu.storage.netdb import (  # noqa: F401
    _MAX_LINE as MAX_LINE,
    _dumps as dumps_line,
    _read_line as read_line,
)
from orion_tpu.utils.exceptions import DatabaseError

#: Ops a gateway client may invoke — anything else is rejected (the wire
#: protocol is not a generic RPC surface; same rule as netdb's _DB_OPS).
#: The fleet ops: ``fleet`` (membership/occupancy probe — the `top --all`
#: header and the router's bootstrap), ``fleet_set`` (operator membership
#: change; triggers handoffs), ``handoff_import`` (gateway→gateway tenant
#: state transfer during a handoff).
GATEWAY_OPS = frozenset(
    {
        "ping", "stats", "attach", "detach", "suggest", "observe",
        "register", "fleet", "fleet_set", "handoff_import",
    }
)


class GatewayError(RuntimeError):
    """Semantic gateway refusal (bad op, over-quota q, malformed payload).

    Deliberately NOT a DatabaseError: the unified retry policy classifies
    it fatal, so a structurally-broken request fails fast instead of
    burning the backoff budget re-sending the same refusal."""


class UnknownTenantError(GatewayError):
    """The gateway has no state for this tenant — re-attach + replay."""


class WrongGatewayError(GatewayError):
    """This tenant belongs to ANOTHER fleet member (the ring says so, or
    a completed handoff left a moved-tombstone here).  Fatal to the retry
    policy — re-sending to the wrong member can never converge — and
    handled one level up: the fleet-aware client adopts the reply's
    authoritative membership (``addresses`` + ``epoch``) and re-resolves.
    """

    def __init__(self, message, owner=None, addresses=None, epoch=0):
        super().__init__(message)
        self.owner = owner
        self.addresses = list(addresses or ())
        self.epoch = int(epoch or 0)


class RetryAfterError(DatabaseError):
    """Backpressure refusal.  Transient by classification (DatabaseError
    family) and safe to re-ask in every mode: admission control refused the
    request BEFORE anything ran, so ``maybe_applied`` is always False.
    ``retry_after`` carries the gateway's pacing hint in seconds."""

    def __init__(self, message, retry_after=0.05):
        super().__init__(message)
        self.retry_after = float(retry_after)
        self.maybe_applied = False


def error_reply(error, message, **extra):
    out = {"ok": False, "error": error, "message": message}
    out.update(extra)
    return out


def ok_reply(result):
    return {"ok": True, "result": result}
