"""Gateway fleet: consistent-hash tenant routing + zero-loss handoff.

One gateway process owns one device — the fleet is how suggest serving
scales past it (ROADMAP item 2).  The machinery is the storage layer's,
lifted one plane up:

- **Placement** is PR 11's :class:`~orion_tpu.storage.shard.HashRing`
  (64 md5 vnodes per member) over the SORTED fleet address list.  Every
  client and every gateway builds the identical ring from the identical
  membership — the ring IS the agreement; there is no coordinator.  The
  ring key is the tenant's experiment identity (``name-vVERSION``, the
  part before the ``@worker`` suffix), so every worker of one experiment
  lands on the same gateway and keeps coalescing with itself.
- **Handoff** is PR 13's placement-override phase discipline reshaped for
  tenant state: pinned (the holder serves, whatever the ring says) →
  fenced (RETRY-AFTER, never a fork) → moved (a tombstone answering
  ``WrongGateway`` with the authoritative membership).  The state that
  moves is the PR 8 persist snapshot — ``state_dict`` carries history,
  trust-region box AND the RNG stream, so a migrated tenant's suggestion
  stream continues bit-identically.
- **Durability** is a :class:`TenantStore`: one pickle file per tenant in
  a (shared) persist directory, written atomically BEFORE a fleet
  gateway releases the round's replies — so the snapshot a survivor
  restores is never behind anything a client saw acknowledged, and a
  mid-stream gateway kill costs a failover, not a fork.

Client side, :class:`FleetRouter` keeps one
:class:`~orion_tpu.serve.client.GatewayClient` (its own connection, its
own retry policy) per member, so one dead gateway never serializes the
rest; a member that exhausts its policy is marked down for a cooldown and
the ring re-resolves over the survivors (the gateway admits the resulting
off-ring attach only when the client declares the takeover explicitly —
see ``docs/serving.md`` for the who-wins matrix).
"""

import hashlib
import logging
import os
import pickle
import threading
import time

from orion_tpu.storage.backends import atomic_pickle_dump
from orion_tpu.storage.shard import DEFAULT_VNODES, HashRing
from orion_tpu.utils.exceptions import DatabaseError

log = logging.getLogger(__name__)

#: Re-resolve bound for one logical op: adopt-membership hops plus
#: mark-down failovers.  Deliberately small — a routing loop longer than
#: this is a misconfigured fleet, not a transient.
FLEET_MAX_HOPS = 8

#: Seconds a marked-down member stays out of the client-side ring before
#: the router re-admits it (one failed policy run per cooldown is the
#: price of probing a still-dead gateway).
DOWN_COOLDOWN_S = 5.0

#: Fleet clients default to a TIGHTER per-gateway policy than the single
#: gateway's ride-out-the-restart default: with survivors to fail over
#: to, burning a 60s deadline on a dead member is the worse trade.
FLEET_RETRY_DEFAULTS = {"max_attempts": 4, "deadline": 10.0, "base_delay": 0.05}

#: Fenced-tenant age (seconds) past which a handoff counts as STUCK —
#: the DX008 doctor threshold and the gateway's own alarm gauge horizon.
HANDOFF_TTL_S = 30.0


def ring_key(tenant):
    """The placement key for a tenant id.

    Tenant ids are ``name-vVERSION@host:pid`` (one per worker process);
    placement strips the worker suffix so every worker of one experiment
    routes to the same gateway — co-placed workers coalesce, and a
    handoff moves the whole experiment at once."""
    return str(tenant).split("@", 1)[0]


def normalize_address(address, default_port=8777):
    """``host[:port]`` -> canonical ``host:port`` string."""
    host, _, port = str(address).partition(":")
    return f"{host or '127.0.0.1'}:{int(port) if port else default_port}"


def parse_serve_addresses(serve_config):
    """The fleet address list from a ``serve:`` config section.

    ``addresses`` (list or comma-separated string) wins over the single
    ``address``; entries are normalized and de-duplicated with order
    preserved.  A one-element result means single-gateway mode."""
    serve_config = serve_config or {}
    raw = serve_config.get("addresses")
    if raw is None:
        raw = [serve_config.get("address", "127.0.0.1:8777")]
    elif isinstance(raw, str):
        raw = [piece for piece in raw.split(",") if piece.strip()]
    addresses = []
    for entry in raw:
        normalized = normalize_address(str(entry).strip())
        if normalized not in addresses:
            addresses.append(normalized)
    if not addresses:
        raise DatabaseError("serve.addresses resolved to an empty fleet")
    return addresses


class FleetState:
    """One fleet membership epoch: the SORTED address tuple + the ring.

    Sorting is load-bearing: every party that learns the same member SET
    must compute the same ring regardless of the order it learned the
    addresses in (config file vs ``WrongGateway`` reply vs ``--fleet``
    flag)."""

    def __init__(self, addresses, epoch=0, vnodes=DEFAULT_VNODES):
        self.addresses = tuple(sorted({normalize_address(a) for a in addresses}))
        if not self.addresses:
            raise DatabaseError("a gateway fleet needs at least one member")
        self.epoch = int(epoch)
        self._ring = HashRing(self.addresses, vnodes=vnodes)

    def owner(self, key):
        """The member address owning ``key`` (a :func:`ring_key`)."""
        return self.addresses[self._ring.lookup(key)]

    def index_of(self, address):
        """The member's stable gauge index (``serve.fleet.tenants.g{i}``):
        its position in the sorted membership."""
        return self.addresses.index(normalize_address(address))

    def to_wire(self):
        return {"addresses": list(self.addresses), "epoch": self.epoch}


class TenantStore:
    """Per-tenant snapshot files in a persist directory.

    One atomic pickle per tenant (PR 8's tempfile+rename discipline,
    sliced per tenant so a fleet gateway can write ONLY the round's dirty
    tenants before releasing the round's replies).  Filenames are the
    md5 of the tenant id — ids carry ``@host:pid`` — with the real name
    stored inside the payload, so a boot-time scan can re-key the
    directory without trusting the filesystem encoding."""

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, name):
        digest = hashlib.md5(str(name).encode("utf-8")).hexdigest()
        return os.path.join(self.root, f"tenant-{digest}.pkl")

    def save(self, name, snapshot):
        atomic_pickle_dump(
            self._path(name), {"tenant": str(name), "snapshot": snapshot}
        )

    def load(self, name):
        """The stored snapshot for ``name``, or None (missing/corrupt —
        a torn write cannot happen by construction, but a partial disk is
        a restore miss, never a crash)."""
        try:
            with open(self._path(name), "rb") as handle:
                payload = pickle.load(handle)
        except FileNotFoundError:
            return None
        except Exception:  # pragma: no cover - corrupt snapshot file
            log.exception("could not load tenant snapshot for %r", name)
            return None
        return payload.get("snapshot")

    def delete(self, name):
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            pass

    def items(self):
        """Yield ``(tenant_name, snapshot)`` for every stored tenant —
        the boot-time restore scan."""
        try:
            entries = sorted(os.listdir(self.root))
        except FileNotFoundError:  # pragma: no cover - root raced away
            return
        for entry in entries:
            if not (entry.startswith("tenant-") and entry.endswith(".pkl")):
                continue
            try:
                with open(os.path.join(self.root, entry), "rb") as handle:
                    payload = pickle.load(handle)
                yield str(payload["tenant"]), payload["snapshot"]
            except Exception:  # pragma: no cover - corrupt snapshot file
                log.exception("skipping unreadable tenant snapshot %s", entry)


class FleetRouter:
    """Client-side fleet view: per-member clients, liveness, the ring.

    ``client_factory(address)`` builds the per-member
    :class:`~orion_tpu.serve.client.GatewayClient` lazily — each member
    gets its OWN connection and its OWN retry policy, so a dead member
    costs its own policy's deadline once, not every request's.
    """

    def __init__(self, addresses, client_factory, epoch=0,
                 vnodes=DEFAULT_VNODES, down_cooldown=DOWN_COOLDOWN_S):
        self._client_factory = client_factory
        self._vnodes = vnodes
        self._down_cooldown = float(down_cooldown)
        self._lock = threading.Lock()
        self._clients = {}
        self._down = {}  # address -> monotonic mark-down time
        self._state = FleetState(addresses, epoch=epoch, vnodes=vnodes)
        self.failovers = 0
        self.adoptions = 0

    @property
    def epoch(self):
        return self._state.epoch

    @property
    def addresses(self):
        return self._state.addresses

    def client(self, address):
        address = normalize_address(address)
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = self._clients[address] = self._client_factory(address)
            return client

    def resolve(self, key):
        """``(owner_address, takeover)`` for a ring key.

        The ring is computed over LIVE members only; ``takeover`` is True
        when the full-membership owner is currently marked down — the
        client must then declare the off-ring attach explicitly, or the
        fallback gateway (which still believes the owner alive) would
        answer ``WrongGateway`` and the pair would bounce forever."""
        with self._lock:
            state = self._state
            now = time.monotonic()
            for address, since in list(self._down.items()):
                if now - since >= self._down_cooldown:
                    del self._down[address]  # cooldown over: re-probe it
            live = [a for a in state.addresses if a not in self._down]
        if not live or len(live) == len(state.addresses):
            return state.owner(key), False
        full_owner = state.owner(key)
        live_owner = FleetState(live, epoch=state.epoch,
                                vnodes=self._vnodes).owner(key)
        return live_owner, live_owner != full_owner

    def mark_down(self, address):
        with self._lock:
            self._down[normalize_address(address)] = time.monotonic()
            self.failovers += 1

    def mark_up(self, address):
        with self._lock:
            self._down.pop(normalize_address(address), None)

    def adopt(self, addresses, epoch):
        """Adopt a gateway-reported membership (a ``WrongGateway`` reply
        or a ``fleet`` probe).  Epoch-guarded: a stale gateway cannot roll
        the client back to a membership the fleet already left."""
        if not addresses:
            return False
        epoch = int(epoch or 0)
        with self._lock:
            if epoch < self._state.epoch:
                return False
            candidate = FleetState(addresses, epoch=epoch, vnodes=self._vnodes)
            if (candidate.addresses == self._state.addresses
                    and epoch == self._state.epoch):
                return False
            self._state = candidate
            self.adoptions += 1
            return True

    def close(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for client in clients:
            try:
                client.close()
            except Exception:  # pragma: no cover - teardown best effort
                pass
