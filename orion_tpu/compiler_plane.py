"""The compiler plane: a process-wide registry of XLA compilations.

Every other plane of the stack is observable (spans, /metrics, health
records, flight events, doctor) but the plane that actually decides TPU
performance — the XLA compiler — was a black box: ``jax.retraces`` counted
synchronous stalls without ever explaining *which static changed*, compile
storms at bucket crossings had no budget, and nobody recorded what each
:class:`~orion_tpu.algo.tpu_bo.FusedPlan`'s compiled executable costs in
FLOPs and HBM bytes (ROADMAP item 1's "extend the q-scaling curve into the
HBM-bound regime" was unanswerable without hardware).  This module makes
the compiler a first-class telemetry plane:

- :class:`CompileRegistry` records, for every fused-plan/stacked/append jit
  compilation, the full static-arg **signature** (a flat field dict —
  ``fit_bucket``, ``width``, ``q``, every static), the compile wall time (a
  ``jax.compile`` span with the signature in args, histogrammed as
  ``jax.compile`` → ``orion_tpu_jax_compile_seconds`` on /metrics, counted
  as ``jax.compiles`` → ``..._jax_compiles_total``), and — lazily, on cold
  paths only — the compiled artifact's ``cost_analysis()`` /
  ``memory_analysis()`` numbers (FLOPs, bytes accessed, argument/output/
  temp/generated-code bytes → a per-plan **HBM footprint** and a predicted
  HBM-bound q for the current device).

- **Retrace attribution**: on every retrace the registry diffs the new
  signature against the nearest prior signature in the same plan family and
  emits a flight ``jax.retrace`` event (mirrored into the spans channel as
  ``flight.jax.retrace``) naming the changed statics — ``fit_bucket
  64→128``, ``q 256→512``, ``warm True→False`` — so ``retraces_after_warm
  == 0`` failures are self-diagnosing.  Prewarm completions record the
  signature they warmed, so an attributed retrace also says whether prewarm
  *should* have covered it (``jax.retraces.prewarm_covered`` — a firing
  count is a prewarm bug, doctor rule DX052).

Cost discipline: ``cost_analysis()`` via AOT ``lower().compile()`` is a
SECOND full XLA compile of the signature, so it must never run on the
synchronous suggest path or a /metrics scrape.  The registry stores a
zero-arg ``analysis_fn`` per entry and runs it only from declared cold
paths (:meth:`CompileRegistry.analyze_all` — bench, ``orion-tpu profile``,
tests).  Lint rule PERF003 pins exactly this: compiler introspection
outside this module is flagged.

Zero-overhead-when-disabled: every ``record_*`` mutator early-returns on
one ``TELEMETRY.enabled`` attribute read, allocating nothing — the same
discipline the telemetry registry itself keeps (TEL003/TEL004).
"""

import sys
import threading
from contextlib import contextmanager

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.health import FLIGHT
from orion_tpu.telemetry import TELEMETRY

#: Diff rendering order: the fields operators reason about first (the
#: pow-2 buckets and the warm flag) lead; everything else is alphabetical.
_FIELD_PRIORITY = ("fit_bucket", "width", "q", "warm", "fit_steps")

#: Fallback per-device HBM capacities by ``device_kind`` substring, used
#: when ``device.memory_stats()`` exposes no ``bytes_limit`` (interop
#: backends).  Sources: the public TPU system architecture tables.
_HBM_CAPACITY_BY_KIND = (
    ("v5e", 16e9),
    ("v5p", 95e9),
    ("v4", 32e9),
    ("v3", 32e9),
    ("v2", 16e9),
    ("v6e", 32e9),
)


def signature_fields(shape, statics):
    """Flatten a fused-plan-style signature — the ``(x.shape, statics)``
    pair the coalescer and prewarmer already key on — into the registry's
    comparable field dict: ``fit_bucket``/``width`` from the fit-buffer
    shape, every static stringified exactly as the plan signature does
    (``str(v)``), so a prewarm-recorded signature and the retrace-recorded
    one can never disagree on formatting."""
    fields = {"fit_bucket": int(shape[0]), "width": int(shape[1])}
    items = statics.items() if isinstance(statics, dict) else statics
    for key, value in items:
        fields[str(key)] = value if isinstance(value, str) else str(value)
    return fields


def fields_from_plan_signature(signature):
    """Field dict from a :class:`FusedPlan`'s ``signature`` attribute
    (``(tuple(x.shape), tuple(sorted((k, str(v)) ...)))``)."""
    shape, items = signature
    return signature_fields(shape, items)


def _field_order(key):
    try:
        return (_FIELD_PRIORITY.index(key), key)
    except ValueError:
        return (len(_FIELD_PRIORITY), key)


def diff_fields(old, new):
    """``["fit_bucket 64→128", ...]`` — every field differing between two
    signature field dicts, priority fields first."""
    changed = []
    for key in sorted(set(old) | set(new), key=_field_order):
        a, b = old.get(key), new.get(key)
        if a != b:
            changed.append(f"{key} {a}→{b}")
    return changed


def format_fields(fields):
    """One-line signature rendering for span args and tables."""
    return " ".join(
        f"{k}={fields[k]}" for k in sorted(fields, key=_field_order)
    )


def _fields_key(fields):
    return tuple(sorted(fields.items()))


def jit_cache_size(fn):
    """Entry count of a jitted function's call cache via the private
    ``_cache_size`` accessor, or None when unavailable — the shared probe
    behind every retrace bracket (growth during a call = a compile)."""
    accessor = getattr(fn, "_cache_size", None)
    if accessor is None:
        return None
    try:
        return accessor()
    except Exception:  # private jax API — degrade, never raise
        return None


def analysis_from_compiled(compiled):
    """Cost/memory numbers off one ``Compiled`` executable, every field
    None-degrading (interop backends return None or partial dicts).

    Returns ``{"flops", "bytes_accessed", "argument_bytes", "output_bytes",
    "temp_bytes", "generated_code_bytes", "hbm_bytes"}`` — ``hbm_bytes``
    is the per-plan HBM footprint: arguments + outputs + temporaries +
    generated code, i.e. what the executable pins while running."""
    out = {
        "flops": None,
        "bytes_accessed": None,
        "argument_bytes": None,
        "output_bytes": None,
        "temp_bytes": None,
        "generated_code_bytes": None,
        "hbm_bytes": None,
    }
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # one entry per device
            cost = cost[0] if cost else None
        if cost:
            flops = cost.get("flops")
            out["flops"] = float(flops) if flops is not None else None
            accessed = cost.get("bytes accessed")
            out["bytes_accessed"] = (
                float(accessed) if accessed is not None else None
            )
    except Exception:  # pragma: no cover - backend quirk, degrade
        pass
    try:
        mem = compiled.memory_analysis()
        if isinstance(mem, (list, tuple)):
            mem = mem[0] if mem else None
        if mem is not None:
            pairs = (
                ("argument_bytes", "argument_size_in_bytes"),
                ("output_bytes", "output_size_in_bytes"),
                ("temp_bytes", "temp_size_in_bytes"),
                ("generated_code_bytes", "generated_code_size_in_bytes"),
            )
            total = 0.0
            seen = False
            for field, attr in pairs:
                value = getattr(mem, attr, None)
                if value is None:
                    continue
                out[field] = float(value)
                total += float(value)
                seen = True
            if seen:
                out["hbm_bytes"] = total
    except Exception:  # pragma: no cover - backend quirk, degrade
        pass
    return out


def lowered_analysis_fn(jitted, arrays, statics):
    """Zero-arg cold-path analysis closure for a jit call site.

    Captures ``ShapeDtypeStruct`` specs (never the arrays — an analysis
    entry must not pin device buffers) and, when invoked, pays the AOT
    ``lower().compile()`` — a SECOND full XLA compile of the signature,
    which is exactly why this closure only ever runs from
    :meth:`CompileRegistry.analyze_all` on declared cold paths."""
    import jax

    specs = jax.tree.map(
        lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype), tuple(arrays)
    )
    statics = dict(statics)

    def analyze():
        compiled = jitted.lower(*specs, **statics).compile()
        return analysis_from_compiled(compiled)

    return analyze


def device_hbm_capacity(device=None):
    """Accelerator memory capacity in bytes for ``device`` (default: the
    first local device), or None when unknowable (CPU interop backends) —
    the denominator of the HBM-headroom line and doctor rule DX053."""
    try:
        import jax

        device = device if device is not None else jax.devices()[0]
    except Exception:
        return None
    stats = getattr(device, "memory_stats", None)
    if callable(stats):
        try:
            limit = (stats() or {}).get("bytes_limit")
            if limit:
                return int(limit)
        except Exception:
            pass
    kind = str(getattr(device, "device_kind", "")).lower()
    for needle, capacity in _HBM_CAPACITY_BY_KIND:
        if needle in kind:
            return int(capacity)
    return None


def predict_hbm_bound_q(fields, hbm_bytes, capacity):
    """Predicted q at which this plan's HBM footprint fills the device.

    The fused step's dominant buffers (candidate pool, q-batch posterior
    draws, temporaries) scale ~linearly in q at fixed history bucket, so
    ``q_bound ≈ q · capacity / hbm_bytes`` extrapolates the measured
    footprint to the HBM-bound regime — the answer to ROADMAP item 1's
    open tail, without hardware.  None when any input is unknown."""
    if not hbm_bytes or not capacity:
        return None
    try:
        q = int(fields.get("q"))
    except (TypeError, ValueError):
        return None
    if q <= 0:
        return None
    return int(q * float(capacity) / float(hbm_bytes))


class _Entry:
    """One recorded compilation: family + signature fields + wall seconds
    + the lazy cost/memory analysis."""

    __slots__ = ("family", "fields", "seconds", "kind", "analysis_fn", "cost")

    def __init__(self, family, fields, seconds, kind, analysis_fn):
        self.family = family
        self.fields = dict(fields)
        self.seconds = seconds
        self.kind = kind
        self.analysis_fn = analysis_fn
        self.cost = None

    def as_dict(self):
        out = {
            "family": self.family,
            "kind": self.kind,
            "signature": format_fields(self.fields),
            "compile_ms": (
                round(self.seconds * 1e3, 3) if self.seconds is not None else None
            ),
        }
        cost = self.cost or {}
        out["flops"] = cost.get("flops")
        out["bytes_accessed"] = cost.get("bytes_accessed")
        out["hbm_bytes"] = cost.get("hbm_bytes")
        return out


class CompileRegistry:
    """Process-wide record of jit compilations, keyed by plan family.

    Families are the stack's jit call sites: ``fused_plan`` (the fused
    suggest step), ``stacked`` (the gateway's coalesced dispatch),
    ``append`` (the device-history append twins).  Thread-safe — prewarm
    compiles record from their background threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []
        self._warmed = {}
        self._cost_cache = {}
        self._retraces = 0
        self._attributed = 0
        self._prewarm_covered = 0

    # --- recording (hot-path adjacent: one enabled check, then cold) -----
    def record_compile(self, family, fields, seconds=None, kind="compile",
                       analysis_fn=None):
        """Book one compilation.  Emits the ``jax.compiles`` counter and a
        ``jax.compile`` span carrying the plan signature in args (the span
        feeds the ``jax.compile`` histogram → compile_seconds on
        /metrics).  Returns the entry, or None when telemetry is off."""
        if not TELEMETRY.enabled:
            return None
        entry = _Entry(family, fields, seconds, kind, analysis_fn)
        with self._lock:
            TSAN.write("CompileRegistry._entries", self)
            self._entries.append(entry)
        TELEMETRY.count("jax.compiles")
        TELEMETRY.record_span(
            "jax.compile",
            duration=seconds or 0.0,
            args={
                "family": family,
                "kind": kind,
                "signature": format_fields(entry.fields),
            },
        )
        return entry

    def record_prewarm(self, family, fields, seconds=None, analysis_fn=None):
        """Book a background prewarm compile AND remember the signature it
        warmed — a later retrace at this exact signature is a prewarm bug
        (the warm should have made it a jit-cache hit)."""
        if not TELEMETRY.enabled:
            return None
        entry = self.record_compile(
            family, fields, seconds=seconds, kind="prewarm",
            analysis_fn=analysis_fn,
        )
        if entry is not None:
            with self._lock:
                TSAN.write("CompileRegistry._entries", self)
                self._warmed.setdefault(family, set()).add(
                    _fields_key(entry.fields)
                )
        return entry

    def record_retrace(self, family, fields, seconds=None, analysis_fn=None):
        """Book a synchronous retrace WITH attribution.

        Diffs ``fields`` against the nearest prior signature in the same
        family (fewest differing fields; ties go to the most recent) and
        emits a flight ``jax.retrace`` event naming the changed statics.
        Counts ``jax.retraces.attributed`` (the smoke gate's invariant:
        every ``jax.retraces`` sample must have a twin here) and
        ``jax.retraces.prewarm_covered`` when a completed prewarm recorded
        this exact signature.  Returns the attribution dict."""
        if not TELEMETRY.enabled:
            return None
        entry = _Entry(family, fields, seconds, "retrace", analysis_fn)
        with self._lock:
            TSAN.write("CompileRegistry._entries", self)
            nearest = None
            nearest_diff = None
            for prior in reversed(self._entries):
                if prior.family != family:
                    continue
                candidate = diff_fields(prior.fields, entry.fields)
                if nearest_diff is None or len(candidate) < len(nearest_diff):
                    nearest, nearest_diff = prior, candidate
                    if not candidate:
                        break
            covered = _fields_key(entry.fields) in self._warmed.get(
                family, ()
            )
            self._entries.append(entry)
            self._retraces += 1
            self._attributed += 1
            if covered:
                self._prewarm_covered += 1
        if nearest is None:
            changed = [f"first {family} signature (cold start)"]
        elif not nearest_diff:
            changed = ["identical signature (jit cache evicted or bypassed)"]
        else:
            changed = nearest_diff
        TELEMETRY.count("jax.retraces.attributed")
        if covered:
            TELEMETRY.count("jax.retraces.prewarm_covered")
        TELEMETRY.count("jax.compiles")
        TELEMETRY.record_span(
            "jax.compile",
            duration=seconds or 0.0,
            args={
                "family": family,
                "kind": "retrace",
                "signature": format_fields(entry.fields),
                "changed": "; ".join(changed),
            },
        )
        if FLIGHT.enabled:
            FLIGHT.record(
                "jax.retrace",
                args={
                    "family": family,
                    "changed": "; ".join(changed),
                    "covered_by_prewarm": covered,
                    "signature": format_fields(entry.fields),
                },
            )
        return {
            "family": family,
            "changed": changed,
            "covered_by_prewarm": covered,
            "against": dict(nearest.fields) if nearest is not None else None,
        }

    # --- cold-path analysis ----------------------------------------------
    def analyze_all(self, families=None, limit=None):
        """Run the pending cost/memory analyses — each one an AOT
        ``lower().compile()``, a SECOND full XLA compile, which is why
        this only runs from declared cold paths (bench's compiler block,
        ``orion-tpu profile``, tests).  Deduplicates by exact signature
        (a prewarm and the retrace it failed to cover share one analysis)
        and returns ``{"analyzed", "skipped"}`` so callers that bound the
        work (``limit``) can report the cap instead of silently
        truncating."""
        with self._lock:
            TSAN.read("CompileRegistry._entries", self)
            pending = [
                e for e in self._entries
                if e.analysis_fn is not None
                and (families is None or e.family in families)
            ]
        analyzed = skipped = 0
        done = set()
        for entry in pending:
            fields_key = _fields_key(entry.fields)
            key = (entry.family, fields_key)
            if key in done:
                continue
            done.add(key)
            with self._lock:
                cached = self._cost_cache.get(key)
            if cached is None:
                if limit is not None and analyzed >= limit:
                    skipped += 1
                    continue
                try:
                    # Outside the lock on purpose: this is a full XLA
                    # compile and must not block dispatch-side recording.
                    cached = entry.analysis_fn()
                except Exception:  # degrade: analysis must never break a bench
                    cached = None
                analyzed += 1
                if cached is not None:
                    with self._lock:
                        self._cost_cache[key] = cached
            self._apply_cost(entry.family, fields_key, cached)
        self.publish_gauges()
        return {"analyzed": analyzed, "skipped": skipped}

    def _apply_cost(self, family, key, cost):
        with self._lock:
            TSAN.write("CompileRegistry._entries", self)
            for entry in self._entries:
                if (
                    entry.family == family
                    and _fields_key(entry.fields) == key
                ):
                    entry.cost = cost

    # --- surfacing --------------------------------------------------------
    def publish_gauges(self):
        """Refresh the ``compiler.*`` gauges from already-analyzed entries
        (no compiles here — safe per /metrics scrape)."""
        if not TELEMETRY.enabled:
            return
        summary = self.summary()
        if summary["compile_ms_total"] is not None:
            TELEMETRY.set_gauge(
                "compiler.compile_ms_total", summary["compile_ms_total"]
            )
        if summary["plan_hbm_bytes_max"] is not None:
            TELEMETRY.set_gauge(
                "compiler.hbm_bytes_max", summary["plan_hbm_bytes_max"]
            )
        if summary["hbm_capacity_bytes"] is not None:
            TELEMETRY.set_gauge(
                "compiler.hbm_capacity_bytes", summary["hbm_capacity_bytes"]
            )
        if summary["hbm_bound_q"] is not None:
            TELEMETRY.set_gauge("compiler.hbm_bound_q", summary["hbm_bound_q"])

    def entries(self, family=None):
        with self._lock:
            TSAN.read("CompileRegistry._entries", self)
            return [
                e for e in self._entries
                if family is None or e.family == family
            ]

    def summary(self):
        """The JSON-able registry digest: totals + the per-plan table —
        the bench payload's ``compiler`` block and ``orion-tpu profile``'s
        local leg both render exactly this."""
        with self._lock:
            TSAN.read("CompileRegistry._entries", self)
            entries = list(self._entries)
            retraces = self._retraces
            attributed = self._attributed
            covered = self._prewarm_covered
        per_plan = [e.as_dict() for e in entries]
        seconds = [e.seconds for e in entries if e.seconds is not None]
        hbm = [
            e.cost["hbm_bytes"]
            for e in entries
            if e.cost and e.cost.get("hbm_bytes")
        ]
        capacity = device_hbm_capacity()
        bound_qs = [
            q
            for q in (
                predict_hbm_bound_q(
                    e.fields, (e.cost or {}).get("hbm_bytes"), capacity
                )
                for e in entries
            )
            if q is not None
        ]
        return {
            "compiles": len(entries),
            "compile_ms_total": (
                round(sum(seconds) * 1e3, 3) if seconds else None
            ),
            "retraces": retraces,
            "retraces_attributed": attributed,
            "retraces_prewarm_covered": covered,
            "plan_hbm_bytes_max": max(hbm) if hbm else None,
            "hbm_capacity_bytes": capacity,
            "hbm_bound_q": min(bound_qs) if bound_qs else None,
            "per_plan": per_plan,
        }

    def reset(self):
        """Tests only — the registry is process-wide state."""
        with self._lock:
            TSAN.write("CompileRegistry._entries", self)
            self._entries = []
            self._warmed = {}
            self._cost_cache = {}
            self._retraces = 0
            self._attributed = 0
            self._prewarm_covered = 0


#: THE process-wide registry — every jit family records here, exactly as
#: every span lands in the one TELEMETRY ring.
COMPILE_REGISTRY = CompileRegistry()


@contextmanager
def profiler_capture(directory):
    """One shared ``jax.profiler`` capture path: ``hunt --profile`` wraps
    the whole worker loop in this, ``orion-tpu profile --capture DIR``
    wraps its registry-analysis pass — both print the SAME artifact
    summary line, so tooling that greps for the trace location works on
    either."""
    import jax

    jax.profiler.start_trace(directory)
    try:
        yield directory
    finally:
        jax.profiler.stop_trace()
        print(f"jax profiler trace written to {directory}", file=sys.stderr)
