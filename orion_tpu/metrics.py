"""Pull-based metrics export plane: Prometheus text exposition + /metrics.

Two consumers, one renderer:

- :class:`MetricsServer` — a stdlib ``http.server`` daemon thread serving
  ``/metrics`` (the process-wide :data:`~orion_tpu.telemetry.TELEMETRY`
  registry as Prometheus text exposition, format 0.0.4) and ``/healthz``
  (a small JSON liveness/saturation document).  Attachable to the suggest
  gateway (``orion-tpu serve --metrics-port``) and to workers
  (``metrics_port:`` config key / ``ORION_TPU_METRICS_PORT`` env).

- ``orion-tpu metrics -n NAME`` (``cli/metrics.py``) — renders the MERGED
  cross-worker snapshot (the storage metrics channel +
  :func:`~orion_tpu.telemetry.merge_snapshots`) in the same exposition
  format, for airgapped scraping: pipe the output into a Pushgateway or a
  file the scraper reads, no open port on the workers required.

Mapping (the registry's primitives are Prometheus-shaped on purpose):

- counters  -> ``orion_tpu_<name>_total`` (monotonic);
- gauges    -> ``orion_tpu_<name>``;
- log2-µs histograms -> ``orion_tpu_<name>_seconds`` with CUMULATIVE
  ``le`` buckets at each bucket's upper bound in seconds, plus
  ``_sum``/``_count`` — merged snapshots sum buckets elementwise, so the
  cumulative conversion commutes with :func:`merge_snapshots`;
- per-tenant request histograms (``serve.tenant.<name>.request``) export
  as ONE ``orion_tpu_serve_tenant_request_seconds`` family with a
  ``tenant`` label (values escaped per the exposition spec).
"""

import http.server
import json
import logging
import os
import re
import threading

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.telemetry import TELEMETRY, bucket_upper_seconds

log = logging.getLogger(__name__)

PREFIX = "orion_tpu_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: Registry names matching this pattern export as a labeled family
#: instead of one metric per tenant (unbounded tenant cardinality would
#: mint unbounded metric names — the exposition-format antipattern).
_TENANT_RE = re.compile(r"^serve\.tenant\.(?P<tenant>.+)\.request$", re.DOTALL)

#: Doctor findings gauges (``doctor.findings.<RULE>``, published by
#: ``orion_tpu.diagnosis.watch.publish_report``) export as ONE
#: ``orion_tpu_doctor_findings{rule,severity}`` family — rule ids are a
#: closed registry, and the severity label comes from each rule's own
#: declaration.
_DOCTOR_RE = re.compile(r"^doctor\.findings\.(?P<rule>[A-Za-z0-9_]+)$")


def _doctor_severities():
    """rule id -> declared severity, lazily (the diagnosis package is a
    lazy facade for the same reason).  Unknown ids label as ``unknown``
    rather than dropping the sample."""
    try:
        from orion_tpu.diagnosis import rule_severities

        return rule_severities()
    except Exception:  # pragma: no cover - exposition must not break
        return {}


def sanitize_name(name):
    """Registry key -> Prometheus metric name component."""
    out = _NAME_RE.sub("_", str(name))
    if out and out[0].isdigit():  # metric names must not start with a digit
        out = "_" + out
    return out


def escape_label_value(value):
    """Exposition-format label escaping: backslash, double quote, newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(value):
    """Floats render without trailing noise; +Inf per the spec."""
    if value == float("inf"):
        return "+Inf"
    formatted = repr(float(value))
    return formatted[:-2] if formatted.endswith(".0") else formatted


def _histogram_lines(metric, hist, labels=""):
    """Cumulative-``le`` lines for one snapshot histogram dict.  Only
    buckets up to the last occupied one are emitted (48 log2 buckets per
    histogram would bloat every scrape ~10x for zero information — the
    ``+Inf`` bucket always closes the family), and cumulative counts are
    monotone non-decreasing by construction."""
    buckets = list(hist.get("buckets") or ())
    last = 0
    for index, count in enumerate(buckets):
        if count:
            last = index + 1
    sep = "," if labels else ""
    lines = []
    cumulative = 0
    for index in range(last):
        cumulative += int(buckets[index])
        upper = _format_value(bucket_upper_seconds(index))
        lines.append(f'{metric}_bucket{{{labels}{sep}le="{upper}"}} {cumulative}')
    total = int(hist.get("count", 0))
    lines.append(f'{metric}_bucket{{{labels}{sep}le="+Inf"}} {total}')
    suffix = f"{{{labels}}}" if labels else ""
    lines.append(f"{metric}_sum{suffix} {_format_value(hist.get('sum', 0.0))}")
    lines.append(f"{metric}_count{suffix} {total}")
    return lines


def render_exposition(snapshot, prefix=PREFIX):
    """One metrics snapshot (``Telemetry.snapshot()`` or a
    ``merge_snapshots`` result) as Prometheus text exposition 0.0.4."""
    lines = []
    for name, value in sorted((snapshot.get("counters") or {}).items()):
        metric = f"{prefix}{sanitize_name(name)}_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {int(value)}")
    doctor_rows = []
    plain_gauges = []
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        match = _DOCTOR_RE.match(name)
        if match:
            doctor_rows.append((match.group("rule"), value))
        else:
            plain_gauges.append((name, value))
    for name, value in plain_gauges:
        metric = f"{prefix}{sanitize_name(name)}"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_format_value(value)}")
    if doctor_rows:
        severities = _doctor_severities()
        metric = f"{prefix}doctor_findings"
        lines.append(f"# TYPE {metric} gauge")
        for rule, value in doctor_rows:
            severity = severities.get(rule, "unknown")
            lines.append(
                f'{metric}{{rule="{escape_label_value(rule)}",'
                f'severity="{escape_label_value(severity)}"}} '
                f"{_format_value(value)}"
            )
    tenant_families = {}
    plain = []
    for name, hist in sorted((snapshot.get("histograms") or {}).items()):
        match = _TENANT_RE.match(name)
        if match:
            metric = f"{prefix}serve_tenant_request_seconds"
            tenant_families.setdefault(metric, []).append(
                (match.group("tenant"), hist)
            )
        else:
            plain.append((f"{prefix}{sanitize_name(name)}_seconds", hist))
    for metric, hist in plain:
        lines.append(f"# TYPE {metric} histogram")
        lines.extend(_histogram_lines(metric, hist))
    for metric, families in sorted(tenant_families.items()):
        lines.append(f"# TYPE {metric} histogram")
        for tenant, hist in families:
            labels = f'tenant="{escape_label_value(tenant)}"'
            lines.extend(_histogram_lines(metric, hist, labels=labels))
    return "\n".join(lines) + "\n"


class _MetricsHandler(http.server.BaseHTTPRequestHandler):
    server_version = "orion-tpu-metrics"

    def do_GET(self):  # noqa: N802 - http.server contract
        if self.path.split("?", 1)[0] == "/metrics":
            # Fresh device-memory gauges per scrape: the sampler is the
            # low-frequency leg; a scrape IS the frequency source here.
            from orion_tpu.devmem import sample_memory

            sample_memory(force=True)
            # Replication-lag gauges for any sharded router this process
            # holds (rate-limited internally; one tiny seq probe per node).
            from orion_tpu.storage.shard import sample_replication_lag

            sample_replication_lag()
            # Compiler-plane gauges (compile_ms_total, hbm_bytes_max,
            # hbm_bound_q) from already-analyzed entries — publish_gauges
            # never compiles, so it is scrape-safe.
            from orion_tpu.compiler_plane import COMPILE_REGISTRY

            COMPILE_REGISTRY.publish_gauges()
            body = render_exposition(self.server.registry.snapshot()).encode()
            content_type = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?", 1)[0] == "/healthz":
            healthz = self.server.healthz
            try:
                payload = healthz() if healthz is not None else {"ok": True}
            except Exception:  # pragma: no cover - prober must get an answer
                payload = {"ok": False}
            body = (json.dumps(payload) + "\n").encode()
            content_type = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        log.debug("metrics http: " + fmt, *args)


class _HTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class MetricsServer:
    """``/metrics`` + ``/healthz`` on a daemon thread.

    ``registry`` defaults to the process-wide TELEMETRY; ``healthz`` is an
    optional zero-arg callable returning the health JSON (the gateway
    passes queue depth / tenant count)."""

    def __init__(self, port=0, host="127.0.0.1", registry=None, healthz=None):
        self._httpd = _HTTPServer((host, int(port)), _MetricsHandler)
        self._httpd.registry = registry if registry is not None else TELEMETRY
        self._httpd.healthz = healthz
        self._thread = None

    @property
    def address(self):
        return self._httpd.server_address[:2]

    @property
    def port(self):
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="orion-tpu-metrics",
            daemon=True,
        )
        self._thread.start()
        return self.address

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


#: Process-wide worker metrics server (workers opt in via env/config; one
#: port per process, idempotent across repeated workon() calls).
_worker_server = None
_worker_lock = threading.Lock()


def _worker_healthz():
    """The worker /healthz payload: liveness + the doctor summary block
    (``orion_tpu.diagnosis``) — probes key off diagnosis, not bare
    process liveness."""
    from orion_tpu.diagnosis import doctor_summary

    return {"ok": True, "doctor": doctor_summary()}


def ensure_worker_metrics_server(port=None):
    """Start (once) the worker-side metrics server.

    ``port`` falls back to the ``ORION_TPU_METRICS_PORT`` env var; absent/
    invalid/empty means "not requested" and returns None.  Failures are
    logged, never raised — observability must not kill a worker.  Two
    worker-fleet realities are handled here:

    - requesting a scrape endpoint IS requesting metrics, so a successful
      start enables the telemetry registry (an endpoint over a disabled
      registry would serve an empty exposition forever);
    - ``hunt --n-workers N`` children all inherit ONE configured port —
      the first binds it, the rest fall back to an EPHEMERAL port (logged
      with the bound address) instead of silently exporting nothing.

    The worker's ``/healthz`` answers a DOCTOR summary block next to bare
    liveness (status + critical/warn counts, from the watchdog's last
    published report or a fresh local-registry pass) so a k8s-style probe
    keys off diagnosis, not just an open socket."""
    global _worker_server
    if port is None:
        raw = os.environ.get("ORION_TPU_METRICS_PORT", "").strip()
        if not raw:
            return None
        try:
            port = int(raw)
        except ValueError:
            log.warning("ignoring non-numeric ORION_TPU_METRICS_PORT=%r", raw)
            return None
    with _worker_lock:
        TSAN.write("metrics._worker_server")
        if _worker_server is not None:
            return _worker_server
        try:
            server = MetricsServer(port=int(port), healthz=_worker_healthz)
        except OSError as exc:
            try:
                server = MetricsServer(port=0, healthz=_worker_healthz)
                log.warning(
                    "metrics port %s unavailable (%s); falling back to an "
                    "ephemeral port", port, exc,
                )
            except OSError as fallback_exc:  # pragma: no cover - no sockets
                log.warning(
                    "could not start worker metrics server: %s", fallback_exc
                )
                return None
        server.start()
        TELEMETRY.enable()
        _worker_server = server
        log.info("worker metrics server on %s:%s", *server.address)
        return server
