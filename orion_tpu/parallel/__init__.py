"""Device-mesh parallelism for the on-device optimizer core.

The reference's only parallelism is N worker processes around a shared DB
(SURVEY.md §2.9); that layer survives over DCN.  *This* layer is the
TPU-native one the reference could not have: inside a single suggest step,
candidate evaluation is sharded across a `jax.sharding.Mesh` so acquisition
over tens of thousands of candidates rides ICI collectives under one jit.

With sharded inputs, XLA's SPMD partitioner inserts the collectives
(the top-k/argmax reductions become all-gathers over the candidate axis);
no hand-written pmap plumbing needed.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

CANDIDATE_AXIS = "candidates"


def device_mesh(n_devices=None, axis_name=CANDIDATE_AXIS):
    """1-D mesh over available devices (candidate/data parallel)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def candidate_sharding(mesh, axis_name=CANDIDATE_AXIS):
    """Shard an (m, d) candidate matrix along m; d replicated."""
    return NamedSharding(mesh, PartitionSpec(axis_name, None))


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def shard_candidates(candidates, mesh, axis_name=CANDIDATE_AXIS):
    """Place host candidates sharded over the mesh (public utility for
    library users bringing their OWN candidate sets; the built-in engine
    shards inside its fused jit via `candidate_sharding` instead)."""
    return jax.device_put(candidates, candidate_sharding(mesh, axis_name))
