"""Device-mesh parallelism for the on-device optimizer core.

The reference's only parallelism is N worker processes around a shared DB
(SURVEY.md §2.9); that layer survives over DCN.  *This* layer is the
TPU-native one the reference could not have: inside a single suggest step,
candidate evaluation is sharded across a `jax.sharding.Mesh` so acquisition
over tens of thousands of candidates rides ICI collectives under one jit.

With sharded inputs, XLA's SPMD partitioner inserts the collectives
(the top-k/argmax reductions become all-gathers over the candidate axis);
no hand-written pmap plumbing needed.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

CANDIDATE_AXIS = "candidates"


def device_mesh(n_devices=None, axis_name=CANDIDATE_AXIS):
    """1-D mesh over available devices (candidate/data parallel)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def candidate_sharding(mesh, axis_name=CANDIDATE_AXIS):
    """Shard an (m, d) candidate matrix along m; d replicated."""
    return NamedSharding(mesh, PartitionSpec(axis_name, None))


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def shard_candidates(candidates, mesh, axis_name=CANDIDATE_AXIS):
    """Place host candidates sharded over the mesh (public utility for
    library users bringing their OWN candidate sets; the built-in engine
    shards inside its fused jit via `candidate_sharding` instead)."""
    return jax.device_put(candidates, candidate_sharding(mesh, axis_name))


def init_distributed(coordinator=None, num_processes=None, process_id=None,
                     local_device_ids=None):
    """Join a multi-host cohort so one *worker* spans several hosts.

    Two distinct scaling axes exist (docs/multi_node.md):

    - Independent workers coordinate through shared storage over DCN — they
      must NOT call this; each keeps its own single-host jax.
    - ONE logical worker running on a multi-host slice calls this in every
      process of the cohort (same arguments everywhere, standard
      `jax.distributed` contract).  Afterwards `jax.devices()` spans all
      hosts, `device_mesh()` builds the global mesh, and the fused suggest
      step's candidate axis shards across the whole slice — XLA routes the
      top-k/argmin collectives over ICI within a host and DCN between
      hosts.  Every process must then execute the same suggest calls
      (SPMD); the producer/storage side stays per-cohort, not per-process.

    Arguments default to jax's env-based autodetection (JAX_COORDINATOR_*,
    cloud TPU metadata); pass them explicitly elsewhere.  Idempotent.
    """
    global _distributed_initialized
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init() if callable(is_init) else _distributed_initialized:
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as exc:
        # jax builds without is_initialized(): re-init of a live cohort must
        # keep the documented idempotency instead of crashing.
        if "already initialized" not in str(exc).lower():
            raise
    _distributed_initialized = True


_distributed_initialized = False
