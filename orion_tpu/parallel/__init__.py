"""Device-mesh parallelism for the on-device optimizer core.

The reference's only parallelism is N worker processes around a shared DB
(SURVEY.md §2.9); that layer survives over DCN.  *This* layer is the
TPU-native one the reference could not have: inside a single suggest step,
candidate evaluation is sharded across a `jax.sharding.Mesh` so acquisition
over tens of thousands of candidates rides ICI collectives under one jit.

With sharded inputs, XLA's SPMD partitioner inserts the collectives
(the top-k/argmax reductions become all-gathers over the candidate axis);
no hand-written pmap plumbing needed.
"""

import jax

from orion_tpu.algo.sharding import (
    CANDIDATE_AXIS,
    TENANT_AXIS,
    candidate_spec,
    get_mesh,
    get_stacked_mesh,
    replicated_spec,
    shard_candidates,
)

__all__ = [
    "CANDIDATE_AXIS",
    "TENANT_AXIS",
    "device_mesh",
    "candidate_sharding",
    "replicated",
    "shard_candidates",
    "get_stacked_mesh",
    "init_distributed",
]


def device_mesh(n_devices=None, axis_name=CANDIDATE_AXIS):
    """1-D mesh over available devices (candidate/data parallel).

    Cached: repeated calls with the same topology return the SAME mesh
    object (`orion_tpu.algo.sharding.get_mesh`), so the fused step's
    static-arg cache probe is an identity hit and per-call construction
    never lands on the hot path (lint rule JIT004).
    """
    return get_mesh(n_devices, axis_name)


def candidate_sharding(mesh, axis_name=CANDIDATE_AXIS):
    """Shard an (m, d) candidate matrix along m; d replicated (cached)."""
    return candidate_spec(mesh, axis_name)


def replicated(mesh):
    return replicated_spec(mesh)


def init_distributed(coordinator=None, num_processes=None, process_id=None,
                     local_device_ids=None):
    """Join a multi-host cohort so one *worker* spans several hosts.

    Two distinct scaling axes exist (docs/multi_node.md):

    - Independent workers coordinate through shared storage over DCN — they
      must NOT call this; each keeps its own single-host jax.
    - ONE logical worker running on a multi-host slice calls this in every
      process of the cohort (same arguments everywhere, standard
      `jax.distributed` contract).  Afterwards `jax.devices()` spans all
      hosts, `device_mesh()` builds the global mesh, and the fused suggest
      step's candidate axis shards across the whole slice — XLA routes the
      top-k/argmin collectives over ICI within a host and DCN between
      hosts.  Every process must then execute the same suggest calls
      (SPMD); the producer/storage side stays per-cohort, not per-process.

    Arguments default to jax's env-based autodetection (JAX_COORDINATOR_*,
    cloud TPU metadata); pass them explicitly elsewhere.  Idempotent.
    """
    global _distributed_initialized
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init() if callable(is_init) else _distributed_initialized:
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as exc:
        # jax builds without is_initialized(): re-init of a live cohort must
        # keep the documented idempotency instead of crashing.
        if "already initialized" not in str(exc).lower():
            raise
    _distributed_initialized = True


_distributed_initialized = False
