"""Optimization-health observability: per-round records + flight recorder.

PR 3's telemetry answers *systems* questions — where a round's wall-clock
went, how often storage retried, whether the device retraced.  This module
answers the *optimizer* questions those numbers cannot: is the incumbent
still improving, is the GP fit healthy (marginal likelihood, lengthscales,
noise), is the trust region expanding or collapsing, are suggested batches
still diverse — the signals that turn "bench regret drifted again" from an
anecdote into a measurable, per-round, per-worker time series.

Two pieces:

- **Health records.**  Each GP round, the fused suggest step packs a small
  health vector ON DEVICE from intermediates it already computed (final
  marginal likelihood, lengthscale spread, EI stats, q-batch uniqueness)
  into :class:`~orion_tpu.algo.gp.gp.GPState` — zero extra device work and
  zero extra host syncs; the vector is read lazily AFTER the q rows were
  already materialized.  ``BaseAlgorithm.health_record()`` merges it with
  the algorithm's host-side truth (incumbent, trust-region box, ASHA rung
  occupancy) and the producer flushes one record per round through the
  ``record_health``/``fetch_health`` storage channel (capped retention,
  ``storage/base.py``).  ``orion-tpu top`` and ``orion-tpu info`` read it
  back; ``bench.py`` gates on the multi-seed regret trajectory
  (``orion_tpu.benchmarks.regret_gate``).

- **Flight recorder.**  A bounded ring of recent structured events (round
  boundaries, storage retries, reconnects, trial status transitions,
  prewarm/retrace events) that can be dumped as a JSONL artifact when it
  matters: on a worker crash, on an ``orion-tpu audit`` failure, and on
  demand via ``orion-tpu flight-record``.  Producers also mirror drained
  events into the spans storage channel (as ``flight.*`` span records), so
  the CLI can reconstruct another process's recent history.

Contract shared with the telemetry registry: emission must never raise
into a hot path, and the DISABLED path must not allocate — call sites
building args dicts guard on ``FLIGHT.enabled`` (lint rule ``TEL004``
enforces this, the same discipline ``TEL003`` enforces for TELEMETRY
mutators).
"""

import json
import os
import threading
import time
import traceback

# Annotated-cell hooks for the runtime concurrency sanitizer
# (orion-tpu tsan) — same disabled-path cost discipline as recording.
from orion_tpu.analysis.sanitizer import TSAN

_ENABLE_VALUES = ("1", "on", "true", "yes")

DEFAULT_FLIGHT_CAPACITY = 512

#: Layout of the packed per-round device-health vector the fused suggest
#: step emits (``GPState.health``).  FIXED order — the array is unpacked
#: positionally by :func:`unpack_device_health`:
#:
#: - ``gp_mll``: marginal log-likelihood per observation of the final fit
#:   (collapsing toward -inf = the model stopped explaining the data);
#: - ``gp_ls_min`` / ``gp_ls_mean`` / ``gp_ls_max``: fitted lengthscales
#:   over the free dims (min pinned at the clip floor = a dimension the
#:   GP treats as pure noise);
#: - ``gp_noise``: fitted noise level (rising toward its ceiling = the
#:   objective looks irreproducible to the model);
#: - ``acq_ei_max`` / ``acq_ei_mean``: expected improvement over the
#:   candidate pool (both ~0 = acquisition has flattened: converged, or
#:   the incumbent is unattainable under the current fit);
#: - ``q_unique_frac``: fraction of distinct rows in the selected q-batch
#:   (below 1.0 = the dedup fill ran out of distinct candidates — the
#:   candidate generator has collapsed onto too few points).
DEVICE_HEALTH_FIELDS = (
    "gp_mll",
    "gp_ls_min",
    "gp_ls_mean",
    "gp_ls_max",
    "gp_noise",
    "acq_ei_max",
    "acq_ei_mean",
    "q_unique_frac",
)


def unpack_device_health(vec):
    """Packed ``(len(DEVICE_HEALTH_FIELDS),)`` device vector -> field dict.

    The one host read of the health vector.  Callers invoke it only after
    the round's q rows were materialized, so the computation is already
    complete — this is a tiny transfer of ready data, not a device sync.
    """
    import numpy as np

    values = np.asarray(vec, dtype=np.float64).ravel()
    if values.shape[0] < len(DEVICE_HEALTH_FIELDS):
        return {}
    return {
        name: float(values[i]) for i, name in enumerate(DEVICE_HEALTH_FIELDS)
    }


def _env_enabled():
    """Flight recording rides the observability toggle: ORION_TPU_FLIGHT
    enables it alone, ORION_TPU_TELEMETRY enables it together with the
    metrics/span registry (one switch for the whole observability layer)."""
    for var in ("ORION_TPU_FLIGHT", "ORION_TPU_TELEMETRY"):
        if os.environ.get(var, "").strip().lower() in _ENABLE_VALUES:
            return True
    return False


class FlightRecorder:
    """Bounded ring of recent structured events, dumpable as JSONL.

    Same cost discipline as the telemetry registry: ``record`` is one
    attribute check when disabled (no lock, no clock read, no allocation
    — provided the call site guards its args construction, see TEL004),
    and never raises into a hot path.  Thread-safe: one lock guards the
    ring.
    """

    def __init__(self, enabled=None, capacity=None):
        if enabled is None:
            enabled = _env_enabled()
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("ORION_TPU_FLIGHT_EVENTS", "")
                    or DEFAULT_FLIGHT_CAPACITY
                )
            except ValueError:
                capacity = DEFAULT_FLIGHT_CAPACITY
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._capacity = max(int(capacity), 8)
        self._ring = [None] * self._capacity
        self._seq = 0
        self._drained = 0

    # --- toggling -----------------------------------------------------------
    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    # --- recording ----------------------------------------------------------
    def record(self, kind, args=None):
        """Append one event ``{"kind", "ts", "pid", "args"?}`` to the ring.

        ``kind`` is a short dotted label (``"producer.round"``,
        ``"storage.retry"``, ``"trial.status"``, the doctor's
        ``"alert"``); ``args`` an optional
        small dict of context.  Oldest events past capacity are dropped —
        a flight recorder keeps the *recent* past."""
        if not self.enabled:
            return
        try:
            event = {"kind": str(kind), "ts": time.time(), "pid": os.getpid()}
            if args:
                event["args"] = dict(args)
            with self._lock:
                TSAN.write("FlightRecorder._ring", self)
                self._ring[self._seq % self._capacity] = event
                self._seq += 1
        except Exception:  # pragma: no cover - must never raise into hot path
            pass

    def events(self):
        """Every event currently in the ring, oldest first."""
        with self._lock:
            TSAN.read("FlightRecorder._ring", self)
            start = max(0, self._seq - self._capacity)
            return [self._ring[i % self._capacity] for i in range(start, self._seq)]

    def drain(self):
        """Events recorded since the last drain, each returned exactly once
        (the producer's storage-mirror channel; wraparound between drains
        drops the overwritten oldest, by design)."""
        with self._lock:
            TSAN.write("FlightRecorder._ring", self)  # advances the drain cursor
            start = max(self._drained, self._seq - self._capacity)
            out = [self._ring[i % self._capacity] for i in range(start, self._seq)]
            self._drained = self._seq
            return out

    def clear(self):
        with self._lock:
            TSAN.write("FlightRecorder._ring", self)
            self._ring = [None] * self._capacity
            self._seq = 0
            self._drained = 0

    # --- dumping ------------------------------------------------------------
    def dump(self, path, reason="on-demand", extra_events=None):
        """Write the ring (oldest first) as a JSONL artifact.

        First line is a header record (``type: flight-record`` with the
        reason, host identity, and wall time); every following line is one
        event.  ``extra_events`` lets cold-path callers (the audit CLI's
        violation dump, the crash handler's traceback) append context that
        never went through the hot-path ring.  Returns ``path``.  Dumping
        is deliberately NOT gated on ``enabled``: the artifact of a
        disabled recorder is just its header + extras, and a post-mortem
        with partial data beats none."""
        import socket

        events = self.events()
        with open(path, "w") as handle:
            header = {
                "type": "flight-record",
                "reason": reason,
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "time": time.time(),
                "events": len(events) + len(extra_events or ()),
                "enabled": self.enabled,
            }
            # The doctor's last published verdict (orion_tpu.diagnosis)
            # rides the header: a crash dump that opens with "status:
            # critical, DX021 firing" starts the post-mortem one step
            # ahead of the raw event ring.  evaluate_local=False — a
            # crash path must not pay a fresh diagnosis pass.
            try:
                from orion_tpu.diagnosis import doctor_summary

                header["doctor"] = doctor_summary(evaluate_local=False)
            except Exception:  # pragma: no cover - dumps must not fail
                pass
            handle.write(json.dumps(header) + "\n")
            for event in events:
                handle.write(json.dumps(event) + "\n")
            for event in extra_events or ():
                handle.write(json.dumps(event) + "\n")
        return path

    def dump_crash(self, name, exc, directory=None):
        """Crash-path dump: ``flight-<name>-<pid>.jsonl`` in ``directory``
        (default cwd), with the exception and traceback as the final
        event.  Returns the path, or None when the recorder is disabled
        (a run that never asked for observability should not scatter
        artifacts on every failure).  Never raises — this runs inside
        exception handlers."""
        if not self.enabled:
            return None
        try:
            path = os.path.join(
                directory or os.getcwd(), f"flight-{name}-{os.getpid()}.jsonl"
            )
            crash_event = {
                "kind": "crash",
                "ts": time.time(),
                "pid": os.getpid(),
                "args": {
                    "error": repr(exc),
                    "traceback": "".join(
                        traceback.format_exception(type(exc), exc, exc.__traceback__)
                    )[-4000:],
                },
            }
            return self.dump(path, reason="crash", extra_events=[crash_event])
        except Exception:  # pragma: no cover - crash path must not re-crash
            return None


def flight_events_as_spans(events):
    """Ring events -> span-shaped records for the spans storage channel.

    The producer mirrors drained flight events through
    ``DocumentStorage.record_spans`` as zero-duration ``flight.<kind>``
    spans, so ``orion-tpu flight-record -n NAME`` can reconstruct another
    process's recent history from storage and a Perfetto trace shows the
    events on the worker's timeline."""
    spans = []
    for event in events:
        if not event:
            continue
        span = {
            "name": f"flight.{event.get('kind', '?')}",
            "ts": float(event.get("ts", 0.0)),
            "dur": 0.0,
            "pid": int(event.get("pid", 0)),
            "tid": 0,
        }
        args = event.get("args")
        if args:
            span["args"] = dict(args)
        spans.append(span)
    return spans


def spans_as_flight_events(spans):
    """Inverse of :func:`flight_events_as_spans` for the CLI read path:
    keep only ``flight.*`` span docs and strip them back to event form."""
    events = []
    for span in spans:
        name = str(span.get("name", ""))
        if not name.startswith("flight."):
            continue
        event = {
            "kind": name[len("flight."):],
            "ts": float(span.get("ts", 0.0)),
            "pid": int(span.get("pid", 0)),
        }
        if span.get("worker") is not None:
            event["worker"] = span["worker"]
        args = span.get("args")
        if args:
            event["args"] = dict(args)
        events.append(event)
    return events


#: THE process-wide flight recorder, next to telemetry.TELEMETRY.  Enabled
#: state comes from ORION_TPU_FLIGHT / ORION_TPU_TELEMETRY at import; the
#: CLI layers the ``telemetry:`` config key on top (cli/base.py).
FLIGHT = FlightRecorder()
