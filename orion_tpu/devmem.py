"""Device-memory & compile-cache accounting: low-frequency gauge sampler.

Publishes, into the process-wide telemetry registry (so the numbers flow
through the storage metrics channel into ``orion-tpu info``/``top`` and
out of ``/metrics`` like every other gauge):

- ``memory.device_live_bytes`` / ``memory.device_live_arrays`` — the sum
  over ``jax.live_arrays()`` (every device buffer the process still
  references) and their count;
- ``memory.device_bytes_in_use`` / ``memory.device_peak_bytes`` — the
  backend allocator's own accounting via ``Device.memory_stats()``, with
  graceful degradation: backends without the API (or returning None —
  older CPU backends) simply publish nothing;
- ``memory.device_live_bytes.d<id>`` — the same live-array walk split per
  device via shard metadata (``addressable_shards``), so a sharded run's
  placement skew is visible in the SAME channel the totals already use
  (one device holding everything = the silent-sharding-regression signal
  doctor rule DX006 watches in health records);
- ``memory.history_device_bytes.b<cap>`` — resident observation-history
  bytes per pow-2 capacity bucket (``DeviceHistory`` introspection: the
  distribution says which experiments are about to cross a bucket);
  ``memory.history_device_bytes`` the total, ``memory.history_host_bytes``
  the :class:`HostHistory` mirror total, ``memory.history_count`` live
  instances;
- ``memory.fused_cache_entries`` — the fused suggest step's jit-cache
  entry count (the private ``_cache_size`` accessor; None-safe), plus
  ``memory.stacked_cache_entries`` for the gateway's stacked step;
- ``memory.prewarm_started`` / ``memory.prewarm_completed`` — the prewarm
  inventory (signatures launched / compiles finished, process-wide).

Donation-hit accounting is the histories' own job (``history.appends.
donated`` / ``.copied`` counters booked at append time); the sampler only
reads state that already exists — TEL-discipline clean: one enabled-flag
check and one monotonic read on the cold path, every allocating call
behind them, and the rate-limit cell is tsan-annotated shared state.

Callers: the producer's metrics-flush gate and every ``/metrics`` scrape
(forced — the scrape IS the frequency source there, and it runs on the
HTTP handler thread so the gateway's dispatcher never pays the
live-buffer walk).
"""

import logging
import os
import threading
import time

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.telemetry import TELEMETRY

log = logging.getLogger(__name__)

#: Seconds between samples (env-tunable): ``jax.live_arrays()`` walks every
#: live buffer, which must stay off the per-round hot path.
try:
    SAMPLE_INTERVAL = float(
        os.environ.get("ORION_TPU_MEMORY_INTERVAL", "") or 10.0
    )
except ValueError:  # pragma: no cover - hostile env
    SAMPLE_INTERVAL = 10.0

_lock = threading.Lock()
_last_sample = 0.0

#: Pow-2 capacity -> gauge name, built lazily so the per-bucket set_gauge
#: call sites pass a plain NAME (no per-call key computation — TEL001).
_BUCKET_GAUGE_NAMES = {}


def _bucket_gauge_name(cap):
    name = _BUCKET_GAUGE_NAMES.get(cap)
    if name is None:
        name = f"memory.history_device_bytes.b{int(cap)}"
        _BUCKET_GAUGE_NAMES[cap] = name
    return name


#: Device id -> gauge name, same lazy-name discipline (TEL001).
_DEVICE_GAUGE_NAMES = {}


def _device_gauge_name(dev):
    name = _DEVICE_GAUGE_NAMES.get(dev)
    if name is None:
        name = f"memory.device_live_bytes.d{int(dev)}"
        _DEVICE_GAUGE_NAMES[dev] = name
    return name


def sample_memory(force=False):
    """Publish the memory/compile gauges; rate-limited to
    :data:`SAMPLE_INTERVAL` unless ``force``.  Returns True when a sample
    ran.  Never raises — accounting must not break a run."""
    if not TELEMETRY.enabled:
        return False
    global _last_sample
    now = time.monotonic()
    with _lock:
        TSAN.write("devmem._state")
        if not force and now - _last_sample < SAMPLE_INTERVAL:
            return False
        _last_sample = now
    try:
        _sample_live_arrays()
        _sample_backend_stats()
        _sample_histories()
        _sample_compile_caches()
        _sample_prewarm_inventory()
    except Exception:  # pragma: no cover - observability never breaks a run
        log.debug("memory sample failed", exc_info=True)
    return True


def _sample_live_arrays():
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:  # backend without the API
        return
    total = 0
    count = 0
    per_device = {}
    for array in arrays:
        count += 1
        try:
            total += int(array.nbytes)
        except Exception:  # pragma: no cover - deleted buffer mid-walk
            pass
        # Per-device split off the shard metadata (no transfers) — same
        # walk, graceful degradation on leaves without the accessor.
        try:
            for shard in array.addressable_shards:
                nbytes = getattr(shard.data, "nbytes", 0)
                per_device[shard.device.id] = (
                    per_device.get(shard.device.id, 0) + int(nbytes)
                )
        except Exception:  # pragma: no cover - deleted buffer mid-walk
            pass
    TELEMETRY.set_gauge("memory.device_live_bytes", total)
    TELEMETRY.set_gauge("memory.device_live_arrays", count)
    # Same zero-stale discipline as the history buckets: a device that held
    # bytes once but holds none now must publish 0, not its last value.
    for dev in _DEVICE_GAUGE_NAMES:
        if dev not in per_device:
            name = _device_gauge_name(dev)
            TELEMETRY.set_gauge(name, 0)
    for dev, nbytes in per_device.items():
        name = _device_gauge_name(dev)
        TELEMETRY.set_gauge(name, nbytes)


def _sample_backend_stats():
    """Allocator-level accounting — graceful degradation when the backend
    lacks ``memory_stats`` (or answers None, as CPU backends may)."""
    try:
        import jax

        device = jax.local_devices()[0]
        stats_fn = getattr(device, "memory_stats", None)
        stats = stats_fn() if stats_fn is not None else None
    except Exception:
        return
    if not isinstance(stats, dict):
        return
    in_use = stats.get("bytes_in_use")
    if in_use is not None:
        TELEMETRY.set_gauge("memory.device_bytes_in_use", in_use)
    peak = stats.get("peak_bytes_in_use")
    if peak is not None:
        TELEMETRY.set_gauge("memory.device_peak_bytes", peak)


def _sample_histories():
    from orion_tpu.algo.history import history_memory_stats

    stats = history_memory_stats()
    TELEMETRY.set_gauge("memory.history_device_bytes", stats["device_bytes"])
    TELEMETRY.set_gauge("memory.history_host_bytes", stats["host_bytes"])
    TELEMETRY.set_gauge("memory.history_count", stats["device_count"])
    buckets = stats["device_buckets"]
    # Gauges are last-write-wins and never deleted: a bucket every history
    # has grown out of must be ZEROED, or its stale byte count survives
    # forever and the per-bucket sum stops matching the total.
    for cap in _BUCKET_GAUGE_NAMES:
        if cap not in buckets:
            name = _bucket_gauge_name(cap)
            TELEMETRY.set_gauge(name, 0)
    for cap, nbytes in buckets.items():
        name = _bucket_gauge_name(cap)
        TELEMETRY.set_gauge(name, nbytes)


def _sample_compile_caches():
    """Fused-step jit-cache entry counts via the private ``_cache_size``
    accessor product code already degrades around (prewarm detection) —
    absent accessor publishes nothing, not zero."""
    try:
        from orion_tpu.algo.tpu_bo import _suggest_step

        cache_size = getattr(_suggest_step, "_cache_size", None)
        if cache_size is not None:
            TELEMETRY.set_gauge("memory.fused_cache_entries", cache_size())
    except Exception:  # pragma: no cover - import/introspection drift
        pass
    try:
        import sys

        coalesce = sys.modules.get("orion_tpu.serve.coalesce")
        if coalesce is not None:  # only if the serve stack is actually loaded
            cache_size = getattr(
                coalesce._stacked_suggest_step, "_cache_size", None
            )
            if cache_size is not None:
                TELEMETRY.set_gauge("memory.stacked_cache_entries", cache_size())
    except Exception:  # pragma: no cover - introspection drift
        pass


def _sample_prewarm_inventory():
    from orion_tpu.algo.prewarm import prewarm_inventory

    inventory = prewarm_inventory()
    TELEMETRY.set_gauge("memory.prewarm_started", inventory["started"])
    TELEMETRY.set_gauge("memory.prewarm_completed", inventory["completed"])
