"""Shipped test harness for algorithm/plugin authors.

Capability parity: reference `src/orion/core/utils/tests.py:59-212`
(``OrionState``) and the scriptable ``DumbAlgo`` fake from the reference's
`tests/conftest.py:23-117` — shipped *in the package* so a third-party
algorithm plugin can test suggest/observe against the full producer/worker
stack using only the published distribution:

    from orion_tpu.testing import DumbAlgo, OrionState

    def test_my_plugin():
        with OrionState(
            experiments=[{"name": "exp", "priors": {"/x": "uniform(0, 1)"}}],
        ) as state:
            exp = state.get_experiment("exp").instantiate()
            producer = Producer(exp)
            producer.update(); producer.produce(4)
            ...

``OrionState`` installs a fresh storage (in-memory by default, or a
file-locked pickled DB in a tempdir with ``pickled=True`` for multi-process
scenarios), preloads experiments/trials/lies, swaps the process-wide storage
singleton, and restores everything on exit.
"""

import contextlib
import os
import shutil
import tempfile

import numpy as np

import orion_tpu.storage.base as _storage_base
from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.core.experiment import Experiment, build_experiment
from orion_tpu.core.trial import Trial
from orion_tpu.storage import create_storage


@algo_registry.register("dumbalgo")
class DumbAlgo(BaseAlgorithm):
    """Fully scriptable fake algorithm (reference `tests/conftest.py:23-117`).

    - suggests a constant unit-cube ``value`` (so resulting params are
      deterministic), counting every request;
    - records every observation it receives;
    - ``opt_out=True`` makes ``suggest`` return None (the temporary opt-out
      contract, reference `algo/base.py:142-163`);
    - ``done=True`` drives the ``is_done`` early-stop path.
    """

    def __init__(self, space, value=0.5, possible_values=None, seed=None):
        super().__init__(space, seed=seed, value=value)
        self.value = value
        # Scriptable distinct suggestions (reference DumbAlgo's
        # possible_values): successive suggested points consume successive
        # values, so a producer asking for q unique trials gets them.
        self.possible_values = list(possible_values or [])
        self._value_cursor = 0
        self.n_suggested = 0
        self.observed_params = []
        self.observed_results = []
        self.opt_out = False
        self.done = False

    def _suggest_cube(self, num):
        if self.opt_out:
            return None
        self.n_suggested += num
        if self.possible_values:
            rows = []
            for _ in range(num):
                v = self.possible_values[
                    self._value_cursor % len(self.possible_values)
                ]
                self._value_cursor += 1
                rows.append(np.full((self.space.n_cols,), v))
            return np.stack(rows)
        return np.full((num, self.space.n_cols), self.value)

    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        self.observed_params.extend(params_list or [])
        self.observed_results.extend(np.asarray(objectives).tolist())

    def register_suggestion(self, params):
        # The producer suggests through a fresh deepcopy every round; advance
        # the REAL instance's cursor per durably-registered trial so the next
        # naive copy starts at the first unconsumed value.
        if self.possible_values:
            self._value_cursor += 1

    @property
    def is_done(self):
        return self.done


def drive_chaos_experiment(
    storage,
    name="chaos",
    priors=None,
    algorithms=None,
    max_trials=9,
    pool_size=3,
    seed=1,
    heartbeat=2.0,
    max_idle_time=30.0,
    proxy=None,
    drop_every=0,
    deadline=120.0,
):
    """THE shared chaos-run driver (docs/robustness.md): drive an
    experiment to completion the way a worker does — reserve, complete,
    tolerate transient storage failures with a short backoff — against a
    (typically fault-injected) storage, then sweep lost trials and audit.

    Used by both the chaos suite (tests/functional/test_chaos.py) and
    ``bench.py --chaos`` so the two cannot drift apart; shipped in the
    package so third-party backend authors can chaos-test their own
    storage the same way.  ``proxy``/``drop_every`` schedule connection
    drops through a :class:`~orion_tpu.storage.faults.FaultProxy` every N
    iterations; ``deadline`` bounds the whole run (TimeoutError on
    non-convergence — a hung chaos run must fail loudly, not spin).

    Returns ``(experiment, audit_report)``.
    """
    import time

    from orion_tpu.core.producer import Producer
    from orion_tpu.core.trial import Result
    from orion_tpu.core.worker import reserve_trial
    from orion_tpu.storage.audit import audit_experiment
    from orion_tpu.storage.retry import is_transient

    experiment = build_experiment(
        storage,
        name,
        priors=dict(priors or {"/x": "uniform(0, 1)", "/y": "uniform(0, 1)"}),
        algorithms=algorithms or {"random": {"seed": seed}},
        max_trials=max_trials,
        pool_size=pool_size,
        metadata={"user": "chaos"},
    ).instantiate(seed=seed)
    experiment.heartbeat = heartbeat  # reply-lost orphans recover fast
    producer = Producer(experiment, max_idle_time=max_idle_time)
    producer.update()
    stop_at = time.monotonic() + deadline
    iterations = 0
    while not experiment.is_done:
        if time.monotonic() >= stop_at:
            raise TimeoutError(
                f"chaos run failed to converge within {deadline}s "
                f"({iterations} iterations)"
            )
        iterations += 1
        if proxy is not None and drop_every and iterations % drop_every == 0:
            proxy.drop_all()  # scheduled "server restart"
        try:
            trial = reserve_trial(experiment, producer)
            value = float(next(iter(trial.params.values())))
            experiment.update_completed_trial(
                trial, [Result("obj", "objective", value)]
            )
        except Exception as exc:
            # The worker loop's degradation contract: transient failures
            # that exhausted the storage policy back off and retry; real
            # bugs raise.
            if not is_transient(exc):
                raise
            time.sleep(0.01)
    # Recover any reply-lost orphaned reservations the run left behind —
    # the sweep is the production path for exactly this state.
    time.sleep(0.05)
    experiment.fix_lost_trials()
    report = audit_experiment(
        experiment.storage, experiment, lost_timeout=experiment.heartbeat
    )
    return experiment, report


class OrionState(contextlib.AbstractContextManager):
    """Temporary, fully-populated orion-tpu stack for tests.

    Parameters
    ----------
    experiments : list of dict
        Experiment configs for :func:`build_experiment` (each needs at least
        ``name``; ``priors`` defaults to a 1-D uniform space and
        ``algorithms`` to the scriptable ``dumbalgo``).
    trials / lies : list of dict or Trial
        Preloaded into the FIRST experiment unless a dict carries an
        explicit ``experiment`` id.
    pickled : bool
        Use a file-locked pickled DB in a private tempdir instead of the
        in-memory store — reach for this in multi-process tests.
    """

    def __init__(self, experiments=(), trials=(), lies=(), pickled=False):
        self._experiment_configs = list(experiments)
        self._trial_docs = list(trials)
        self._lie_docs = list(lies)
        self._pickled = pickled
        self._tempdir = None
        self._saved_singleton = None
        self.storage = None
        self.experiments = []

    # --- setup / teardown ---------------------------------------------------
    def __enter__(self):
        if self._pickled:
            self._tempdir = tempfile.mkdtemp(prefix="orion_tpu_state_")
            self.storage = create_storage(
                {"type": "pickled", "path": os.path.join(self._tempdir, "db.pkl")}
            )
        else:
            self.storage = create_storage({"type": "memory"})
        self._saved_singleton = _storage_base._storage_singleton
        _storage_base._storage_singleton = self.storage

        for config in self._experiment_configs:
            config = dict(config)
            name = config.pop("name")
            config.setdefault("priors", {"/x": "uniform(0, 1)"})
            config.setdefault("algorithms", {"dumbalgo": {}})
            self.experiments.append(
                build_experiment(self.storage, name, **config)
            )
        default_exp = self.experiments[0].id if self.experiments else None
        for doc in self._trial_docs:
            self.storage.register_trial(self._as_trial(doc, default_exp))
        for doc in self._lie_docs:
            self.storage.register_lie(self._as_trial(doc, default_exp))
        return self

    def __exit__(self, exc_type, exc, tb):
        _storage_base._storage_singleton = self._saved_singleton
        if self._tempdir:
            shutil.rmtree(self._tempdir, ignore_errors=True)
        return False

    # --- helpers ------------------------------------------------------------
    def _as_trial(self, doc, default_experiment):
        if isinstance(doc, Trial):
            if doc.experiment is None and default_experiment is not None:
                doc.experiment = default_experiment
            return doc
        doc = dict(doc)
        doc.setdefault("experiment", default_experiment)
        return Trial(**doc)

    def get_experiment(self, name, version=None):
        """Reload an experiment from the temporary storage."""
        query = {"name": name}
        if version is not None:
            query["version"] = version
        docs = self.storage.fetch_experiments(query)
        if not docs:
            raise KeyError(f"no experiment {name!r} in OrionState")
        return Experiment(self.storage, max(docs, key=lambda d: d.get("version", 1)))
