"""Dotted-key dict flatten/unflatten helpers.

Used by the ephemeral document store and layered configuration (capability
parity: reference `src/orion/core/utils/flatten.py`).
"""


def flatten(nested, prefix=""):
    """Flatten a nested dict into a single-level dict with dotted keys."""
    out = {}
    for key, value in nested.items():
        full = f"{prefix}{key}"
        if isinstance(value, dict) and value:
            out.update(flatten(value, prefix=full + "."))
        else:
            out[full] = value
    return out


def unflatten(flat):
    """Inverse of :func:`flatten`."""
    out = {}
    for key, value in flat.items():
        parts = key.split(".")
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out
