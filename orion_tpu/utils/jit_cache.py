"""Persistent XLA compilation cache for the framework's jitted steps.

The fused suggest step compiles once per (padded-buffer-size, q-bucket,
config) signature; a TPU compile costs tens of seconds, and every fresh
process pays it again for each bucket its history growth crosses.  Pointing
jax at an on-disk cache makes every later process (and every later bucket
crossing in CI/benchmarks) warm.  SURVEY.md §5 assigns profiling/latency
concerns to the TPU build; this is the biggest single lever.

Opt out with ORION_TPU_JIT_CACHE=off, or point it at a custom directory.
A user-configured jax cache dir always wins.
"""

import logging
import os

log = logging.getLogger(__name__)

_DISABLE = ("0", "off", "false", "no")


def enable_persistent_compilation_cache():
    """Idempotent; returns the cache dir in effect (None when disabled)."""
    import jax

    if not hasattr(jax.config, "jax_compilation_cache_dir"):
        return None  # jax build without a persistent cache: nothing to do
    configured = jax.config.jax_compilation_cache_dir
    if configured:  # the user (or a test harness) already chose one
        return configured
    override = os.environ.get("ORION_TPU_JIT_CACHE", "").strip()
    if override.lower() in _DISABLE:
        return None
    if override and override.lower() not in ("1", "on", "true", "yes"):
        # A path; bare enable values keep the default location (same
        # boolean-flag convention as ORION_TPU_PALLAS).
        cache_dir = override
    else:
        xdg = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
        cache_dir = os.path.join(xdg, "orion_tpu", "jax_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        # Catch the acquisition sub-jits too; below ~0.5s the write
        # amplification outweighs the win.  Respect a user-tuned threshold
        # (only replace jax's default), and set the dir LAST so the return
        # value always matches the enabled/disabled state.
        if getattr(jax.config, "jax_persistent_cache_min_compile_time_secs", None) == 1.0:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as exc:  # unwritable home, read-only fs, old jax…
        log.debug("persistent compilation cache unavailable: %s", exc)
        return None
    return cache_dir
