"""Per-trial working directory context manager.

Capability parity: reference `src/orion/core/utils/working_dir.py` — a
permanent directory (created under the experiment working dir, kept) or a
self-cleaning temporary directory per trial.
"""

import os
import shutil
import tempfile


class WorkingDir:
    def __init__(self, working_dir=None, temp=None, suffix="", prefix="trial-"):
        self.working_dir = working_dir
        self.temp = temp if temp is not None else working_dir is None
        self.suffix = suffix
        self.prefix = prefix
        self.path = None

    def __enter__(self):
        if self.temp:
            self.path = tempfile.mkdtemp(
                suffix=self.suffix, prefix=self.prefix, dir=self.working_dir
            )
        else:
            self.path = os.path.join(self.working_dir, self.prefix + self.suffix)
            os.makedirs(self.path, exist_ok=True)
        return self.path

    def __exit__(self, exc_type, exc_value, traceback):
        if self.temp and self.path:
            shutil.rmtree(self.path, ignore_errors=True)
