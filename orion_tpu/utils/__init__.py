"""Shared utilities: exceptions, dict flattening, plugin registry."""

from orion_tpu.utils.exceptions import (
    BrokenExperiment,
    CheckError,
    DatabaseError,
    DuplicateKeyError,
    ExecutionError,
    FailedUpdate,
    InvalidResult,
    NoConfigurationError,
    OrionTPUError,
    RaceCondition,
)
from orion_tpu.utils.flatten import flatten, unflatten
from orion_tpu.utils.registry import Registry

__all__ = [
    "BrokenExperiment",
    "CheckError",
    "DatabaseError",
    "DuplicateKeyError",
    "ExecutionError",
    "FailedUpdate",
    "InvalidResult",
    "NoConfigurationError",
    "OrionTPUError",
    "RaceCondition",
    "Registry",
    "flatten",
    "unflatten",
]
