"""Framework-wide exception types.

Capability parity: reference `src/orion/core/utils/exceptions.py` plus DB error
types from `src/orion/core/io/database/__init__.py` (DuplicateKeyError,
DatabaseError) — unified here since our storage layer is one subsystem.
"""


class OrionTPUError(Exception):
    """Base class for all framework errors."""


class NoConfigurationError(OrionTPUError):
    """Raised when an experiment configuration cannot be found."""


class CheckError(OrionTPUError):
    """Raised when a staged database check fails."""


class RaceCondition(OrionTPUError):
    """Raised when a concurrent writer won a create/update race.

    Callers are expected to re-fetch state and retry once (reference semantics:
    `experiment_builder.py:239-251`).
    """


class DatabaseError(OrionTPUError):
    """Generic storage-backend failure.

    ``maybe_applied`` marks the applied-or-not-unknowable failures: the
    operation MAY have been durably applied before the failure surfaced
    (the network driver's lost-in-flight-mutation case, a fault-injected
    reply loss).  The unified retry policy (``storage/retry.py``) only
    re-runs such a failure for operations that converge under
    re-application; everything else surfaces the ambiguity.  Class
    default False; raisers set the instance attribute."""

    maybe_applied = False


class DuplicateKeyError(DatabaseError):
    """A unique-index constraint was violated on insert/update."""


class AuthenticationError(DatabaseError):
    """Network storage rejected the client's credentials (or none given)."""


class FailedUpdate(DatabaseError):
    """A compare-and-swap update matched no document."""


class ExecutionError(OrionTPUError):
    """User trial script exited with a nonzero return code."""


class BrokenExperiment(OrionTPUError):
    """Too many broken trials; experiment aborted."""


class InvalidResult(OrionTPUError):
    """User script reported malformed results."""


class SampleTimeout(OrionTPUError):
    """Algorithm failed to sample a new unique point within max_idle_time."""


class AlgorithmExhausted(OrionTPUError):
    """A finite algorithm opted out with no trials in flight anywhere.

    Nothing can change its state (no pending observation exists and lies
    have nothing to fantasize over), so the producer ends the hunt now
    instead of burning ``max_idle_time`` (reference opt-out contract:
    `src/orion/algo/base.py:142-163`, `src/orion/core/worker/producer.py:74-78`
    back off forever; workers exit cleanly on this signal)."""


class WaitingForTrials(OrionTPUError):
    """No trial could be reserved right now."""


class MissingResultFile(OrionTPUError):
    """User script exited 0 but never reported results."""
