"""Framework-wide exception types.

Capability parity: reference `src/orion/core/utils/exceptions.py` plus DB error
types from `src/orion/core/io/database/__init__.py` (DuplicateKeyError,
DatabaseError) — unified here since our storage layer is one subsystem.
"""


class OrionTPUError(Exception):
    """Base class for all framework errors."""


class NoConfigurationError(OrionTPUError):
    """Raised when an experiment configuration cannot be found."""


class CheckError(OrionTPUError):
    """Raised when a staged database check fails."""


class RaceCondition(OrionTPUError):
    """Raised when a concurrent writer won a create/update race.

    Callers are expected to re-fetch state and retry once (reference semantics:
    `experiment_builder.py:239-251`).
    """


class DatabaseError(OrionTPUError):
    """Generic storage-backend failure."""


class DuplicateKeyError(DatabaseError):
    """A unique-index constraint was violated on insert/update."""


class AuthenticationError(DatabaseError):
    """Network storage rejected the client's credentials (or none given)."""


class FailedUpdate(DatabaseError):
    """A compare-and-swap update matched no document."""


class ExecutionError(OrionTPUError):
    """User trial script exited with a nonzero return code."""


class BrokenExperiment(OrionTPUError):
    """Too many broken trials; experiment aborted."""


class InvalidResult(OrionTPUError):
    """User script reported malformed results."""


class SampleTimeout(OrionTPUError):
    """Algorithm failed to sample a new unique point within max_idle_time."""


class WaitingForTrials(OrionTPUError):
    """No trial could be reserved right now."""


class MissingResultFile(OrionTPUError):
    """User script exited 0 but never reported results."""
