"""Colored terminal diffs for the branching flow.

Capability parity: reference `src/orion/core/utils/diff.py` (red/green
ANSI-colored diff lines shown during conflict resolution).  Colors engage
only on a TTY and honor the NO_COLOR convention — branching output is also
consumed by tests and scripted sessions, which must see plain text.
"""

import os
import sys

_RESET = "\x1b[0m"
_COLORS = {
    "+": "\x1b[0;32m",  # additions: green
    "-": "\x1b[0;31m",  # removals: red
    "~": "\x1b[0;33m",  # changes: yellow
    ">": "\x1b[0;36m",  # renames: cyan
}


def color_enabled(stream=None):
    stream = stream if stream is not None else sys.stdout
    if os.environ.get("NO_COLOR"):
        return False
    return bool(getattr(stream, "isatty", lambda: False)())


def colorize_diff_line(line, stream=None):
    """Color one conflict diff line by its leading marker (+/-/~/>)."""
    if not color_enabled(stream):
        return line
    code = _COLORS.get(line[:1])
    if code is None:
        return line
    return f"{code}{line}{_RESET}"
