"""Plugin registry — the framework's extension mechanism.

Capability parity with the reference's ``Factory`` metaclass + pkg_resources
entry-point discovery (`src/orion/core/utils/__init__.py:80-160`), redesigned
without metaclass magic: an explicit registry per extension kind (algorithms,
storage backends, parallel strategies, adapters, converters) that also scans
``importlib.metadata`` entry points lazily, so third-party packages can ship
algorithms by declaring an ``orion_tpu.<kind>`` entry point.
"""

import importlib.metadata
import logging

log = logging.getLogger(__name__)


class Registry:
    """Named registry of classes with entry-point discovery."""

    def __init__(self, kind, entry_point_group=None):
        self.kind = kind
        self.entry_point_group = entry_point_group or f"orion_tpu.{kind}"
        self._classes = {}
        self._scanned_entry_points = False

    def register(self, name=None):
        """Class decorator: ``@registry.register("random")``."""

        def deco(cls):
            key = (name or cls.__name__).lower()
            self._classes[key] = cls
            return cls

        return deco

    def add(self, name, cls):
        self._classes[name.lower()] = cls

    def _scan_entry_points(self):
        if self._scanned_entry_points:
            return
        self._scanned_entry_points = True
        try:
            eps = importlib.metadata.entry_points(group=self.entry_point_group)
        except Exception:  # pragma: no cover - metadata backend quirks
            return
        for ep in eps:
            if ep.name.lower() in self._classes:
                continue
            try:
                self._classes[ep.name.lower()] = ep.load()
            except Exception as exc:  # pragma: no cover
                log.warning("Could not load %s plugin %r: %s", self.kind, ep.name, exc)

    def get(self, name):
        key = name.lower()
        if key not in self._classes:
            self._scan_entry_points()
        if key not in self._classes:
            raise NotImplementedError(
                f"Unknown {self.kind} {name!r}. Available: {sorted(self._classes)}"
            )
        return self._classes[key]

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)

    def names(self):
        self._scan_entry_points()
        return sorted(self._classes)
