"""Distributed-trace analysis: cross-process merge + critical-path attribution.

``orion_tpu.telemetry`` records the spans and stamps the
:class:`~orion_tpu.telemetry.TraceContext` fields; this module answers the
two questions the merged records exist for:

- **merge** (:func:`collect_distributed_spans`): one causally-linked span
  set per experiment.  Worker processes flush their spans through the
  storage channel keyed by experiment; adopting SERVERS (the netdb
  ``DBServer``) have no experiment identity — the requests they serve are
  raw document ops — so they flush under the reserved
  :data:`SERVER_EXPERIMENT` id and the merge joins them back to the
  experiment by ``trace_id``: a server span is included exactly when its
  trace appears in the experiment's own spans.

- **attribution** (:func:`attribute_traces` / :func:`summarize_attribution`):
  the per-trace critical-path split behind ``orion-tpu trace --attribute``
  and bench's ``host_attribution`` payload.  Each sampled round's wall time
  (the trace's root span, normally ``producer.round``) buckets into
  client-host / wire / server-host / device, turning ROADMAP item 2's
  "~90% of the round is host work" into a measurement with an address:

  - **device**: spans named in :data:`DEVICE_SPAN_NAMES` (async device
    windows, fused-step dispatch/compile, the gateway's stacked dispatch);
  - **server-host**: spans recorded on server tracks (worker label with a
    ``netdb:``/``gateway:`` prefix) minus their own device children;
  - **wire**: for every client span that has server-track children, the
    client-observed duration minus the server-side time — what the network
    (and framing/serialization) actually cost;
  - **client-host**: the remainder of the root span.

  The split is an approximation over OVERLAPPING spans (the pipelined
  commit deliberately runs under the device window), so buckets are
  clamped non-negative and the residual lands in client-host — consistent
  round over round, which is what a burn-down needs.
"""

#: Reserved experiment id server-side spans are flushed under (the netdb
#: server adopts trace contexts but has no experiment identity).
SERVER_EXPERIMENT = "__server__"

#: Track-label prefixes that mark a span as SERVER-side host work.
SERVER_TRACK_PREFIXES = ("netdb:", "gateway:")

#: Span names booked to the device bucket.
DEVICE_SPAN_NAMES = frozenset(
    {
        "device.dispatch",
        "jax.suggest_step.dispatch",
        "jax.suggest_step.compile",
        "serve.dispatch",
    }
)


def is_server_span(span):
    """True when the record was produced by an adopting server (netdb /
    gateway) rather than a worker — keyed off the track label the server
    stamps into its own records."""
    worker = str(span.get("worker") or "")
    return worker.startswith(SERVER_TRACK_PREFIXES)


def collect_distributed_spans(storage, experiment):
    """The experiment's spans plus every server-side span belonging to one
    of its traces, time-ordered — the input ``orion-tpu trace
    --distributed`` renders and ``--attribute`` analyzes."""
    spans = list(storage.fetch_spans(experiment))
    trace_ids = {s.get("trace_id") for s in spans if s.get("trace_id")}
    if trace_ids:
        try:
            server_spans = storage.fetch_spans(SERVER_EXPERIMENT)
        except Exception:  # third-party protocol without the channel
            server_spans = []
        spans.extend(
            s for s in server_spans if s.get("trace_id") in trace_ids
        )
    spans.sort(key=lambda s: s.get("ts") or 0.0)
    return spans


def _group_traces(spans):
    """trace_id -> member spans.  A span with LINKS but no trace identity
    of its own (the gateway's shared coalesced dispatch) belongs to EVERY
    linked trace — each tenant's round genuinely waited on that dispatch,
    so each trace's device bucket must see it."""
    traces = {}
    for span in spans:
        trace_id = span.get("trace_id")
        if trace_id:
            traces.setdefault(trace_id, []).append(span)
        for link in span.get("links") or ():
            linked = (link or {}).get("trace_id")
            if linked and linked != trace_id:
                traces.setdefault(linked, []).append(span)
    return traces


def attribute_traces(spans):
    """Per-trace critical-path buckets (ms), keyed by trace_id.

    Only traces with an identifiable ROOT span (no ``parent_span_id`` —
    the producer round) are attributed: a trace whose root was evicted
    from the ring has no honest total to split."""
    out = {}
    for trace_id, members in _group_traces(spans).items():
        roots = [s for s in members if not s.get("parent_span_id")]
        if not roots:
            continue
        root = max(roots, key=lambda s: float(s.get("dur") or 0.0))
        total = float(root.get("dur") or 0.0)
        device = sum(
            float(s.get("dur") or 0.0)
            for s in members
            if s.get("name") in DEVICE_SPAN_NAMES
        )
        server_spans = [s for s in members if is_server_span(s)]
        server_host = sum(
            float(s.get("dur") or 0.0)
            for s in server_spans
            if s.get("name") not in DEVICE_SPAN_NAMES
        )
        # Wire: client-observed op time minus the server-side time nested
        # under it, summed per client parent of a server span.
        by_id = {s.get("span_id"): s for s in members if s.get("span_id")}
        server_under = {}
        for s in server_spans:
            parent = by_id.get(s.get("parent_span_id"))
            if parent is not None and not is_server_span(parent):
                server_under.setdefault(id(parent), [parent, 0.0])
                server_under[id(parent)][1] += float(s.get("dur") or 0.0)
        wire = sum(
            max(float(parent.get("dur") or 0.0) - nested, 0.0)
            for parent, nested in server_under.values()
        )
        device = min(device, total) if total else device
        client_host = max(total - wire - server_host - device, 0.0)
        out[trace_id] = {
            "root": root.get("name"),
            "total_ms": round(total * 1e3, 3),
            "client_host_ms": round(client_host * 1e3, 3),
            "wire_ms": round(wire * 1e3, 3),
            "server_host_ms": round(server_host * 1e3, 3),
            "device_ms": round(device * 1e3, 3),
            "spans": len(members),
        }
    return out


def summarize_attribution(spans, root_name=None):
    """Mean per-trace bucket split (ms) over every attributed trace —
    bench's ``host_attribution`` payload block and the footer of
    ``orion-tpu trace --attribute``.  ``root_name`` restricts to traces
    rooted at one span name (``producer.round``) so a stray ad-hoc trace
    cannot skew the round numbers."""
    traces = attribute_traces(spans)
    if root_name is not None:
        traces = {k: v for k, v in traces.items() if v["root"] == root_name}
    n = len(traces)
    keys = ("total_ms", "client_host_ms", "wire_ms", "server_host_ms", "device_ms")
    summary = {"traces": n}
    for key in keys:
        summary[key] = (
            round(sum(t[key] for t in traces.values()) / n, 3) if n else None
        )
    return summary


def format_attribution(spans, root_name=None):
    """Human table for ``orion-tpu trace --attribute``."""
    traces = attribute_traces(spans)
    if root_name is not None:
        traces = {k: v for k, v in traces.items() if v["root"] == root_name}
    header = (
        f"{'trace':<18} {'root':<18} {'total':>9} {'client':>9} "
        f"{'wire':>9} {'server':>9} {'device':>9}"
    )
    lines = [header, "-" * len(header)]
    for trace_id, row in sorted(traces.items(), key=lambda kv: -kv[1]["total_ms"]):
        lines.append(
            f"{trace_id[:16]:<18} {str(row['root'])[:18]:<18} "
            f"{row['total_ms']:>9.3f} {row['client_host_ms']:>9.3f} "
            f"{row['wire_ms']:>9.3f} {row['server_host_ms']:>9.3f} "
            f"{row['device_ms']:>9.3f}"
        )
    summary = summarize_attribution(spans, root_name=root_name)
    lines.append("-" * len(header))
    if summary["traces"]:
        lines.append(
            f"{'mean of ' + str(summary['traces']):<18} {'':<18} "
            f"{summary['total_ms']:>9.3f} {summary['client_host_ms']:>9.3f} "
            f"{summary['wire_ms']:>9.3f} {summary['server_host_ms']:>9.3f} "
            f"{summary['device_ms']:>9.3f}"
        )
    else:
        lines.append("(no attributable traces — run with telemetry enabled)")
    return "\n".join(lines)
