"""Shard drain: remove a shard from the topology with zero lost documents.

``orion-tpu db drain SHARD`` is the planned-change half of day-2 storage
operations (ISSUE 20; the unplanned half is replica auto-reprovisioning
and quorum mode).  Shrinking a topology with a bare ``set_topology`` would
strand every document the drained shard holds; the :class:`Drainer` runs
the ring diff **before** the shard disappears and migrates each resident
experiment through the same crash-resumable pin → copy → byte-verify →
flip placement-override machinery live rebalancing uses
(``storage/rebalance.py``) — with two deliberate inversions:

- **Destinations come from the SURVIVOR ring** (the current ring minus the
  drained shard, same vnodes): each resident experiment moves to exactly
  the shard the post-removal ring will hash it to, so dropping the shard
  afterwards changes nothing about placement.
- **Placement overrides live on the DRAINED shard** — the experiments'
  ring home on the topology the routers still run.  Any router resolves a
  drained experiment's ring home TO the drained shard, reads the override
  there, and follows it.  The ``moved`` override is therefore **kept**
  after the flip (the base migrator drops it): it is the only thing
  routing live traffic to the destination until ``set_topology`` removes
  the shard, at which point the new ring maps those experiments straight
  to the destination and the override — gone with the shard — is no
  longer consulted by anyone.

Phase order and crash-resume semantics are inherited from the base
migrator; a re-run recomputes the plan from the standing placement docs
on the drained shard and resumes.  When the drain completes the shard
holds only its ``_placement`` docs (and server-internal bookkeeping);
:meth:`Drainer.residual_experiments` is the completeness check the CLI
and the soak gate assert on.

The drain publishes ``storage.drain.phase_age_s`` — seconds since the
current phase last made progress, 0 between runs — which is what the
DX060 ``drain-stuck`` doctor rule watches (docs/monitoring.md).
"""

import logging
import threading
import time

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.storage.rebalance import Move, RebalancePlan, Rebalancer
from orion_tpu.storage.retry import MODE_ALWAYS
from orion_tpu.storage.shard import PLACEMENT_COLLECTION, HashRing
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import DatabaseError

log = logging.getLogger(__name__)

#: Gauge the DX060 ``drain-stuck`` doctor rule thresholds against.
DRAIN_PHASE_AGE_GAUGE = "storage.drain.phase_age_s"


class Drainer(Rebalancer):
    """Crash-resumable drain of one shard over a
    :class:`~orion_tpu.storage.shard.ShardedNetworkDB` router.

    ``drain_index`` names the shard to empty.  The router must still carry
    the shard (drain runs BEFORE the topology change); call
    ``set_topology`` with the surviving specs once :meth:`run` returns and
    :meth:`residual_experiments` reports zero."""

    def __init__(self, router, drain_index, **kwargs):
        super().__init__(router, **kwargs)
        self.drain_index = int(drain_index)
        if self.drain_index not in self._conns:
            raise DatabaseError(
                f"no shard at index {self.drain_index} "
                f"(topology has {len(self._conns)} shard(s))"
            )
        identities = {s.index: s.identity for s in router._shards}
        self.drain_identity = identities[self.drain_index]
        survivors = [
            identities[i] for i in sorted(identities) if i != self.drain_index
        ]
        if not survivors:
            raise DatabaseError(
                "refusing to drain the only shard — nothing would survive "
                "to receive its documents"
            )
        #: The post-removal ring: same identities minus the drained one,
        #: same vnodes — deterministic, so a crashed drain re-run computes
        #: identical destinations.
        self._dst_ring = HashRing(survivors, vnodes=router._ring.vnodes)
        self._survivors = survivors
        self._identity_to_index = {v: k for k, v in identities.items()}
        # Phase state shared with concurrent gauge readers (doctor probes
        # sample the gauge; the state itself is also inspected by tests) —
        # every access under the lock, TSAN-annotated.
        self._phase_lock = threading.Lock()
        self._phase = None
        self._phase_since = time.monotonic()

    # --- plan ----------------------------------------------------------------
    def _dst_index(self, exp_id):
        """Post-removal ring home of one experiment, as a CURRENT-topology
        shard index."""
        identity = self._survivors[self._dst_ring.lookup(str(exp_id))]
        return self._identity_to_index[identity]

    def plan(self):
        """Every experiment RESIDENT on the drained shard, destined for
        its survivor-ring home.  Refuses (as strays) anything that needs a
        rebalance first: a resident experiment whose current-ring home is
        some OTHER shard (its move belongs to ``db rebalance``), an
        experiment ring-homed here but living elsewhere without an
        override, and any unfinished migration state found on other
        shards — one migrator owns the placement machinery at a time."""
        placements = {}
        foreign_placements = []
        for index, conn in self._conns.items():
            docs = self.policy.run(
                lambda conn=conn: conn.read(PLACEMENT_COLLECTION, {}),
                op="drain.plan.placements", mode=MODE_ALWAYS,
            )
            for doc in docs:
                exp_id = str(doc.get("experiment"))
                if index == self.drain_index:
                    placements[exp_id] = doc
                else:
                    foreign_placements.append((exp_id, [index]))
        located = {}
        meta = {}
        for index, conn in self._conns.items():
            docs = self.policy.run(
                lambda conn=conn: conn.read("experiments", {}),
                op="drain.plan.experiments", mode=MODE_ALWAYS,
            )
            for doc in docs:
                exp_id = str(doc["_id"])
                located.setdefault(exp_id, []).append(index)
                meta.setdefault(
                    exp_id, (doc.get("name"), doc.get("version", 1))
                )
        moves, stays, strays = [], 0, list(foreign_placements)
        for exp_id in sorted(set(located) | set(placements)):
            name, version = meta.get(exp_id, ("?", "?"))
            homes = located.get(exp_id, [])
            placement = placements.get(exp_id)
            ring_home = self.router.shard_for(exp_id)
            if placement is None and self.drain_index not in homes:
                if ring_home == self.drain_index and homes:
                    # Ring-homed here but living elsewhere with no
                    # override: a half-finished REBALANCE this machine
                    # didn't start — operator eyes.
                    strays.append((exp_id, homes))
                else:
                    stays += 1
                continue
            if placement is None and ring_home != self.drain_index:
                # Resident here but ring-homed elsewhere: a pending
                # `db rebalance` — running both diffs as one would race
                # the other migrator's state machine.
                strays.append((exp_id, homes))
                continue
            dst_index = self._dst_index(exp_id)
            state = placement.get("state") if placement is not None else None
            if state == "moved":
                moves.append(
                    Move(
                        exp_id, name, version,
                        self.drain_index, dst_index, "moved",
                    )
                )
                continue
            moves.append(
                Move(exp_id, name, version, self.drain_index, dst_index, state)
            )
        return RebalancePlan(moves, stays, strays)

    # --- base-machinery inversions -------------------------------------------
    def _placement_conn(self, move):
        """Override docs live on the DRAINED shard — the experiments' ring
        home on the topology the routers still run (module docstring)."""
        return self._conns[self.drain_index]

    def _drop_placement(self, move):
        """Keep the ``moved`` override: it routes live traffic to the
        destination until ``set_topology`` removes the drained shard (and
        the override with it).  Dropping it here would bounce routers back
        to the ring — which still names the drained, now-empty shard."""

    # --- phase-age gauge (DX060) ---------------------------------------------
    def _note_phase(self, name):
        with self._phase_lock:
            TSAN.write("Drainer._phase", self)
            self._phase = name
            self._phase_since = time.monotonic()
        TELEMETRY.set_gauge(DRAIN_PHASE_AGE_GAUGE, 0.0)

    def _note_progress(self):
        with self._phase_lock:
            TSAN.write("Drainer._phase", self)
            since = self._phase_since
        TELEMETRY.set_gauge(
            DRAIN_PHASE_AGE_GAUGE, max(0.0, time.monotonic() - since)
        )

    def phase(self):
        """``(phase_name_or_None, seconds_in_phase)`` — operator surface."""
        with self._phase_lock:
            TSAN.write("Drainer._phase", self)
            return self._phase, max(0.0, time.monotonic() - self._phase_since)

    # --- completeness --------------------------------------------------------
    def residual_experiments(self):
        """Experiment ids still resident on the drained shard — must be
        empty before ``set_topology`` may drop it.  (``_placement`` docs
        and server bookkeeping are EXPECTED to remain; they vanish with
        the shard.)"""
        conn = self._conns[self.drain_index]
        docs = self.policy.run(
            lambda: conn.read("experiments", {}),
            op="drain.residual", mode=MODE_ALWAYS,
        )
        return [str(doc["_id"]) for doc in docs]

    def ring_share(self):
        """Fraction of the hash space the drained shard owns on the
        CURRENT ring — the expected move fraction (the soak gate bounds
        the observed fraction by 2x of this)."""
        ring = self.router._ring
        span = 1 << 64
        total = 0
        hashes, indices = ring._hashes, ring._indices
        for position, point in enumerate(hashes):
            if indices[position] != self.drain_index:
                continue
            previous = hashes[position - 1] if position else hashes[-1] - span
            total += point - previous
        return total / span
