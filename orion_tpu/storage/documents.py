"""In-memory Mongo-flavored document store.

Capability parity: reference `src/orion/core/io/database/ephemeraldb.py`
(collections of flattened documents, unique indexes with duplicate detection,
query operators ``$ne,$in,$gte,$gt,$lte,$lt``, projection semantics) and the
`AbstractDB` contract from `src/orion/core/io/database/__init__.py`
(read/write/read_and_write/count/remove/ensure_index + DuplicateKeyError).

This is the reference model for correctness; the pickled file backend wraps
one of these under a cross-process file lock.
"""

import copy
import json
import threading

from orion_tpu.utils.exceptions import DatabaseError, DuplicateKeyError


def json_default(value):
    """Tolerate numpy scalars/arrays in documents (params carry them)."""
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return value.item()
        except Exception:
            pass
    tolist = getattr(value, "tolist", None)
    if callable(tolist):
        return value.tolist()
    raise TypeError(f"not JSON serializable: {type(value)}")


def dumps_canonical(value):
    """Canonical JSON form of a document: sorted keys, numpy tolerated.
    Shared by the sqlite backend (row payloads, unique-index keys) and
    `db copy` (content comparison across backend representations)."""
    return json.dumps(value, sort_keys=True, default=json_default)


def index_key(doc, fields):
    """Canonical key of a document under a (possibly dotted) field tuple —
    the key function every backend's unique-index enforcement agrees on."""
    return dumps_canonical([_get_path(doc, f)[1] for f in fields])

def _ordered(op):
    """Range operators never raise on incomparable types — they just don't
    match.  A list-valued field meeting ``{$gte: 2}`` must behave the same
    on every backend; letting TypeError escape made the in-process backends
    raise it while the network server translated it into a DatabaseError —
    a per-backend divergence (found by the differential fuzzer) and a way
    for one malformed query to break a shared server's request loop."""

    def safe(doc_val, qv):
        if doc_val is None:
            return False
        try:
            # bool() inside the try: numpy-array field values make the
            # comparison return an elementwise array whose truthiness
            # raises LATER (outside any guard) — force the ValueError here.
            return bool(op(doc_val, qv))
        except (TypeError, ValueError):
            return False

    return safe


def _in(doc_val, qv):
    try:
        return bool(doc_val in qv)
    except (TypeError, ValueError):
        return False


_OPS = {
    "$ne": lambda doc_val, qv: doc_val != qv,
    "$in": _in,
    "$gte": _ordered(lambda a, b: a >= b),
    "$gt": _ordered(lambda a, b: a > b),
    "$lte": _ordered(lambda a, b: a <= b),
    "$lt": _ordered(lambda a, b: a < b),
}


def _plain_value(value):
    """Numpy values normalize to their python list/scalar form BEFORE any
    comparison, so the in-process backends judge queries on exactly what
    the sqlite/network backends stored (those serialize through JSON on
    write).  Without this, {'a': np.array(...)} matched {'a': {'$ne': 2}}
    differently per backend — and equality raised ValueError at
    array-truthiness time (differential-fuzzer find, extended by review)."""
    tolist = getattr(value, "tolist", None)
    if callable(tolist) and not isinstance(value, (str, bytes, list, dict)):
        try:
            return value.tolist()
        except Exception:  # pragma: no cover - exotic array-likes
            return value
    return value


def _match_value(doc_val, query_val):
    doc_val = _plain_value(doc_val)
    if isinstance(query_val, dict) and any(k.startswith("$") for k in query_val):
        return all(_OPS[op](doc_val, qv) for op, qv in query_val.items())
    try:
        return bool(doc_val == query_val)
    except ValueError:  # pragma: no cover - array-likes without tolist
        return False


def _matches(nested_doc, query):
    """Match a query against a nested document, walking dotted paths
    directly — flattening the whole document per candidate per query was the
    dominant cost of every collection scan at q-batch scale."""
    for key, qv in (query or {}).items():
        found, value = _get_path(nested_doc, key)
        if not _match_value(value if found else None, qv):
            return False
    return True


def _get_path(doc, dotted):
    """Resolve a dotted path against nested dicts; literal keys win first."""
    if dotted in doc:
        return True, doc[dotted]
    node = doc
    for part in dotted.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return False, None
    return True, node


def _hashable(value):
    try:
        hash(value)
        return value
    except TypeError:
        return repr(value)


class _Unhashable:
    """Sentinel bucket key for value maps: repr() is not canonical under
    equality ([1] == [1.0] but their reprs differ), so unhashable stored
    values all share one bucket that every narrowed scan includes."""


def _value_map_key(value):
    try:
        hash(value)
        return value
    except TypeError:
        return _Unhashable


def _set_path(doc, dotted, value):
    parts = dotted.split(".")
    node = doc
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


_SCALAR_TYPES = frozenset((str, int, float, bool, type(None)))


def _copy_doc(value):
    """Deep copy for JSON-like documents (dict/list/scalars) without
    copy.deepcopy's dispatch+memo machinery — which dominated the in-memory
    backend's profile (28 s of a 32 s q=512 ackley50 run was deepcopy).
    Documents are acyclic JSON-ish trees, so direct recursion is safe;
    exotic node values (numpy arrays, tuples, sets) fall back per-node.
    Scalar leaves are handled inline in the comprehensions — most nodes of
    a trial document are {name,type,value} leaves, and a function call per
    scalar is the bulk of the copy cost at q-batch scale."""
    tv = type(value)
    if tv is dict:
        return {
            k: (v if type(v) in _SCALAR_TYPES else _copy_doc(v))
            for k, v in value.items()
        }
    if tv is list:
        return [v if type(v) in _SCALAR_TYPES else _copy_doc(v) for v in value]
    if tv in _SCALAR_TYPES:
        return value
    return copy.deepcopy(value)


def _project(nested_doc, projection):
    """Inclusion-style projection walking dotted paths directly — documents
    with literal "." in keys are returned byte-identical, never restructured."""
    if not projection:
        return _copy_doc(nested_doc)
    keep_id = projection.get("_id", 1)
    selected = {k for k, v in projection.items() if v and k != "_id"}
    out = {}
    for key in selected:
        found, value = _get_path(nested_doc, key)
        if found:
            if key in nested_doc:
                out[key] = _copy_doc(value)
            else:
                _set_path(out, key, _copy_doc(value))
    if keep_id and "_id" in nested_doc:
        out["_id"] = nested_doc["_id"]
    return out


def apply_update(doc, update):
    """Return a new doc with a Mongo-style update applied; ``doc`` is never
    mutated.

    Copy-on-write along the updated paths only: the returned doc SHARES
    every unmodified subtree with ``doc``.  That is safe because every
    caller replaces the stored doc with the result and discards the old one
    (reads hand out `_copy_doc`/`_project` copies, and indexes reference
    `_id`s, not subtrees) — and it is what keeps a 2-field status update
    from deep-copying a several-hundred-node trial document (a 2048-trial
    ackley50 sweep spends ~35% of its host wall in `_copy_doc` otherwise,
    most of it under updates).

    Walks dotted update keys into the nested doc directly — never
    flatten/unflatten the whole document, which would restructure any
    stored key that itself contains a "." (e.g. a param named "opt.lr").
    Shared by every backend (memory/pickled/network/sqlite) so update
    semantics cannot diverge."""
    sets = update.get("$set") if any(k.startswith("$") for k in update) else update
    unsets = update.get("$unset", {})
    new_doc = dict(doc)
    for key, value in (sets or {}).items():
        parts = key.split(".")
        node = new_doc
        for part in parts[:-1]:
            child = node.get(part)
            # Shallow-copy the dict on the path (COW); anything else is
            # replaced by {} (previous behavior).  Re-copying a dict this
            # update already copied is redundant but harmless.
            node[part] = dict(child) if isinstance(child, dict) else {}
            node = node[part]
        node[parts[-1]] = _copy_doc(value)
    for key in unsets:
        parts = key.split(".")
        # Read-only probe first: an absent final key must stay an
        # allocation-free no-op — the COW walk below copies every dict on
        # the path, which would manufacture garbage for a no-op update.
        probe = new_doc
        for part in parts[:-1]:
            probe = probe.get(part) if isinstance(probe, dict) else None
        if not isinstance(probe, dict) or parts[-1] not in probe:
            continue
        node = new_doc
        for part in parts[:-1]:
            node[part] = dict(node[part])
            node = node[part]
        node.pop(parts[-1], None)
    return new_doc


class Collection:
    """One named collection of documents with unique-index enforcement."""

    def __init__(self):
        self._docs = {}  # _id -> nested document
        self._indexes = {}  # name -> (tuple of fields, unique)
        self._unique_maps = {}  # fields -> {index key -> _id}; O(1) dup checks
        # field -> {value key -> {_id: None}} for single-field indexes:
        # narrows scans for equality/$in queries on indexed fields (the
        # reservation hot path filters on status — a full _matches scan per
        # reservation is O(trials^2) over a q-batch run).  Ordered dicts so
        # candidate order stays deterministic.
        self._value_maps = {}
        self._auto_id = 0

    def __getstate__(self):
        # The hash indexes are derivable from docs+indexes: dropping them
        # keeps pickled snapshots from growing with every distinct value,
        # at an O(n) rebuild-on-load cost (__setstate__).
        state = self.__dict__.copy()
        state.pop("_unique_maps", None)
        state.pop("_value_maps", None)
        return state

    def __setstate__(self, state):
        # DB files pickled by versions that predate the hash indexes must
        # keep loading: rebuild them from the stored docs/indexes.
        self.__dict__.update(state)
        if "_unique_maps" not in self.__dict__:
            self._unique_maps = {}
            for fields, unique in self._indexes.values():
                if unique and fields not in self._unique_maps:
                    self._unique_maps[fields] = self._build_unique_map(fields)
        if "_value_maps" not in self.__dict__:
            self._value_maps = {}
            for fields, _unique in self._indexes.values():
                if len(fields) == 1:
                    self._rebuild_value_map(fields[0])

    # --- indexes ----------------------------------------------------------
    def ensure_index(self, keys, unique=False):
        fields = tuple(k[0] if isinstance(k, (tuple, list)) else k for k in keys)
        name = "_".join(fields) + "_1"
        self._indexes[name] = (fields, unique)
        if unique and fields not in self._unique_maps:
            self._unique_maps[fields] = self._build_unique_map(fields)
        elif not unique:
            # Redefined unique -> non-unique: stop enforcing uniqueness.
            # (Index names are a pure function of the fields tuple, so this
            # entry is the only one that can cover these fields.)
            self._unique_maps.pop(fields, None)
        if len(fields) == 1 and fields[0] not in self._value_maps:
            self._rebuild_value_map(fields[0])

    def _rebuild_value_map(self, field):
        entries = {}
        for _id, doc in self._docs.items():
            key = _value_map_key(_get_path(doc, field)[1])
            entries.setdefault(key, {})[_id] = None
        self._value_maps[field] = entries

    def _build_unique_map(self, fields):
        return {
            self._index_key(doc, fields): _id for _id, doc in self._docs.items()
        }

    def index_information(self):
        return {name: unique for name, (_, unique) in self._indexes.items()}

    def drop_index(self, name):
        if name not in self._indexes:
            raise KeyError(f"index not found: {name}")
        fields, unique = self._indexes.pop(name)
        if unique and not any(
            f == fields and u for f, u in self._indexes.values()
        ):
            self._unique_maps.pop(fields, None)
        if len(fields) == 1:
            self._value_maps.pop(fields[0], None)

    def _index_key(self, doc, fields):
        return tuple(_hashable(_get_path(doc, f)[1]) for f in fields)

    def _check_unique(self, doc, ignore_id=None):
        for fields, entries in self._unique_maps.items():
            other = entries.get(self._index_key(doc, fields))
            if other is not None and other != ignore_id:
                raise DuplicateKeyError(
                    f"duplicate key on index {fields}"
                )

    def _unique_keys(self, doc):
        """One ``_index_key`` computation per unique index, shared by the
        duplicate check AND the index insert — ``insert`` previously paid
        the dotted-path walk + canonicalization twice per document, which
        is pure overhead at q-batch registration scale."""
        return [
            (fields, entries, self._index_key(doc, fields))
            for fields, entries in self._unique_maps.items()
        ]

    def _index_add(self, doc):
        for fields, entries in self._unique_maps.items():
            entries[self._index_key(doc, fields)] = doc["_id"]
        for field, entries in self._value_maps.items():
            key = _value_map_key(_get_path(doc, field)[1])
            entries.setdefault(key, {})[doc["_id"]] = None

    def _index_discard(self, doc):
        for fields, entries in self._unique_maps.items():
            key = self._index_key(doc, fields)
            if entries.get(key) == doc["_id"]:
                del entries[key]
        for field, entries in self._value_maps.items():
            key = _value_map_key(_get_path(doc, field)[1])
            bucket = entries.get(key)
            if bucket is not None:
                bucket.pop(doc["_id"], None)
                if not bucket:
                    del entries[key]  # maps must not grow with history

    # --- CRUD --------------------------------------------------------------
    def insert(self, doc):
        doc = _copy_doc(doc)
        if "_id" not in doc:
            self._auto_id += 1
            doc["_id"] = self._auto_id
        _id = doc["_id"]
        if _id in self._docs:
            raise DuplicateKeyError(f"duplicate _id {_id!r}")
        # Compute each unique-index key ONCE, check-then-add with the same
        # values (the q-batch register path inserts q docs back to back).
        unique_keys = self._unique_keys(doc)
        for fields, entries, key in unique_keys:
            if entries.get(key) is not None:
                raise DuplicateKeyError(f"duplicate key on index {fields}")
        self._docs[_id] = doc
        for _fields, entries, key in unique_keys:
            entries[key] = _id
        for field, entries in self._value_maps.items():
            key = _value_map_key(_get_path(doc, field)[1])
            entries.setdefault(key, {})[_id] = None
        return _id

    def _candidates(self, query):
        """Docs possibly matching: O(1) for point queries by _id; narrowed
        through the value maps for equality/$in on indexed fields (every
        candidate still passes through `_matches` — this only prunes)."""
        _id = (query or {}).get("_id")
        if _id is not None and not isinstance(_id, dict):
            doc = self._docs.get(_id)
            return [doc] if doc is not None else []
        # Pick the cheapest indexed key by bucket sizes FIRST; materialize
        # only the winner (merging every key's buckets would copy the full
        # per-experiment id set on each reservation — O(trials^2) again).
        best_key = None
        best_size = None
        candidates = {}
        for key, qv in (query or {}).items():
            entries = self._value_maps.get(key)
            if entries is None:
                continue
            if isinstance(qv, dict):
                if set(qv) != {"$in"}:
                    continue
                values = qv["$in"]
            else:
                values = [qv]
            try:
                for v in values:
                    hash(v)
            except TypeError:
                continue  # unhashable query value: repr isn't canonical
            size = sum(len(entries.get(v, ())) for v in values) + len(
                entries.get(_Unhashable, ())
            )
            if best_size is None or size < best_size:
                best_key, best_size, candidates = key, size, (entries, values)
        if best_key is None:
            return self._docs.values()
        entries, values = candidates
        ids = {}
        for value in values:
            ids.update(entries.get(value, {}))
        ids.update(entries.get(_Unhashable, {}))
        return [self._docs[i] for i in ids if i in self._docs]

    def find(self, query=None, projection=None):
        out = []
        for doc in self._candidates(query):
            if _matches(doc, query):
                out.append(_project(doc, projection))
        return out

    def update(self, query, update, many=True):
        count = 0
        for doc in list(self._candidates(query)):
            if not _matches(doc, query):
                continue
            _id = doc["_id"]
            new_doc = apply_update(doc, update)
            new_doc["_id"] = _id
            self._check_unique(new_doc, ignore_id=_id)
            self._index_discard(doc)
            self._docs[_id] = new_doc
            self._index_add(new_doc)
            count += 1
            if not many:
                break
        return count

    def find_one_and_update(self, query, update, return_new=True):
        """Atomic single-document compare-and-swap (the sync primitive)."""
        for doc in self._candidates(query):
            if _matches(doc, query):
                _id = doc["_id"]
                new_doc = apply_update(doc, update)
                new_doc["_id"] = _id
                self._check_unique(new_doc, ignore_id=_id)
                self._index_discard(doc)
                self._docs[_id] = new_doc
                self._index_add(new_doc)
                return _copy_doc(new_doc if return_new else doc)
        return None

    def count(self, query=None):
        # No projection/copy per match — the producer's count-gated sync
        # calls this every round; it must cost a scan, not allocations.
        return sum(
            1 for doc in self._candidates(query) if _matches(doc, query)
        )

    def remove(self, query=None):
        doomed = [
            doc["_id"] for doc in self._candidates(query) if _matches(doc, query)
        ]
        for _id in doomed:
            self._index_discard(self._docs[_id])
            del self._docs[_id]
        return len(doomed)


class MemoryDB:
    """Thread-safe in-memory database of named collections."""

    #: A count/targeted query costs a scan here, not a full-DB reload —
    #: the producer's count-gated sync keys on this (see Producer.update).
    cheap_counts = True

    def __init__(self):
        self._collections = {}
        self._lock = threading.RLock()

    def __getstate__(self):
        # The RLock is process-local; the pickled backend provides its own
        # cross-process file lock.
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.RLock()

    def _col(self, name):
        if name not in self._collections:
            self._collections[name] = Collection()
        return self._collections[name]

    def collection_names(self):
        """Every collection this store holds — the enumeration surface the
        netdb replication snapshot and `db dump` walk (every backend offers
        it so full-state transfer never needs backend-specific probing)."""
        with self._lock:
            return sorted(self._collections)

    def index_specs(self):
        """``[(collection, [field, ...], unique), ...]`` for every declared
        index — the shape ``ensure_index`` accepts, so a snapshot resync
        can rebuild the index layout verbatim."""
        with self._lock:
            out = []
            for name in sorted(self._collections):
                for fields, unique in self._collections[name]._indexes.values():
                    out.append((name, list(fields), unique))
            return out

    # AbstractDB-style contract (reference `database/__init__.py:23-264`)
    def ensure_index(self, collection, keys, unique=False):
        with self._lock:
            self._col(collection).ensure_index(keys, unique=unique)

    def ensure_indexes(self, specs):
        """Batched index setup: [(collection, keys, unique), ...] in one pass."""
        with self._lock:
            for collection, keys, unique in specs:
                self._col(collection).ensure_index(keys, unique=unique)

    def index_information(self, collection):
        with self._lock:
            return self._col(collection).index_information()

    def drop_index(self, collection, name):
        with self._lock:
            self._col(collection).drop_index(name)

    def write(self, collection, data, query=None):
        """Insert when no query; update-many when query given."""
        with self._lock:
            return self._write_locked(collection, data, query)

    def update_many(self, collection, pairs):
        """Apply ``[(query, update), ...]`` in order; returns the total
        matched count.  One lock here, one lock/load/dump cycle on the
        pickled wrapper, one transaction on SQL, one pipelined round trip
        on the network driver — the batched-update path schema migrations
        (`db upgrade`) use instead of a write (and a full file rewrite on
        file-backed stores) per document.

        Mid-batch failure semantics are backend-dependent, so callers must
        be idempotent-re-runnable (the migration updates are): memory keeps
        the applied prefix, pickled and SQLite discard the whole batch
        (the pickled wrapper only dumps its state after a clean run;
        SQLite's transaction rolls back), and the network driver applies
        every non-failing pair before raising the first failure (the
        pipeline is fully drained)."""
        with self._lock:
            col = self._col(collection)
            return sum(col.update(q, u, many=True) for q, u in pairs)

    #: Sub-operations apply_batch accepts — the write-cycle subset of the
    #: contract (index management stays per-op: it is startup-time work and
    #: its KeyError semantics don't fit slot outcomes).
    BATCH_OPS = frozenset({"write", "read", "read_and_write", "count", "remove"})

    def apply_batch(self, ops):
        """Apply ``[(op, args, kwargs), ...]`` as ONE atomic unit with
        respect to other clients: the lock is taken once for the whole
        batch, so no concurrent writer interleaves between slots.  Returns
        one outcome per op — the op's result, or the exception instance it
        raised (slot independence: a DuplicateKeyError in slot 3 says
        nothing about slot 4).  This is the backend primitive the batched
        storage write path (register_trials & friends) commits through —
        one lock here, one transaction on SQL, one wire round trip on the
        network driver, one load/dump cycle on the pickled file.

        An op name outside BATCH_OPS is a programming error and rejects
        the WHOLE batch before anything applies (every backend and the
        network server agree on this upfront validation)."""
        for op, _args, _kwargs in ops:
            if op not in self.BATCH_OPS:
                raise DatabaseError(f"bad batch op {op!r}")
        out = []
        with self._lock:
            for op, args, kwargs in ops:
                try:
                    out.append(getattr(self, f"_{op}_locked")(*args, **kwargs))
                except Exception as exc:
                    out.append(exc)
        return out

    def _write_locked(self, collection, data, query=None):
        col = self._col(collection)
        if query is None:
            if isinstance(data, (list, tuple)):
                return [col.insert(doc) for doc in data]
            return col.insert(data)
        return col.update(query, data, many=True)

    def _read_locked(self, collection, query=None, projection=None):
        return self._col(collection).find(query, projection)

    def _read_and_write_locked(self, collection, query, data):
        return self._col(collection).find_one_and_update(query, data)

    def _count_locked(self, collection, query=None):
        return self._col(collection).count(query)

    def _remove_locked(self, collection, query=None):
        return self._col(collection).remove(query)

    def read(self, collection, query=None, projection=None):
        with self._lock:
            return self._read_locked(collection, query, projection)

    def read_and_write(self, collection, query, data):
        with self._lock:
            return self._read_and_write_locked(collection, query, data)

    def count(self, collection, query=None):
        with self._lock:
            return self._count_locked(collection, query)

    def remove(self, collection, query=None):
        with self._lock:
            return self._remove_locked(collection, query)
