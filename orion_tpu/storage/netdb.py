"""Networked document database: TCP server + client driver.

Capability parity: reference `src/orion/core/io/database/mongodb.py` — the
networked, multi-node storage backend.  The reference delegates to an
external mongod; pymongo is not available in this image, so the framework
ships its own wire protocol: newline-delimited JSON requests against a
server-side document store — a locked in-memory
:class:`~orion_tpu.storage.documents.MemoryDB`, or in ``--persist
x.sqlite`` mode a :class:`~orion_tpu.storage.sqlitedb.SQLiteDB` whose
IMMEDIATE transactions serialize writers across per-thread connections.
Either way ``read_and_write`` (find-one-and-update) is atomic across every
connected worker — the same role mongod's atomic `find_one_and_update`
plays in the reference (`mongodb.py:229-247`).

Workers on different hosts coordinate through one server:

    host A$ orion-tpu db serve --port 8765 --persist shared.pkl
    host B$ ORION_DB_TYPE=network ORION_DB_ADDRESS=hostA:8765 orion-tpu hunt ...

The server optionally persists so it can restart without losing the
experiment: a ``--persist x.sqlite`` path backs it with the durable SQLite
store (every mutation committed, WAL); any other path uses rate-limited
pickle snapshots (atomic tempfile + rename, same pattern as PickledDB).
"""

import functools
import hashlib
import hmac
import json
import logging
import os
import pickle
import secrets as _secrets
import socket
import socketserver
import threading
import time

from orion_tpu.health import FLIGHT
from orion_tpu.storage.backends import atomic_pickle_dump
from orion_tpu.storage.documents import MemoryDB
from orion_tpu.telemetry import (
    TELEMETRY,
    Telemetry,
    TraceContext,
    current_trace_context,
)
from orion_tpu.tracing import SERVER_EXPERIMENT
from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.utils.exceptions import (
    AuthenticationError,
    DatabaseError,
    DuplicateKeyError,
)

log = logging.getLogger(__name__)

_TERM = b"\n"
_MAX_LINE = 64 * 1024 * 1024

# Ops a client may invoke — anything else is rejected (the wire protocol is
# not a generic RPC surface).
_DB_OPS = frozenset(
    {
        "write",
        "read",
        "read_and_write",
        "count",
        "remove",
        "ensure_index",
        "ensure_indexes",
        "index_information",
        "drop_index",
        "ping",
        "batch",
    }
)

# Sub-ops a batch request may carry: the write-cycle subset — ONE
# whitelist shared with every in-process backend (index management and
# ping stay per-request).
_BATCH_OPS = MemoryDB.BATCH_OPS

# Ops (and batch sub-ops) that dirty the persisted snapshot.
_MUTATING_OPS = frozenset(
    {"write", "read_and_write", "remove", "ensure_index", "ensure_indexes",
     "drop_index"}
)


class _JSONEncoder(json.JSONEncoder):
    """Tolerate numpy scalars/arrays leaking into documents."""

    def default(self, o):
        for attr in ("item",):  # numpy scalar -> python scalar
            if hasattr(o, attr) and not isinstance(o, (list, dict)):
                try:
                    return o.item()
                except Exception:  # pragma: no cover - exotic objects
                    break
        if hasattr(o, "tolist"):
            return o.tolist()
        return super().default(o)


def _dumps(obj):
    return json.dumps(obj, cls=_JSONEncoder).encode() + _TERM


@functools.lru_cache(maxsize=8)
def _derive_key(secret):
    """PBKDF2-stretched key from the shared secret (100k iterations, once
    per process): a captured handshake MAC then costs an offline attacker
    100k hashes per password guess instead of one — the standard defense
    for human-chosen secrets, same idea as MongoDB's SCRAM iteration
    count."""
    return hashlib.pbkdf2_hmac(
        "sha256", secret.encode(), b"orion-tpu-netdb-v1", 100_000
    )


def _mac(key, *parts):
    """HMAC-SHA256 over the concatenated handshake parts — the secret itself
    never crosses the wire, and per-connection nonces kill replay."""
    return hmac.new(key, "|".join(parts).encode(), "sha256").hexdigest()


def _read_line(sock_file):
    line = sock_file.readline(_MAX_LINE)
    if not line:
        return None
    if not line.endswith(_TERM):
        # Truncated line (the connection died mid-send): treat as closed,
        # never dispatch.  A payload cut ONE byte short of its terminator
        # is still complete JSON, and applying it would break the client's
        # send-phase retry contract — the resend would double-apply.
        return None
    return json.loads(line)


def _encode_outcome(result):
    """One batch-slot outcome as a wire response dict — the same encoding
    ``_dispatch``'s except clauses produce for a standalone request, so the
    client translates both through one path (``_translate``)."""
    if not isinstance(result, Exception):
        return {"ok": True, "result": result}
    if isinstance(result, DuplicateKeyError):
        error = "DuplicateKeyError"
    elif isinstance(result, KeyError):
        error = "KeyError"
    else:
        error = type(result).__name__
    out = {"ok": False, "error": error, "message": str(result)}
    if getattr(result, "maybe_applied", False):
        # The applied-or-not-unknowable marker must survive the wire, or
        # the client-side retry policy would blind-resend non-converging
        # mutations a failing server may already have applied.
        out["maybe_applied"] = True
    return out


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        db = self.server.db
        # No server secret -> open server (localhost dev, --no-auth).
        self._authenticated = self.server.secret is None
        self._auth_nonce = None
        self._hangup = False
        while True:
            try:
                request = _read_line(self.rfile)
            except (json.JSONDecodeError, OSError) as exc:
                log.warning("bad request from %s: %s", self.client_address, exc)
                return
            if request is None:
                return
            self.wfile.write(_dumps(self._dispatch(db, request)))
            if self._hangup:
                # Failed credential check: force a reconnect (and a fresh
                # nonce) per guess, so brute force pays a TCP handshake each.
                return

    def _auth_dispatch(self, request):
        """Two-step mutual handshake, CLIENT proves first: hello -> nonces,
        auth -> client proof, verified before the server's own proof is
        released.  Handing out a server MAC pre-verification would give any
        port-scanner a free chosen-nonce sample to brute-force offline."""
        op = request["op"]
        key = self.server.auth_key
        if op == "auth_hello":
            if key is None:
                return {"ok": True, "result": {"nonce": None}}
            self._auth_client_nonce = str(request.get("nonce", ""))
            self._auth_nonce = _secrets.token_hex(32)
            return {"ok": True, "result": {"nonce": self._auth_nonce}}
        # op == "auth"
        nonce, self._auth_nonce = self._auth_nonce, None  # one-shot
        client_nonce = getattr(self, "_auth_client_nonce", "")
        expected = (
            None
            if (key is None or nonce is None)
            else _mac(key, "client", client_nonce, nonce)
        )
        if expected is not None and hmac.compare_digest(
            str(request.get("mac", "")), expected
        ):
            self._authenticated = True
            return {
                "ok": True,
                "result": {
                    "status": "authenticated",
                    # Mutual: released only to a proven client, so an
                    # impostor server (or mismatched secret files) is
                    # detected client-side before any data flows.
                    "server_mac": _mac(key, "server", client_nonce, nonce),
                },
            }
        self._hangup = True
        return {
            "ok": False,
            "error": "AuthenticationError",
            "message": "bad credentials (wrong or missing shared secret)",
        }

    def _dispatch(self, db, request):
        op = request.get("op")
        if op in ("auth_hello", "auth"):
            return self._auth_dispatch(request)
        if op not in _DB_OPS:
            return {"ok": False, "error": "DatabaseError", "message": f"bad op {op!r}"}
        if op == "ping":
            # Health checks stay open: ping reveals nothing and monitoring
            # should not need the experiment secret.
            return {"ok": True, "result": "pong"}
        if not self._authenticated:
            return {
                "ok": False,
                "error": "AuthenticationError",
                "message": "authentication required (server started with a secret)",
            }
        if op == "batch":
            return self._batch_dispatch(db, request)
        # Distributed tracing: a request may carry an optional `ctx` field
        # (the client's ambient TraceContext) — adopted as the parent of
        # this server's apply span.  Pre-upgrade clients simply omit it;
        # pre-upgrade servers ignored unknown top-level keys, so the field
        # is wire-compatible in both directions.
        t0, ctx = self.server.adopt_begin(request)
        try:
            method = getattr(db, op)
            result = method(*request.get("args", []), **request.get("kwargs", {}))
            if op in _MUTATING_OPS:
                self.server.persist_snapshot()
            return {"ok": True, "result": result}
        except Exception as exc:
            if not isinstance(exc, (DuplicateKeyError, KeyError)):
                log.exception("op %s failed", op)  # pragma: no cover - defensive
            return _encode_outcome(exc)
        finally:
            self.server.adopt_finish(op, t0, ctx)

    def _batch_dispatch(self, db, request):
        """ONE request carrying N sub-operations: applied as one atomic
        unit against the store (one lock hold on MemoryDB, one transaction
        on a SQLite-persisted server) and answered with ONE response line
        holding per-slot outcomes.  Next to ``pipeline`` (N request lines
        in one send) this drops the server's per-op dispatch/persist cycle
        and, in SQLite persist mode, q fsyncs down to one."""
        try:
            args = request.get("args") or [[]]
            ops = args[0] if args else []
            normalized = []
            for entry in ops:
                op = (
                    entry[0]
                    if isinstance(entry, (list, tuple)) and entry
                    else None
                )
                if op not in _BATCH_OPS:
                    return {
                        "ok": False,
                        "error": "DatabaseError",
                        "message": f"bad batch sub-op {op!r}",
                    }
                sub_args = list(entry[1]) if len(entry) > 1 and entry[1] else []
                sub_kwargs = dict(entry[2]) if len(entry) > 2 and entry[2] else {}
                normalized.append((op, sub_args, sub_kwargs))
        except (TypeError, ValueError, KeyError) as exc:
            # A malformed payload must get a structured refusal, never kill
            # the handler without a response line — the client would read
            # that as applied-or-not-unknowable when nothing was applied.
            return {
                "ok": False,
                "error": "DatabaseError",
                "message": f"malformed batch request: {exc}",
            }
        t0, ctx = self.server.adopt_begin(request)
        try:
            apply_batch = getattr(db, "apply_batch", None)
            if apply_batch is not None:
                results = apply_batch(normalized)
            else:  # pragma: no cover - every in-tree store has apply_batch
                results = []
                for op, sub_args, sub_kwargs in normalized:
                    try:
                        results.append(getattr(db, op)(*sub_args, **sub_kwargs))
                    except Exception as exc:
                        results.append(exc)
            if any(op in _MUTATING_OPS for op, _, _ in normalized):
                self.server.persist_snapshot()
            return {"ok": True, "result": [_encode_outcome(r) for r in results]}
        except Exception as exc:
            # Whole-batch failure (e.g. a fault-injected mid-batch kill):
            # encode through the one shared path so markers like
            # maybe_applied survive the wire.
            log.exception("batch of %d ops failed", len(normalized))
            return _encode_outcome(exc)
        finally:
            # In a finally like the single-op path: a FAILED batch is the
            # one whose server-side span the post-mortem needs most.
            self.server.adopt_finish("batch", t0, ctx)


class DBServer(socketserver.ThreadingTCPServer):
    """Serve a document DB over TCP; one request = one atomic DB operation
    (MemoryDB per-op lock, or SQLiteDB transactions in x.sqlite persist
    mode)."""

    allow_reuse_address = True
    daemon_threads = True

    #: Seconds between flushes of the server's OWN adopted-ctx spans into
    #: its spans collection (under the reserved ``__server__`` experiment
    #: id) — what `orion-tpu trace --distributed` joins back by trace_id.
    SPAN_FLUSH_INTERVAL = 1.0
    #: Retention cap for the __server__ span channel (same unbounded-growth
    #: guard as DocumentStorage.SPANS_CAP; pruned with hysteresis to 90%).
    SERVER_SPANS_CAP = 20000

    def __init__(
        self,
        host="127.0.0.1",
        port=0,
        persist=None,
        persist_interval=1.0,
        secret=None,
    ):
        self.persist = persist
        self.persist_interval = persist_interval
        # Server-side span recording rides a PRIVATE registry, not the
        # process-global one: an in-process loopback server sharing the
        # global ring would have its spans drained (exactly-once) by
        # whichever worker flush ran next, splitting them unpredictably
        # between the experiment channel and the __server__ channel.
        # Mutations are gated on the GLOBAL TELEMETRY.enabled switch.
        self._span_tel = Telemetry(enabled=True, span_capacity=2048)
        self._span_flush_lock = threading.Lock()
        self._last_span_flush = 0.0
        self._span_track = f"netdb:{socket.gethostname()}:{os.getpid()}"
        # Shared-secret authentication (reference parity: the networked
        # backend takes username/password credentials,
        # `mongodb.py:86,289`).  None = open server for localhost dev.
        self.secret = secret
        self.auth_key = _derive_key(secret) if secret is not None else None
        self._persist_lock = threading.Lock()
        self._dirty = threading.Event()
        self._stop_flusher = threading.Event()
        self._flusher = None
        # A .sqlite/.db persist path backs the server with the SQLite store:
        # durable per-mutation by design (WAL), so no snapshot machinery —
        # handler threads each get their own connection (thread-local).
        # Header-sniffed so a legacy pickle snapshot named *.db keeps
        # loading as a snapshot.
        from orion_tpu.storage.sqlitedb import SQLiteDB, sqlite_path_selected

        self._snapshotting = bool(persist) and not sqlite_path_selected(persist)
        if persist and not self._snapshotting:
            self.db = SQLiteDB(persist)
        else:
            self.db = MemoryDB()
            if persist and os.path.exists(persist):
                with open(persist, "rb") as handle:
                    self.db = pickle.load(handle)
        super().__init__((host, port), _Handler)
        if self._snapshotting:
            self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
            self._flusher.start()

    @property
    def address(self):
        return self.server_address[:2]

    # --- distributed-trace adoption ------------------------------------------
    def adopt_begin(self, request):
        """``(t0, ctx)`` when this request carries a sampled trace context
        and telemetry is on — the handler's apply span window opens here;
        ``(None, None)`` otherwise (zero-cost beyond one dict get)."""
        if not TELEMETRY.enabled:
            return None, None
        wire = request.get("ctx")
        if wire is None:
            return None, None
        ctx = TraceContext.from_wire(wire)
        if ctx is None or not ctx.sampled:
            return None, None
        return time.perf_counter(), ctx

    def adopt_finish(self, op, t0, ctx):
        """Record the server-side ``netdb.apply`` span parented at the
        client's injected context, on this server's own trace track."""
        if t0 is None:
            return
        self._span_tel.record_span(
            "netdb.apply",
            start=t0,
            args={"op": op},
            parent_ctx=ctx,
            track=self._span_track,
        )
        self.flush_server_spans()

    def flush_server_spans(self, force=False):
        """Drain the private span ring into this server's own ``spans``
        collection under :data:`~orion_tpu.tracing.SERVER_EXPERIMENT`
        (rate-limited; the server has no experiment identity, so the merge
        joins these back by trace_id).  Never raises — observability must
        not break the wire."""
        now = time.monotonic()
        with self._span_flush_lock:
            TSAN.write("DBServer._span_flush", self)
            if not force and now - self._last_span_flush < self.SPAN_FLUSH_INTERVAL:
                return
            self._last_span_flush = now
        spans = self._span_tel.drain_spans()
        if not spans:
            return
        try:
            self.db.write(
                "spans",
                [
                    {"experiment": SERVER_EXPERIMENT, "worker": self._span_track, **s}
                    for s in spans
                ],
            )
            # Bounded retention (runs at most once per flush gate): prune
            # the oldest down to 90% of the cap, same hysteresis rationale
            # as DocumentStorage._prune_spans.
            query = {"experiment": SERVER_EXPERIMENT}
            if self.db.count("spans", query) > self.SERVER_SPANS_CAP:
                docs = self.db.read("spans", query)
                keep = max(1, int(self.SERVER_SPANS_CAP * 0.9))
                if len(docs) > keep:
                    docs.sort(key=lambda d: d.get("ts") or 0.0)
                    cutoff = docs[len(docs) - keep].get("ts") or 0.0
                    self.db.remove(
                        "spans",
                        {"experiment": SERVER_EXPERIMENT, "ts": {"$lt": cutoff}},
                    )
        except Exception:  # pragma: no cover - observability never breaks serving
            log.debug("could not flush server spans", exc_info=True)

    def persist_snapshot(self):
        """Mark the DB dirty; the flusher thread writes at most one snapshot
        per ``persist_interval`` — a per-mutation dump would hold the DB lock
        for an O(DB-size) pickle on every heartbeat at multi-worker scale."""
        self._dirty.set()

    def _flush_loop(self):
        while not self._stop_flusher.wait(self.persist_interval):
            self._flush_if_dirty()

    def _flush_if_dirty(self):
        if not (self._snapshotting and self._dirty.is_set()):
            return
        self._dirty.clear()
        t0 = time.perf_counter() if TELEMETRY.enabled else None
        with self._persist_lock:
            # Hold the DB lock while pickling: handler threads mutate the
            # collections concurrently and pickle iterating a changing dict
            # raises mid-dump.  The static resolver cannot see this edge
            # (the lock lives on the attribute-held db object), so the
            # runtime sanitizer's cross-check anchors its LCK003 here:
            # the ordering is one-directional by construction — no MemoryDB
            # op calls back into the server, so persist_lock is always the
            # outer lock.  Pinned by tests/fixtures/lint/tsan_edge_cases.py.
            # lint: disable=LCK003 -- one-directional flusher edge; persist_lock always outer
            with self.db._lock:
                atomic_pickle_dump(self.persist, self.db)
        if t0 is not None:
            # The persist span rides the server track (no parent: the
            # flusher batches many requests' dirt into one dump).  Recorded
            # OUTSIDE the persist lock — span bookkeeping must never mint a
            # persist_lock -> registry-lock ordering edge.
            self._span_tel.record_span(
                "netdb.persist", start=t0, track=self._span_track
            )

    def shutdown(self):
        self._stop_flusher.set()
        super().shutdown()
        # Span flush BEFORE the final snapshot so adopted spans recorded
        # since the last gate land in the persisted image too.
        if TELEMETRY.enabled:
            self.flush_server_spans(force=True)
        self._flush_if_dirty()  # final durable snapshot

    def serve_background(self):
        """Start serving on a daemon thread; returns (host, port)."""
        thread = threading.Thread(target=self.serve_forever, daemon=True)
        thread.start()
        return self.address


def serve(host="127.0.0.1", port=8765, persist=None, secret=None):  # pragma: no cover - CLI
    """Blocking server entry point (`orion-tpu db serve`)."""
    server = DBServer(host=host, port=port, persist=persist, secret=secret)
    log.info("serving orion-tpu DB on %s:%s", *server.address)
    auth = "shared-secret auth" if secret else "NO auth (open server)"
    print(
        f"orion-tpu db server listening on "
        f"{server.address[0]}:{server.address[1]} ({auth})"
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()


def _translate(response, raise_errors=True):
    """Wire response -> result, or the mapped exception (raised, or returned
    as an instance when ``raise_errors=False`` for pipelined batches)."""
    if response.get("ok"):
        return response.get("result")
    error = response.get("error")
    message = response.get("message", "")
    exc_cls = {
        "DuplicateKeyError": DuplicateKeyError,
        "KeyError": KeyError,
        "AuthenticationError": AuthenticationError,
    }.get(error)
    exc = exc_cls(message) if exc_cls else DatabaseError(f"{error}: {message}")
    if response.get("maybe_applied") and isinstance(exc, DatabaseError):
        exc.maybe_applied = True
    if raise_errors:
        raise exc
    return exc


class NetworkDB:
    """AbstractDB-contract client for a :class:`DBServer`.

    Thread-safe: one socket guarded by a lock (requests are tiny; contention
    is on the server's DB lock anyway).  Idempotent reads reconnect and
    retry transparently across a server restart (``--persist``).  Mutations
    are never blindly re-sent; instead, a connection idle longer than
    ``idle_probe`` seconds is ping-probed (and re-established if dead)
    before a mutation uses it, so the common restart-while-idle case also
    succeeds.  Only a server death in the middle of an in-flight mutation
    surfaces as DatabaseError — the one case where applied-or-not is
    genuinely unknowable without server-side request ids.
    """

    #: A count is one small request/reply, vastly cheaper than shipping the
    #: full trial history over the wire (the producer's count-gated sync
    #: keys on this).
    cheap_counts = True

    def __init__(
        self, host="127.0.0.1", port=8765, timeout=60.0, idle_probe=1.0,
        secret=None,
    ):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.idle_probe = idle_probe
        self.secret = secret
        self._lock = threading.Lock()
        self._sock = None
        self._file = None
        self._last_used = 0.0
        #: Socket send/receive cycles since construction (one per _call,
        #: one per pipeline/batch regardless of op count) — bench.py's
        #: storage breakdown reads this to prove a q-batch round costs O(1)
        #: wire round trips.
        self.round_trips = 0
        #: Request lines put on the wire: a pipeline of N ops writes N (the
        #: server runs N dispatch/persist cycles), the batch op writes 1.
        #: This is the per-round "wire operations" count the breakdown
        #: reports — the quantity the batch op takes from O(q) to O(1).
        self.wire_requests = 0
        #: Re-established connections (any _connect after the first):
        #: restarts, idle-probe failures, send-phase EPIPE resends.  A
        #: rising rate is THE first symptom of a flapping server/link —
        #: exported as the ``storage.network.reconnects`` telemetry counter.
        self.reconnects = 0
        self._ever_connected = False
        # Flipped when a server rejects the batch wire op (pre-batch
        # server); apply_batch then rides pipeline() instead.
        self._batch_unsupported = False

    # --- wire ----------------------------------------------------------------
    def _connect(self):
        TSAN.write("NetworkDB._conn", self)
        self._close()
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        if self._ever_connected:
            self.reconnects += 1
            # Reconnects are flight-recorder events (orion_tpu.health):
            # the first symptom of a flapping link belongs on the crash
            # timeline.  Guarded — no args allocation when disabled.
            if FLIGHT.enabled:
                FLIGHT.record(
                    "storage.reconnect",
                    args={"host": self.host, "port": self.port},
                )
        self._ever_connected = True
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._file = sock.makefile("rb")
        # lint: disable=LCK002 -- every caller of _connect holds _lock
        self._last_used = time.monotonic()
        if self.secret is not None:
            self._authenticate()

    def _authenticate(self):
        """Mutual HMAC handshake on a fresh connection (reconnects redo it):
        client proves first, then verifies the server proof released with
        the auth-ok reply."""
        key = _derive_key(self.secret)
        client_nonce = _secrets.token_hex(16)
        hello = self._exchange(_dumps({"op": "auth_hello", "nonce": client_nonce}))
        result = hello.get("result") or {}
        nonce = result.get("nonce")
        if nonce is None:
            # This client was configured with a secret; silently proceeding
            # against a server that refuses to authenticate would hand every
            # read AND write to whoever answered on this address (DNS/IP
            # hijack, typoed port).  No downgrade.
            self._close()
            raise AuthenticationError(
                f"server {self.host}:{self.port} does not require "
                "authentication, but this client is configured with a "
                "secret — refusing to proceed (remove the secret only if "
                "you trust the network path)"
            )
        reply = self._exchange(
            _dumps({"op": "auth", "mac": _mac(key, "client", client_nonce, nonce)})
        )
        if not reply.get("ok"):
            self._close()
            raise AuthenticationError(reply.get("message", "authentication failed"))
        server_mac = str((reply.get("result") or {}).get("server_mac", ""))
        if not hmac.compare_digest(
            server_mac, _mac(key, "server", client_nonce, nonce)
        ):
            self._close()
            raise AuthenticationError(
                f"server {self.host}:{self.port} failed to prove knowledge of "
                "the shared secret (impostor server, or mismatched secret files)"
            )

    def _close(self):
        TSAN.write("NetworkDB._conn", self)
        for closer in (self._file, self._sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:  # pragma: no cover
                    pass
        self._sock = self._file = None

    def close(self):
        """Public teardown: ``_close`` is the internal caller-holds-_lock
        form — external owners (bench, tests, pools) must come through the
        lock or a concurrent request could race the socket teardown (the
        runtime sanitizer flags the bare form)."""
        with self._lock:
            self._close()

    def __getstate__(self):
        # Sockets don't cross fork/pickle; children reconnect lazily.
        return {
            "host": self.host,
            "port": self.port,
            "timeout": self.timeout,
            "secret": self.secret,
        }

    def __setstate__(self, state):
        self.__init__(**state)

    # Ops safe to re-send after a dropped connection.  Mutating ops must NOT
    # be retried blindly: the server may have applied the request before the
    # reply was lost, and a re-send would double-apply it (a second trial
    # reserved, a spurious DuplicateKeyError on an insert that succeeded).
    _IDEMPOTENT = frozenset({"read", "count", "index_information", "ping"})

    def _exchange(self, payload):
        """One request/response on the current socket; raises on any break.
        Round-trip latency feeds the ``storage.network.rtt`` telemetry
        histogram when the registry is enabled."""
        t0 = time.perf_counter() if TELEMETRY.enabled else None
        TSAN.write("NetworkDB._conn", self)
        self._sock.sendall(payload)
        response = _read_line(self._file)
        if response is None:
            raise ConnectionError("server closed the connection")
        self._last_used = time.monotonic()  # lint: disable=LCK002 -- caller holds _lock
        self.round_trips += 1  # lint: disable=LCK002 -- caller holds _lock
        self.wire_requests += 1  # lint: disable=LCK002 -- caller holds _lock
        if t0 is not None:
            TELEMETRY.observe("storage.network.rtt", time.perf_counter() - t0)
        return response

    def _probe_idle_connection(self):
        """Ping a connection that has sat idle so a mutation never rides a
        half-open socket from a restarted server."""
        if self._sock is None:
            return
        if time.monotonic() - self._last_used <= self.idle_probe:
            return
        try:
            self._exchange(_dumps({"op": "ping"}))
        except (OSError, ConnectionError, json.JSONDecodeError):
            self._close()  # mutation path will reconnect fresh

    @staticmethod
    def _wire_request(op, args, kwargs):
        """The request envelope, with the ambient TraceContext injected as
        the optional ``ctx`` field when telemetry is on — the server adopts
        it as the parent of its apply span.  Pre-upgrade servers ignore the
        key (wire-compatible), and a disabled registry pays one attribute
        check."""
        request = {"op": op, "args": list(args), "kwargs": kwargs}
        if TELEMETRY.enabled:
            ctx = current_trace_context()
            if ctx is not None and ctx.sampled:
                request["ctx"] = ctx.to_wire()
        return request

    def _call(self, op, *args, **kwargs):
        payload = _dumps(self._wire_request(op, args, kwargs))
        retriable = op in self._IDEMPOTENT
        with self._lock:
            for attempt in range(2):
                sent = False
                try:
                    if not retriable:
                        self._probe_idle_connection()
                    if self._sock is None:
                        self._connect()
                    response = self._exchange(payload)
                    break
                except (OSError, ConnectionError, json.JSONDecodeError) as exc:
                    sent = self._sock is not None
                    self._close()
                    if attempt or (sent and not retriable):
                        error = DatabaseError(
                            f"connection to {self.host}:{self.port} lost during "
                            f"{op!r}: {exc}"
                        )
                        # The request may have reached the server before the
                        # connection died: applied-or-not is unknowable, and
                        # the unified retry policy must not blindly re-send
                        # non-converging mutations (storage/retry.py).
                        error.maybe_applied = sent
                        raise error from exc
        return _translate(response)

    def pipeline(self, ops):
        """Execute ``[(op, args, kwargs), ...]`` over ONE round trip.

        All requests are written in a single send; the server's handler loop
        consumes them back-to-back off the stream (each op individually
        atomic, exactly as if sent one by one), and the responses are read in
        order afterwards.  This is what makes q-batch reservation affordable
        over the wire: q pipelined find-one-and-updates cost ~1 RTT instead
        of q serialized ones (the role MongoDB's wire batching plays for the
        reference, `mongodb.py:229-247`).

        Returns a list the same length as ``ops``: each element is the op's
        result, or an *exception instance* (DuplicateKeyError/KeyError/...)
        for that op — per-op failures must not abort the batch (a duplicate
        in slot 3 says nothing about slot 4).  A connection drop mid-batch
        raises DatabaseError: mutations may or may not have applied, same
        contract as a lost in-flight ``_call``.
        """
        if not ops:
            return []
        payload = b"".join(
            _dumps(self._wire_request(op, args, kwargs))
            for op, args, kwargs in ops
        )
        with self._lock:
            # Mirror _call's connect contract: nothing has been sent yet, so
            # one reconnect retry is safe, and a dead server surfaces as
            # DatabaseError (the type the CLI handles), never a raw OSError.
            try:
                self._probe_idle_connection()
                if self._sock is None:
                    self._connect()
            except (OSError, ConnectionError):
                self._close()
                try:
                    self._connect()
                except (OSError, ConnectionError) as exc:
                    # lint: disable=STO003 -- connect failed pre-send: nothing applied
                    raise DatabaseError(
                        f"cannot connect to {self.host}:{self.port} for "
                        f"pipeline of {len(ops)} ops: {exc}"
                    ) from exc
            # Responses are drained CONCURRENTLY with the send (reads and
            # writes ride opposite socket directions): a send-then-read
            # pipeline deadlocks once a big batch fills both kernel socket
            # buffers — the server blocks writing responses nobody reads,
            # stops consuming requests, and the client's sendall blocks too.
            rtt_t0 = time.perf_counter() if TELEMETRY.enabled else None
            responses, reader_error = [], []

            def _drain():
                try:
                    for _ in ops:
                        response = _read_line(self._file)
                        if response is None:
                            raise ConnectionError("server closed the connection")
                        responses.append(response)
                except Exception as exc:  # surfaced after join
                    reader_error.append(exc)

            reader = threading.Thread(target=_drain, daemon=True)
            reader.start()
            try:
                self._sock.sendall(payload)
            except OSError as exc:
                reader_error.append(exc)
            # No join deadline: the socket timeout already bounds each READ
            # (60s of silence = dead server, surfaced by the reader), so the
            # reader always terminates — while a big batch whose responses
            # are steadily streaming in may legitimately take longer than
            # any single-op timeout and must not be declared lost mid-flight.
            reader.join()
            if reader_error:
                exc = reader_error[0]
                self._close()
                error = DatabaseError(
                    f"connection to {self.host}:{self.port} lost during "
                    f"pipeline of {len(ops)} ops: {exc}"
                )
                # A prefix of the pipelined ops may have applied before the
                # connection died (the server dispatches line by line).
                error.maybe_applied = True
                raise error from exc
            self._last_used = time.monotonic()
            self.round_trips += 1
            self.wire_requests += len(ops)
            if rtt_t0 is not None:
                # One histogram sample per socket round trip, same as
                # _exchange — the batch paths are the produce round's
                # dominant wire ops and must not be invisible in the rtt
                # signal.
                TELEMETRY.observe(
                    "storage.network.rtt", time.perf_counter() - rtt_t0
                )
        return [_translate(r, raise_errors=False) for r in responses]

    def apply_batch(self, ops):
        """Execute ``[(op, args, kwargs), ...]`` as ONE wire request/response.

        Tighter than :meth:`pipeline` (N request lines, N response lines,
        N server dispatch/persist cycles in ~1 RTT): the batch rides one
        request line, the server applies it as one atomic unit against the
        store — one lock hold, and in ``--persist x.sqlite`` mode ONE
        transaction/fsync for the whole q-batch — and answers with one
        response line of per-slot outcomes (results or exception
        instances, same contract as pipeline).

        The request reuses this instance's persistent socket.  A send-phase
        failure (EPIPE/ECONNRESET against a socket a restarted server
        closed) means the request line never fully reached the server, so
        nothing was applied and a reconnect + single resend is safe; only a
        failure AFTER the payload was handed off is genuinely unknowable
        and surfaces as DatabaseError.  Talking to a pre-batch server, the
        rejected op falls back to :meth:`pipeline` transparently (and stops
        re-trying the batch op on that instance)."""
        if not ops:
            return []
        if self._batch_unsupported:
            return self.pipeline(ops)
        # The batch's single RESPONSE line aggregates every sub-op result;
        # document-returning ops (read / read_and_write, e.g. a q-batch
        # reservation's claimed trial docs) at large op counts could push
        # it past the server's line cap — which the request-side guard
        # below cannot see.  Chunk those through pipeline's per-op
        # response lines (still ~1 RTT).
        if len(ops) > 512 and any(
            op in ("read", "read_and_write") for op, _, _ in ops
        ):
            return self.pipeline(ops)
        payload = _dumps(
            self._wire_request(
                "batch",
                [[[op, list(args), kwargs] for op, args, kwargs in ops]],
                {},
            )
        )
        if len(payload) > _MAX_LINE:
            # One line over the server's readline cap would be read as a
            # truncated request and silently dropped (surfacing as a
            # misleading "connection lost").  pipeline ships one line per
            # op, so an oversized batch rides it instead.
            return self.pipeline(ops)
        with self._lock:
            response = None
            for attempt in range(2):
                try:
                    # Shrink the applied-or-not window: a socket that sat
                    # idle across a server restart is ping-probed (and
                    # reconnected) before the batch rides it — sendall can
                    # succeed into the kernel buffer of a dead connection.
                    self._probe_idle_connection()
                    if self._sock is None:
                        self._connect()
                    rtt_t0 = time.perf_counter() if TELEMETRY.enabled else None
                    self._sock.sendall(payload)
                except (OSError, ConnectionError) as exc:
                    # Send phase: the request line was not fully delivered
                    # (a partial line is dropped by the server's readline),
                    # so retrying on a fresh connection cannot double-apply.
                    self._close()
                    if attempt:
                        # lint: disable=STO003 -- send-phase loss: nothing applied
                        raise DatabaseError(
                            f"cannot send batch of {len(ops)} ops to "
                            f"{self.host}:{self.port}: {exc}"
                        ) from exc
                    continue
                try:
                    response = _read_line(self._file)
                    if response is None:
                        raise ConnectionError("server closed the connection")
                except (OSError, ConnectionError, json.JSONDecodeError) as exc:
                    # Read phase: the server may or may not have applied the
                    # batch — same contract as a lost in-flight _call.
                    self._close()
                    error = DatabaseError(
                        f"connection to {self.host}:{self.port} lost during "
                        f"batch of {len(ops)} ops: {exc}"
                    )
                    error.maybe_applied = True
                    raise error from exc
                self._last_used = time.monotonic()
                self.round_trips += 1
                self.wire_requests += 1
                if rtt_t0 is not None:
                    TELEMETRY.observe(
                        "storage.network.rtt", time.perf_counter() - rtt_t0
                    )
                break
        try:
            outcomes = _translate(response)
        except DatabaseError as exc:
            if "bad op 'batch'" in str(exc):
                # Pre-batch server: nothing was applied (the op was
                # rejected before dispatch) — downgrade to pipeline.
                self._batch_unsupported = True
                return self.pipeline(ops)
            raise
        return [_translate(r, raise_errors=False) for r in outcomes]

    # --- AbstractDB contract --------------------------------------------------
    def ping(self):
        return self._call("ping") == "pong"

    def ensure_index(self, collection, keys, unique=False):
        return self._call("ensure_index", collection, keys, unique=unique)

    def ensure_indexes(self, specs):
        return self._call("ensure_indexes", [list(s) for s in specs])

    def index_information(self, collection):
        return self._call("index_information", collection)

    def drop_index(self, collection, name):
        return self._call("drop_index", collection, name)

    def write(self, collection, data, query=None):
        return self._call("write", collection, data, query=query)

    def update_many(self, collection, pairs):
        """One pipelined round trip (see MemoryDB.update_many); the first
        per-op failure is raised after the whole batch has been drained."""
        results = self.pipeline(
            [("write", [collection, data], {"query": query})
             for query, data in pairs]
        )
        total = 0
        for result in results:
            if isinstance(result, Exception):
                raise result
            total += result
        return total

    def read(self, collection, query=None, projection=None):
        return self._call("read", collection, query=query, projection=projection)

    def read_and_write(self, collection, query, data):
        return self._call("read_and_write", collection, query, data)

    def count(self, collection, query=None):
        return self._call("count", collection, query=query)

    def remove(self, collection, query=None):
        return self._call("remove", collection, query=query)
