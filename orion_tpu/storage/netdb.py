"""Networked document database: TCP server + client driver.

Capability parity: reference `src/orion/core/io/database/mongodb.py` — the
networked, multi-node storage backend.  The reference delegates to an
external mongod; pymongo is not available in this image, so the framework
ships its own wire protocol: newline-delimited JSON requests against a
server-side document store — a locked in-memory
:class:`~orion_tpu.storage.documents.MemoryDB`, or in ``--persist
x.sqlite`` mode a :class:`~orion_tpu.storage.sqlitedb.SQLiteDB` whose
IMMEDIATE transactions serialize writers across per-thread connections.
Either way ``read_and_write`` (find-one-and-update) is atomic across every
connected worker — the same role mongod's atomic `find_one_and_update`
plays in the reference (`mongodb.py:229-247`).

Workers on different hosts coordinate through one server:

    host A$ orion-tpu db serve --port 8765 --persist shared.pkl
    host B$ ORION_DB_TYPE=network ORION_DB_ADDRESS=hostA:8765 orion-tpu hunt ...

The server optionally persists so it can restart without losing the
experiment: a ``--persist x.sqlite`` path backs it with the durable SQLite
store (every mutation committed, WAL); any other path uses rate-limited
pickle snapshots (atomic tempfile + rename, same pattern as PickledDB).
"""

import functools
import hashlib
import hmac
import json
import logging
import os
import pickle
import random
import secrets as _secrets
import socket
import socketserver
import threading
import time
from collections import deque

from orion_tpu.health import FLIGHT
from orion_tpu.storage.backends import atomic_pickle_dump
from orion_tpu.storage.documents import MemoryDB
from orion_tpu.telemetry import (
    TELEMETRY,
    Telemetry,
    TraceContext,
    current_trace_context,
)
from orion_tpu.tracing import SERVER_EXPERIMENT
from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.utils.exceptions import (
    AuthenticationError,
    DatabaseError,
    DuplicateKeyError,
)

log = logging.getLogger(__name__)

_TERM = b"\n"
_MAX_LINE = 64 * 1024 * 1024

# Ops a client may invoke — anything else is rejected (the wire protocol is
# not a generic RPC surface).
_DB_OPS = frozenset(
    {
        "write",
        "read",
        "read_and_write",
        "count",
        "remove",
        "ensure_index",
        "ensure_indexes",
        "index_information",
        "drop_index",
        "ping",
        "batch",
    }
)

# Sub-ops a batch request may carry: the write-cycle subset — ONE
# whitelist shared with every in-process backend (index management and
# ping stay per-request).
_BATCH_OPS = MemoryDB.BATCH_OPS

# Ops (and batch sub-ops) that dirty the persisted snapshot.
_MUTATING_OPS = frozenset(
    {"write", "read_and_write", "remove", "ensure_index", "ensure_indexes",
     "drop_index"}
)

# Server-level ops outside the document contract: the replication stream a
# primary pushes to its read replicas, the applied-sequence probe the
# pushers (and operators) use to measure replica lag, the promotion op a
# router's election sends to the most-caught-up replica, the
# replica-adoption op auto-reprovisioning sends to a short primary, and the
# consistent-snapshot export behind `orion-tpu db backup`.  All require
# authentication — the replication stream is a full write channel, and
# promotion/adoption/snapshot reshape or export the whole store.
_SERVER_OPS = frozenset({"replicate", "seq", "promote", "adopt_replica", "snapshot"})

# Collections whose writes are SYNC under quorum mode (`storage.quorum`):
# the registration ground truth whose loss the async replication contract
# would otherwise permit on a kill -9 of the primary.  Telemetry, metrics,
# spans and health stay async — they are observability volume, re-emitted
# or tolerably lossy by contract, and gating them on replica acks would put
# the whole heartbeat path behind the slowest replica.
SYNC_COLLECTIONS = frozenset(
    {"experiments", "trials", "lying_trials", "_placement"}
)

# Mutating ops (wire AND batch sub-ops) whose first positional argument
# names the collection — the quorum gate classifies sync vs async through
# it.  Index management carries no collection data worth gating: its
# replay converges identically either way.
_COLLECTION_MUTATORS = frozenset({"write", "read_and_write", "remove"})


def _quorum_sync(op, args):
    """True when ``op(args...)`` mutates a SYNC collection (quorum-gated)."""
    return op in _COLLECTION_MUTATORS and bool(args) and args[0] in SYNC_COLLECTIONS

#: Bounded primary-side replication log (ops, not bytes).  A replica that
#: falls further behind than this gets a full snapshot resync instead of an
#: op replay — the log is a fast path, never the source of truth.
REPL_LOG_CAP = 4096


class _JSONEncoder(json.JSONEncoder):
    """Tolerate numpy scalars/arrays leaking into documents."""

    def default(self, o):
        for attr in ("item",):  # numpy scalar -> python scalar
            if hasattr(o, attr) and not isinstance(o, (list, dict)):
                try:
                    return o.item()
                except Exception:  # pragma: no cover - exotic objects
                    break
        if hasattr(o, "tolist"):
            return o.tolist()
        return super().default(o)


def _dumps(obj):
    return json.dumps(obj, cls=_JSONEncoder).encode() + _TERM


@functools.lru_cache(maxsize=8)
def _derive_key(secret):
    """PBKDF2-stretched key from the shared secret (100k iterations, once
    per process): a captured handshake MAC then costs an offline attacker
    100k hashes per password guess instead of one — the standard defense
    for human-chosen secrets, same idea as MongoDB's SCRAM iteration
    count."""
    return hashlib.pbkdf2_hmac(
        "sha256", secret.encode(), b"orion-tpu-netdb-v1", 100_000
    )


def _mac(key, *parts):
    """HMAC-SHA256 over the concatenated handshake parts — the secret itself
    never crosses the wire, and per-connection nonces kill replay."""
    return hmac.new(key, "|".join(parts).encode(), "sha256").hexdigest()


def _read_line(sock_file):
    line = sock_file.readline(_MAX_LINE)
    if not line:
        return None
    if not line.endswith(_TERM):
        # Truncated line (the connection died mid-send): treat as closed,
        # never dispatch.  A payload cut ONE byte short of its terminator
        # is still complete JSON, and applying it would break the client's
        # send-phase retry contract — the resend would double-apply.
        return None
    return json.loads(line)


def _encode_outcome(result):
    """One batch-slot outcome as a wire response dict — the same encoding
    ``_dispatch``'s except clauses produce for a standalone request, so the
    client translates both through one path (``_translate``)."""
    if not isinstance(result, Exception):
        return {"ok": True, "result": result}
    if isinstance(result, DuplicateKeyError):
        error = "DuplicateKeyError"
    elif isinstance(result, KeyError):
        error = "KeyError"
    else:
        error = type(result).__name__
    out = {"ok": False, "error": error, "message": str(result)}
    if getattr(result, "maybe_applied", False):
        # The applied-or-not-unknowable marker must survive the wire, or
        # the client-side retry policy would blind-resend non-converging
        # mutations a failing server may already have applied.
        out["maybe_applied"] = True
    return out


class ServerHandshake:
    """Server side of the two-step mutual handshake, CLIENT proves first:
    hello -> nonces, auth -> client proof, verified before the server's own
    proof is released.  Handing out a server MAC pre-verification would give
    any port-scanner a free chosen-nonce sample to brute-force offline.

    Extracted so BOTH wire surfaces authenticate identically — the netdb
    handler below and the suggest gateway (``serve/gateway.py``) each hold
    one per connection; ``hangup`` tells the owner to drop the connection
    after a failed credential check (a fresh nonce per guess, so brute
    force pays a TCP handshake each)."""

    AUTH_OPS = frozenset({"auth_hello", "auth"})

    def __init__(self, auth_key):
        self.auth_key = auth_key
        # No server secret -> open server (localhost dev, --no-auth).
        self.authenticated = auth_key is None
        self.hangup = False
        self._nonce = None
        self._client_nonce = ""

    def step(self, request):
        op = request["op"]
        key = self.auth_key
        if op == "auth_hello":
            if key is None:
                return {"ok": True, "result": {"nonce": None}}
            self._client_nonce = str(request.get("nonce", ""))
            self._nonce = _secrets.token_hex(32)
            return {"ok": True, "result": {"nonce": self._nonce}}
        # op == "auth"
        nonce, self._nonce = self._nonce, None  # one-shot
        client_nonce = self._client_nonce
        expected = (
            None
            if (key is None or nonce is None)
            else _mac(key, "client", client_nonce, nonce)
        )
        if expected is not None and hmac.compare_digest(
            str(request.get("mac", "")), expected
        ):
            self.authenticated = True
            return {
                "ok": True,
                "result": {
                    "status": "authenticated",
                    # Mutual: released only to a proven client, so an
                    # impostor server (or mismatched secret files) is
                    # detected client-side before any data flows.
                    "server_mac": _mac(key, "server", client_nonce, nonce),
                },
            }
        self.hangup = True
        return {
            "ok": False,
            "error": "AuthenticationError",
            "message": "bad credentials (wrong or missing shared secret)",
        }


def perform_client_handshake(exchange, secret, peer):
    """Client side of the mutual handshake on a FRESH connection.

    ``exchange`` is a callable taking one encoded request line and
    returning the decoded response dict; ``peer`` labels error messages
    (``host:port``).  Shared by :class:`NetworkDB` and the gateway client
    (``serve/client.py``) so the downgrade/impostor refusals cannot drift
    between the two wire surfaces.  Raises :class:`AuthenticationError`;
    the caller closes its connection."""
    key = _derive_key(secret)
    client_nonce = _secrets.token_hex(16)
    hello = exchange(_dumps({"op": "auth_hello", "nonce": client_nonce}))
    result = hello.get("result") or {}
    nonce = result.get("nonce")
    if nonce is None:
        # This client was configured with a secret; silently proceeding
        # against a server that refuses to authenticate would hand every
        # read AND write to whoever answered on this address (DNS/IP
        # hijack, typoed port).  No downgrade.
        raise AuthenticationError(
            f"server {peer} does not require authentication, but this "
            "client is configured with a secret — refusing to proceed "
            "(remove the secret only if you trust the network path)"
        )
    reply = exchange(
        _dumps({"op": "auth", "mac": _mac(key, "client", client_nonce, nonce)})
    )
    if not reply.get("ok"):
        raise AuthenticationError(reply.get("message", "authentication failed"))
    server_mac = str((reply.get("result") or {}).get("server_mac", ""))
    if not hmac.compare_digest(server_mac, _mac(key, "server", client_nonce, nonce)):
        raise AuthenticationError(
            f"server {peer} failed to prove knowledge of the shared secret "
            "(impostor server, or mismatched secret files)"
        )


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        db = self.server.db
        self._auth = ServerHandshake(self.server.auth_key)
        while True:
            try:
                request = _read_line(self.rfile)
            except (json.JSONDecodeError, OSError) as exc:
                log.warning("bad request from %s: %s", self.client_address, exc)
                return
            if request is None:
                return
            self.wfile.write(_dumps(self._dispatch(db, request)))
            if self._auth.hangup:
                return

    def _dispatch(self, db, request):
        op = request.get("op")
        if op in ServerHandshake.AUTH_OPS:
            return self._auth.step(request)
        if op not in _DB_OPS and op not in _SERVER_OPS:
            return {"ok": False, "error": "DatabaseError", "message": f"bad op {op!r}"}
        if op == "ping":
            # Health checks stay open: ping reveals nothing and monitoring
            # should not need the experiment secret.
            return {"ok": True, "result": "pong"}
        if not self._auth.authenticated:
            return {
                "ok": False,
                "error": "AuthenticationError",
                "message": "authentication required (server started with a secret)",
            }
        if op == "seq":
            return {"ok": True, "result": self.server.seq_info()}
        if op == "snapshot":
            return {"ok": True, "result": self.server.snapshot_payload()}
        if op in ("replicate", "promote", "adopt_replica"):
            try:
                args = request.get("args") or []
                payload = args[0] if args else None
                handler = {
                    "replicate": self.server.handle_replicate,
                    "promote": self.server.handle_promote,
                    "adopt_replica": self.server.handle_adopt_replica,
                }[op]
                return {"ok": True, "result": handler(payload)}
            except Exception as exc:  # pragma: no cover - defensive
                log.exception("%s failed", op)
                return _encode_outcome(exc)
        if op == "batch":
            return self._batch_dispatch(db, request)
        if op in _MUTATING_OPS and self.server.refuses_mutations():
            # Epoch fencing, server side: a replica (including a demoted
            # stale primary) must never apply a client mutation — accepting
            # one would fork it from the authoritative primary's timeline
            # and the divergence would be silently erased by the next
            # resync.  Refused BEFORE any apply, so nothing was applied and
            # the router's retry can safely re-route to the real primary.
            return self.server.not_primary_reply()
        # Distributed tracing: a request may carry an optional `ctx` field
        # (the client's ambient TraceContext) — adopted as the parent of
        # this server's apply span.  Pre-upgrade clients simply omit it;
        # pre-upgrade servers ignored unknown top-level keys, so the field
        # is wire-compatible in both directions.
        t0, ctx = self.server.adopt_begin(request)
        try:
            method = getattr(db, op)
            args = request.get("args", [])
            kwargs = request.get("kwargs", {})
            if op in _MUTATING_OPS:
                result, seq = self.server.apply_replicated(op, args, kwargs, method)
                self.server.persist_snapshot()
                if _quorum_sync(op, args) and not self.server.await_quorum(seq):
                    return self.server.quorum_timeout_reply(op, seq)
                out = {"ok": True, "result": result}
            else:
                # A read replica stamps its applied replication sequence on
                # read replies so clients can tell a fresh answer from a
                # lagging one (the sharded router's staleness contract).
                # Stamped BEFORE the read executes: the stamp must be a
                # LOWER bound on the state the read observed — sampling
                # after could stamp a pre-apply read with a post-apply
                # sequence and launder a stale answer as fresh.  Plain
                # servers stamp nothing — zero wire change.
                seq = self.server.read_stamp()
                result = method(*args, **kwargs)
                out = {"ok": True, "result": result}
            if seq is not None:
                out["seq"] = seq
                # The epoch rides next to the seq so routers can fence a
                # stale primary's replies (shard.py's promotion protocol);
                # epoch 0 = replication never configured, nothing stamped.
                epoch = self.server.epoch
                if epoch:
                    out["epoch"] = epoch
            return out
        except Exception as exc:
            if not isinstance(exc, (DuplicateKeyError, KeyError)):
                log.exception("op %s failed", op)  # pragma: no cover - defensive
            return _encode_outcome(exc)
        finally:
            self.server.adopt_finish(op, t0, ctx)

    def _batch_dispatch(self, db, request):
        """ONE request carrying N sub-operations: applied as one atomic
        unit against the store (one lock hold on MemoryDB, one transaction
        on a SQLite-persisted server) and answered with ONE response line
        holding per-slot outcomes.  Next to ``pipeline`` (N request lines
        in one send) this drops the server's per-op dispatch/persist cycle
        and, in SQLite persist mode, q fsyncs down to one."""
        try:
            args = request.get("args") or [[]]
            ops = args[0] if args else []
            normalized = []
            for entry in ops:
                op = (
                    entry[0]
                    if isinstance(entry, (list, tuple)) and entry
                    else None
                )
                if op not in _BATCH_OPS:
                    return {
                        "ok": False,
                        "error": "DatabaseError",
                        "message": f"bad batch sub-op {op!r}",
                    }
                sub_args = list(entry[1]) if len(entry) > 1 and entry[1] else []
                sub_kwargs = dict(entry[2]) if len(entry) > 2 and entry[2] else {}
                normalized.append((op, sub_args, sub_kwargs))
        except (TypeError, ValueError, KeyError) as exc:
            # A malformed payload must get a structured refusal, never kill
            # the handler without a response line — the client would read
            # that as applied-or-not-unknowable when nothing was applied.
            return {
                "ok": False,
                "error": "DatabaseError",
                "message": f"malformed batch request: {exc}",
            }
        mutating = any(op in _MUTATING_OPS for op, _, _ in normalized)
        if mutating and self.server.refuses_mutations():
            # Same epoch fence as the single-op path: nothing applied.
            return self.server.not_primary_reply()
        t0, ctx = self.server.adopt_begin(request)
        try:
            # All-read batch (the producer's fetch_update_view pair): the
            # replica stamp is taken BEFORE the batch runs — a lower bound
            # on the observed state, same rationale as the single-op path.
            pre_stamp = None if mutating else self.server.read_stamp()
            results, seq = self.server.apply_batch_replicated(db, normalized)
            if mutating:
                self.server.persist_snapshot()
                if any(
                    _quorum_sync(op, sub_args)
                    for op, sub_args, _ in normalized
                ) and not self.server.await_quorum(seq):
                    return self.server.quorum_timeout_reply("batch", seq)
            else:
                seq = pre_stamp
            out = {"ok": True, "result": [_encode_outcome(r) for r in results]}
            if seq is not None:
                out["seq"] = seq
                epoch = self.server.epoch
                if epoch:
                    out["epoch"] = epoch
            return out
        except Exception as exc:
            # Whole-batch failure (e.g. a fault-injected mid-batch kill):
            # encode through the one shared path so markers like
            # maybe_applied survive the wire.
            log.exception("batch of %d ops failed", len(normalized))
            return _encode_outcome(exc)
        finally:
            # In a finally like the single-op path: a FAILED batch is the
            # one whose server-side span the post-mortem needs most.
            self.server.adopt_finish("batch", t0, ctx)


def _parse_addr(addr):
    """``"host:port"`` / ``(host, port)`` -> (host, int(port))."""
    if isinstance(addr, str):
        host, _, port = addr.rpartition(":")
        if not host or not port:
            raise DatabaseError(f"bad replica address {addr!r}; expected host:port")
        return host, int(port)
    host, port = addr
    return host, int(port)


class _ReplicaLink:
    """Asynchronous primary -> replica pusher: one background thread per
    replica streams the primary's ORDERED mutation log over the ordinary
    wire (``replicate`` requests carrying ``[(seq, op, args, kwargs), ...]``
    chunks); a replica that restarted empty, answered with a sequence gap,
    or fell behind the bounded log gets a full snapshot resync.  Pushes
    retry forever with backoff — a dead replica must never stall the
    primary (writes are acknowledged before replication: the replica tier
    is a read-scaling plane, not a quorum)."""

    PUSH_BATCH = 256

    #: Upper bound of the jittered pre-resync sleep: spreads the (gated,
    #: serialized) snapshot dumps of a replica restart storm so the
    #: primary's lock sees breathing room between them.
    RESYNC_JITTER_S = 0.05

    def __init__(self, server, addr, secret=None):
        self.server = server
        self.host, self.port = _parse_addr(addr)
        self.client = NetworkDB(
            host=self.host, port=self.port, timeout=10.0, secret=secret
        )
        self.acked_seq = None  # unknown until the first probe
        #: Set when the replica's last reply demanded a resync (an epoch
        #: change or a fork repair): the next cycle must ship a snapshot
        #: even if the bounded log happens to cover the replica's position
        #: — entry replay across a fork corrupts silently.
        self.force_resync = False
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"netdb-repl-{self.host}:{self.port}",
            daemon=True,
        )

    def start(self):
        self._thread.start()

    def notify(self):
        self._wake.set()

    def stop(self, flush=True):
        """Stop pushing; ``flush`` attempts one final best-effort push so a
        clean primary shutdown leaves reachable replicas fully caught up."""
        if flush and not self._stopped.is_set():
            try:
                self._push_pending()
            except Exception:  # replica down at shutdown: nothing owed
                log.debug("final replica flush failed", exc_info=True)
        self._stopped.set()
        self._wake.set()
        self.client.close()

    #: Consecutive push failures before the pusher escalates to WARNING:
    #: a replica riding out a restart fails a handful of times (debug
    #: noise); a PERMANENT failure — wrong secret, wrong address — would
    #: otherwise leave the replica tier silently empty forever.
    WARN_AFTER_FAILURES = 10

    def _run(self):
        backoff = 0.05
        failures = 0
        while not self._stopped.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stopped.is_set():
                return
            try:
                self._push_pending()
                backoff = 0.05
                failures = 0
            except Exception as exc:
                # Usually a down/partitioned replica (transient); jittered
                # backoff so a fleet of pushers doesn't hammer a
                # restarting replica in lockstep.  A persistent streak is
                # escalated: auth/config mistakes are NOT transient and
                # must reach the operator, not the debug log.
                failures += 1
                TELEMETRY.count("netdb.replication.push_failures")
                if failures % self.WARN_AFTER_FAILURES == 0:
                    log.warning(
                        "replica %s:%s has refused %d consecutive pushes "
                        "(latest: %s: %s) — replication to it is STALLED",
                        self.host, self.port, failures,
                        type(exc).__name__, exc,
                    )
                else:
                    log.debug(
                        "replica %s:%s push failed", self.host, self.port,
                        exc_info=True,
                    )
                self.acked_seq = None  # re-probe after the outage
                self._stopped.wait(backoff * (0.5 + random.random()))
                backoff = min(backoff * 2, 2.0)

    def _push_pending(self):
        """Drain everything the replica has not acknowledged yet."""
        while not self._stopped.is_set():
            if self.acked_seq is None:
                info = self.client._call("seq") or {}
                peer_epoch = int(info.get("epoch", 0) or 0)
                if peer_epoch > self.server.epoch:
                    # The peer lives in a NEWER epoch: this server is a
                    # stale reborn primary — demote instead of pushing a
                    # forked history (split-brain guard, docs/multi_node.md).
                    self.server.demote(peer_epoch)
                    return
                self.acked_seq = int(info.get("seq", 0))
                self.server.ack_notify()
            with self.server._repl_lock:
                entries = [
                    list(e) for e in self.server._repl_log
                    if e[0] > self.acked_seq
                ]
                epoch = self.server.epoch
                behind = self.server.seq > self.acked_seq
                covered = bool(entries) and entries[0][0] == self.acked_seq + 1
            if (behind and not covered) or self.force_resync:
                # The gap fell off the bounded log (or the replica
                # restarted empty / demanded an epoch resync): full resync.
                # Resyncs are BOUNDED to one replica at a time per primary
                # (jittered): each snapshot is an O(DB-size) dump under the
                # replication lock, and a restart storm of R replicas
                # re-probing at once would otherwise stampede the primary
                # with R back-to-back dumps, starving client mutations of
                # ``_repl_lock`` for R full copies.
                if not self.server._resync_gate.acquire(timeout=2.0):
                    continue  # re-check _stopped, then wait our turn again
                try:
                    if self._stopped.is_set():
                        return
                    self._stopped.wait(random.random() * self.RESYNC_JITTER_S)
                    with self.server._repl_lock:
                        # Re-read from a consistent point — the log may
                        # have grown while we waited for the gate.
                        snapshot = self.server._snapshot_payload_locked()
                    result = self.client._call(
                        "replicate", {"snapshot": snapshot, "epoch": epoch}
                    )
                    TELEMETRY.count("netdb.replication.resyncs")
                finally:
                    self.server._resync_gate.release()
                result = result or {}
                if result.get("fenced"):
                    # Promoted between our probe and this push: same
                    # demotion as a fenced entry push.
                    self.server.demote(int(result.get("epoch", 0) or 0))
                    return
                self.force_resync = False
                self.acked_seq = int(result.get("seq", 0))
                self.server.ack_notify()
                continue
            if not entries:
                return
            chunk = entries[: self.PUSH_BATCH]
            result = self.client._call(
                "replicate", {"entries": chunk, "epoch": epoch}
            ) or {}
            TELEMETRY.count("netdb.replication.pushes")
            if result.get("fenced"):
                # The replica refused this epoch: a newer primary owns the
                # stream now.  Demote; never push a stale fork.
                self.server.demote(int(result.get("epoch", 0) or 0))
                return
            self.acked_seq = int(result.get("seq", 0))
            self.server.ack_notify()
            if result.get("resync"):
                # The replica saw a sequence gap (or an epoch change /
                # fork) mid-chunk; ship a snapshot next cycle — the log
                # may well still "cover" the reported position, but the
                # replica has declared entry replay unsafe.
                self.force_resync = True
                continue


class DBServer(socketserver.ThreadingTCPServer):
    """Serve a document DB over TCP; one request = one atomic DB operation
    (MemoryDB per-op lock, or SQLiteDB transactions in x.sqlite persist
    mode).

    **Replication** (the sharded control plane's read tier,
    docs/multi_node.md): a primary started with ``replicate_to=[addr,...]``
    assigns every applied mutation a monotonically increasing sequence
    number under one lock (log order IS apply order), stamps that ``seq``
    on the mutating reply, and streams the log to each replica from a
    background :class:`_ReplicaLink`.  A replica (any server that receives
    ``replicate`` ops, or one started with ``replica=True``) replays the
    stream in order and stamps its APPLIED seq on read replies — which is
    what lets :class:`~orion_tpu.storage.shard.ShardedNetworkDB` detect a
    lagging replica and fail a read over to the primary.  Replication is
    asynchronous by default: writes are acknowledged before they reach any
    replica.  **Quorum mode** (``quorum=N``, `storage.quorum`) tightens the
    contract for the registration collections (:data:`SYNC_COLLECTIONS`):
    a mutating reply waits until at least N replica links have acknowledged
    the write's sequence — the log is ordered, so a replica acking seq S
    holds every write ≤ S, which is exactly why a max-seq election winner
    carries every quorum-acked write and a kill -9 loses nothing sync by
    construction.  An ack that never comes within ``quorum_timeout`` fails
    the reply with ``maybe_applied`` (the write DID apply locally; the
    retry layer's MODE_UNAPPLIED ops give up, MODE_ALWAYS ops converge
    through their duplicate-key/absolute-id discipline)."""

    allow_reuse_address = True
    daemon_threads = True

    #: Seconds between flushes of the server's OWN adopted-ctx spans into
    #: its spans collection (under the reserved ``__server__`` experiment
    #: id) — what `orion-tpu trace --distributed` joins back by trace_id.
    SPAN_FLUSH_INTERVAL = 1.0
    #: Retention cap for the __server__ span channel (same unbounded-growth
    #: guard as DocumentStorage.SPANS_CAP; pruned with hysteresis to 90%).
    SERVER_SPANS_CAP = 20000

    def __init__(
        self,
        host="127.0.0.1",
        port=0,
        persist=None,
        persist_interval=1.0,
        secret=None,
        replicate_to=None,
        replica=False,
        quorum=0,
        quorum_timeout=2.0,
    ):
        self.persist = persist
        self.persist_interval = persist_interval
        #: Per-write replication-ack floor for SYNC_COLLECTIONS mutations
        #: (0 = classic async replication).  Configured on every server of
        #: a shard — replicas carry it dormant so a promoted one enforces
        #: the same contract its predecessor did.
        self.quorum = int(quorum or 0)
        self.quorum_timeout = float(quorum_timeout)
        # Server-side span recording rides a PRIVATE registry, not the
        # process-global one: an in-process loopback server sharing the
        # global ring would have its spans drained (exactly-once) by
        # whichever worker flush ran next, splitting them unpredictably
        # between the experiment channel and the __server__ channel.
        # Mutations are gated on the GLOBAL TELEMETRY.enabled switch.
        self._span_tel = Telemetry(enabled=True, span_capacity=2048)
        self._span_flush_lock = threading.Lock()
        self._last_span_flush = 0.0
        self._span_track = f"netdb:{socket.gethostname()}:{os.getpid()}"
        # Shared-secret authentication (reference parity: the networked
        # backend takes username/password credentials,
        # `mongodb.py:86,289`).  None = open server for localhost dev.
        self.secret = secret
        self.auth_key = _derive_key(secret) if secret is not None else None
        self._persist_lock = threading.Lock()
        self._dirty = threading.Event()
        self._stop_flusher = threading.Event()
        self._flusher = None
        # A .sqlite/.db persist path backs the server with the SQLite store:
        # durable per-mutation by design (WAL), so no snapshot machinery —
        # handler threads each get their own connection (thread-local).
        # Header-sniffed so a legacy pickle snapshot named *.db keeps
        # loading as a snapshot.
        from orion_tpu.storage.sqlitedb import SQLiteDB, sqlite_path_selected

        self._snapshotting = bool(persist) and not sqlite_path_selected(persist)
        if persist and not self._snapshotting:
            self.db = SQLiteDB(persist)
        else:
            self.db = MemoryDB()
            if persist and os.path.exists(persist):
                with open(persist, "rb") as handle:
                    self.db = pickle.load(handle)
        # Live client sockets, tracked so shutdown can force-drop them: an
        # in-process "restart" must look like a killed process to clients
        # and replication pushers — otherwise a handler thread keeps
        # serving the DISCARDED store over the old connection (a zombie the
        # soak harness's shard-restart scenarios would silently talk to).
        self._conn_lock = threading.Lock()
        self._open_conns = set()
        # --- replication state (primary AND replica roles) -------------------
        # RLock: handle_replicate applies ops through the same locked window
        # apply_replicated uses, and a snapshot resync applies indexes via
        # the same db surface.
        self._repl_lock = threading.RLock()
        #: Pusher threads notify here whenever a replica's acked position
        #: advances; the quorum gate waits on it.  Sharing _repl_lock means
        #: the ack predicate is always read consistently with the link set.
        self._ack_cond = threading.Condition(self._repl_lock)
        self._is_replica = bool(replica)
        self._repl_log = deque(maxlen=REPL_LOG_CAP)
        self._repl_links = []
        #: Serializes full snapshot resyncs across this primary's pusher
        #: threads (see _ReplicaLink._push_pending).
        self._resync_gate = threading.BoundedSemaphore(1)
        #: Set when this server's history may have FORKED from the
        #: authoritative stream (a demoted stale primary, or a replica that
        #: observed an epoch change): seq probes report 0 until a full
        #: snapshot overwrites the fork — entry replay on top of diverged
        #: state would corrupt silently.
        self._resync_pending = False
        #: True for any server that ever served as a primary (constructed
        #: replicating, or promoted): its local history may contain writes
        #: no other node has, so an epoch change can never be absorbed by
        #: entry replay — only by a snapshot.
        self._was_primary = bool(replicate_to)
        # The applied/assigned sequence AND the replication epoch survive
        # restarts THROUGH the store itself (a meta doc): a restarted
        # primary must keep numbering where it left off or replicas would
        # silently discard its new mutations as already-seen; a restarted
        # STALE primary must come back knowing which epoch it last served
        # so a single contact with a newer-epoch peer demotes it.
        self.seq, self.epoch = self._load_replmeta()
        if replicate_to and self.epoch == 0:
            # A replicating primary always serves a concrete epoch (>= 1):
            # epoch 0 means "replication never configured" and is never
            # stamped on the wire.
            with self._repl_lock:
                self.epoch = 1
                self._persist_seq_locked()
        super().__init__((host, port), _Handler)
        for addr in replicate_to or ():
            link = _ReplicaLink(self, addr, secret=secret)
            self._repl_links.append(link)
            link.start()
        if self._snapshotting:
            self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
            self._flusher.start()

    @property
    def address(self):
        return self.server_address[:2]

    # --- connection tracking -------------------------------------------------
    def process_request(self, request, client_address):
        with self._conn_lock:
            self._open_conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._open_conns.discard(request)
        super().shutdown_request(request)

    def close_connections(self):
        """Force-drop every live client connection (see ``_open_conns``)."""
        with self._conn_lock:
            doomed = list(self._open_conns)
        for sock in doomed:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # --- replication ---------------------------------------------------------
    @property
    def _replicating(self):
        """True when this server participates in the replication protocol:
        it pushes to live links, OR it carries a concrete epoch (a
        promoted primary whose peers are all currently dead must still
        number and epoch-stamp its mutations — its log is what a reborn
        peer replays, and the stamp is the routers' fencing signal)."""
        return bool(self._repl_links) or self.epoch > 0

    def apply_replicated(self, op, args, kwargs, method):
        """Apply one mutating op; when this server replicates, the apply and
        its log append happen under ONE lock so the log order IS the apply
        order (replicas replay the log and must converge on identical
        state).  Only a SUCCESSFUL apply is logged — a refused op
        (DuplicateKeyError) changed nothing and replaying it would at best
        waste a wire trip.  Returns ``(result, seq_or_None)``."""
        if not self._replicating:
            return method(*args, **kwargs), None
        with self._repl_lock:
            result = method(*args, **kwargs)
            seq = self._log_entry_locked(op, list(args), dict(kwargs or {}))
        self._notify_links()
        return result, seq

    @staticmethod
    def _run_batch(db, normalized):
        """Apply one normalized batch against ``db`` with per-slot
        outcomes — shared by the primary's logged path and the replica's
        stream replay (which manages seq itself)."""
        apply_batch = getattr(db, "apply_batch", None)
        if apply_batch is not None:
            return apply_batch(normalized)
        results = []  # pragma: no cover - every in-tree store has apply_batch
        for op, sub_args, sub_kwargs in normalized:
            try:
                results.append(getattr(db, op)(*sub_args, **sub_kwargs))
            except Exception as exc:
                results.append(exc)
        return results

    def apply_batch_replicated(self, db, normalized):
        """The batch-op sibling of :meth:`apply_replicated`: the whole batch
        is ONE log entry (per-slot outcomes are deterministic replays of the
        same op stream, so a slot the primary refused is refused identically
        on the replica).  All-read batches are never logged."""
        mutating = any(op in _MUTATING_OPS for op, _, _ in normalized)
        if not self._replicating or not mutating:
            return self._run_batch(db, normalized), None
        with self._repl_lock:
            results = self._run_batch(db, normalized)
            seq = self._log_entry_locked(
                "batch",
                [[[op, list(a), dict(k)] for op, a, k in normalized]],
                {},
            )
        self._notify_links()
        return results, seq

    def handle_replicate(self, payload):
        """Apply a pusher's ``replicate`` request: an ordered entry chunk
        (seqs at or below the applied position are dropped — resends
        converge), or a full ``snapshot``.  A mid-chunk sequence GAP stops
        the replay and reports ``resync`` so the pusher falls back to a
        snapshot instead of applying out of order.

        **Epoch discipline** (the promotion protocol's replication half):
        a push from a LOWER epoch is fenced — refused outright with the
        current epoch in the reply, so a stale reborn primary demotes
        itself instead of overwriting the promoted timeline.  A push from
        a HIGHER epoch demotes this server if it ever was a primary (its
        unreplicated tail is a condemned fork) and, for any server with
        state, demands a full snapshot instead of entry replay — entries
        replayed across an epoch boundary could land on top of a fork and
        corrupt silently.  Epoch-less pushes (pre-upgrade primaries) are
        treated as same-epoch."""
        payload = payload or {}
        has_epoch = "epoch" in payload
        push_epoch = int(payload.get("epoch", 0) or 0)
        doomed_links = []
        demoted = False
        own_epoch = 0
        with self._repl_lock:
            if has_epoch and self.epoch and push_epoch < self.epoch:
                return {
                    "seq": self.seq,
                    "resync": False,
                    "fenced": True,
                    "epoch": self.epoch,
                }
            epoch_advanced = has_epoch and push_epoch > self.epoch
            if epoch_advanced and (self._was_primary or self._repl_links):
                # A primary (current or former) hearing a newer epoch:
                # demote NOW — every local write since the election is a
                # fork no other node acknowledges.
                doomed_links, self._repl_links = self._repl_links, []
                self._resync_pending = True
                demoted = True
                own_epoch = self.epoch
            self._is_replica = True
            snapshot = payload.get("snapshot")
            if snapshot is not None:
                self._apply_snapshot_locked(snapshot)
                self._resync_pending = False
                applied, resync = self.seq, False
            elif self._resync_pending or (epoch_advanced and self.seq > 0):
                # A fork is pending repair (or this replica's tail may
                # extend past the new primary's fork point): only a
                # snapshot is safe.  Report position 0 so the pusher's
                # gap logic takes the resync path.
                self._resync_pending = True
                applied, resync = 0, True
            else:
                if epoch_advanced:
                    # Fresh follower (no state): adopt the stream's epoch.
                    self.epoch = push_epoch  # lint: disable=LCK002 -- under _repl_lock
                applied, resync = self.seq, False
                for entry in payload.get("entries") or []:
                    seq = int(entry[0])
                    op = entry[1]
                    args = entry[2] or []
                    kwargs = entry[3] if len(entry) > 3 and entry[3] else {}
                    if seq <= applied:
                        continue  # resend of an already-applied entry
                    if seq != applied + 1:
                        resync = True
                        break
                    try:
                        if op == "batch":
                            normalized = [
                                (e[0], list(e[1]), dict(e[2])) for e in args[0]
                            ]
                            # Direct apply: the stream replay manages seq
                            # itself — the logged path would double-number.
                            self._run_batch(self._meta_db, normalized)
                        else:
                            getattr(self._meta_db, op)(*args, **kwargs)
                    except (DuplicateKeyError, KeyError):
                        # The primary logged this op as a SUCCESS; a
                        # semantic refusal here means the replica diverged
                        # (e.g. it took direct writes).  Keep going — the
                        # stream stays ordered — but say so loudly.
                        log.warning(
                            "replicated op %r refused at seq %d — replica "
                            "state diverged from its primary", op, seq,
                        )
                    applied = seq
                self.seq = applied
                self._persist_seq_locked()
        for link in doomed_links:
            link.stop(flush=False)
        if demoted:
            self._note_demotion(push_epoch, own_epoch)
        self.persist_snapshot()
        return {"seq": applied, "resync": resync, "epoch": self.epoch}

    def handle_promote(self, payload):
        """The ``promote`` wire op: flip replica -> primary at a NEW epoch.

        Sent by a router's election (``storage/shard.py``) to the
        most-caught-up replica of a shard whose primary died.  Idempotent
        and concurrent-router safe: a promotion at or below the current
        epoch changes nothing and reports the standing state, so every
        router converges on the same winner; a mid-resync server refuses
        (its state is a fork in repair, not electable)."""
        payload = payload or {}
        new_epoch = int(payload.get("epoch", 0) or 0)
        peers = payload.get("replicate_to") or []
        with self._repl_lock:
            if self._resync_pending:
                return {
                    "promoted": False, "primary": False,
                    "epoch": self.epoch, "seq": 0,
                }
            if new_epoch <= self.epoch:
                return {
                    "promoted": False,
                    "primary": not self._is_replica,
                    "epoch": self.epoch,
                    "seq": self.seq,
                }
            self.epoch = new_epoch  # lint: disable=LCK002 -- under _repl_lock
            self._is_replica = False
            self._was_primary = True
            self._persist_seq_locked()
            seq = self.seq
            known = {(link.host, link.port) for link in self._repl_links}
        self_addr = tuple(self.address)
        for addr in peers:
            parsed = _parse_addr(addr)
            if parsed in known or parsed == self_addr:
                continue
            known.add(parsed)
            link = _ReplicaLink(self, parsed, secret=self.secret)
            with self._repl_lock:
                self._repl_links.append(link)
            link.start()
        TELEMETRY.count("netdb.promotions")
        if FLIGHT.enabled:
            FLIGHT.record(
                "promote",
                args={"epoch": new_epoch, "seq": seq, "peers": len(peers)},
            )
        log.warning(
            "PROMOTED to primary at epoch %d (seq %d), replicating to %d "
            "peer(s)", new_epoch, seq, len(peers),
        )
        self.persist_snapshot()
        return {"promoted": True, "primary": True, "epoch": new_epoch, "seq": seq}

    def handle_adopt_replica(self, payload):
        """The ``adopt_replica`` wire op: start pushing this primary's
        stream to a freshly provisioned replica (auto-reprovisioning,
        ``storage/shard.py``).  Idempotent: an address already linked (or
        this server's own) reports ``existing`` instead of double-pushing.
        A replica refuses — adoption reshapes the replication fan-out and
        only the shard's current primary owns that."""
        payload = payload or {}
        addr = payload.get("address")
        if not addr:
            raise DatabaseError("adopt_replica needs an 'address'")
        parsed = _parse_addr(addr)
        with self._repl_lock:
            if self._is_replica:
                return {
                    "adopted": False,
                    "primary": False,
                    "epoch": self.epoch,
                }
            known = {(link.host, link.port) for link in self._repl_links}
            if parsed in known or parsed == tuple(self.address):
                return {"adopted": True, "existing": True, "epoch": self.epoch}
            if self.epoch == 0:
                # Adopting a replica makes this server a replicating
                # primary; it must stamp a concrete epoch from here on
                # (same floor a replicate_to construction applies).
                self.epoch = 1  # lint: disable=LCK002 -- under _repl_lock
                self._persist_seq_locked()
            self._was_primary = True
            link = _ReplicaLink(self, parsed, secret=self.secret)
            self._repl_links.append(link)
            epoch = self.epoch
        # Outside the lock: the empty (or stale) replica snapshot-resyncs
        # through the pusher's ordinary gap logic — bounded by _resync_gate
        # like any replica restart.
        link.start()
        link.notify()
        TELEMETRY.count("netdb.adoptions")
        log.warning(
            "ADOPTED replica %s:%s at epoch %d (reprovision)", *parsed, epoch
        )
        return {"adopted": True, "existing": False, "epoch": epoch}

    # --- quorum gate (storage.quorum) ----------------------------------------
    def ack_notify(self):
        """A pusher advanced a replica's acked position: wake quorum waits."""
        with self._ack_cond:
            self._ack_cond.notify_all()

    def await_quorum(self, seq, timeout=None):
        """Block until at least ``quorum`` replica links acknowledge
        ``seq`` (or every link has, when fewer links than the floor
        exist — a shard mid-reprovision must not refuse all writes for
        asking more acks than replicas).  True on success, False on
        timeout.  Vacuously true with quorum off, no seq, or no links.
        Books the wait as the ``storage.quorum.wait`` histogram."""
        if self.quorum <= 0 or seq is None:
            return True
        timeout = self.quorum_timeout if timeout is None else timeout
        t0 = time.perf_counter()
        deadline = time.monotonic() + timeout
        with self._ack_cond:
            while True:
                links = self._repl_links
                floor = min(self.quorum, len(links))
                acked = sum(
                    1 for link in links
                    if link.acked_seq is not None and link.acked_seq >= seq
                )
                if acked >= floor:
                    TELEMETRY.observe(
                        "storage.quorum.wait", time.perf_counter() - t0
                    )
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    TELEMETRY.observe(
                        "storage.quorum.wait", time.perf_counter() - t0
                    )
                    TELEMETRY.count("storage.quorum.timeouts")
                    return False
                self._ack_cond.wait(remaining)

    def quorum_timeout_reply(self, op, seq):
        """The reply for a sync write whose replica acks never arrived:
        the op DID apply locally, so the wire carries ``maybe_applied`` —
        transient for the retry classifier (MODE_ALWAYS ops converge via
        their duplicate-key discipline; MODE_UNAPPLIED ops give up instead
        of double-applying)."""
        with self._repl_lock:
            epoch = self.epoch
        return {
            "ok": False,
            "error": "DatabaseError",
            "message": (
                f"quorum not reached for {op!r} at seq {seq}: fewer than "
                f"{self.quorum} replica(s) acknowledged within "
                f"{self.quorum_timeout:.1f}s — the write applied locally "
                "but its replication guarantee is not met"
            ),
            "maybe_applied": True,
            "quorum_timeout": True,
        }

    def demote(self, peer_epoch):
        """Runtime primary -> replica demotion: a peer proved a NEWER epoch
        exists, so every local write since that election is a condemned
        fork.  Mutations refuse from here on (``refuses_mutations``), the
        pushers stop, and every seq probe reports 0 until the new
        primary's snapshot overwrites the fork (``_resync_pending``)."""
        with self._repl_lock:
            if self._is_replica and self._resync_pending:
                return  # already demoted and awaiting repair
            doomed, self._repl_links = self._repl_links, []
            self._is_replica = True
            self._resync_pending = True
            own_epoch = self.epoch
        for link in doomed:
            link.stop(flush=False)
        self._note_demotion(peer_epoch, own_epoch)

    def _note_demotion(self, peer_epoch, own_epoch):
        TELEMETRY.count("netdb.demotions")
        if FLIGHT.enabled:
            FLIGHT.record(
                "demote", args={"peer_epoch": peer_epoch, "epoch": own_epoch}
            )
        log.warning(
            "DEMOTED: a peer serves epoch %d, newer than ours (%d) — now a "
            "read replica awaiting snapshot resync",
            peer_epoch, own_epoch,
        )

    def refuses_mutations(self):
        """Server half of the epoch fence: replicas — including a stale
        primary demoted by a newer epoch — never apply client mutations."""
        return self._is_replica

    def not_primary_reply(self):
        with self._repl_lock:
            epoch = self.epoch
        return {
            "ok": False,
            "error": "DatabaseError",
            "message": (
                f"not primary (epoch {epoch}): this server is a read "
                "replica — mutations must go to the shard's current primary"
            ),
            "not_primary": True,
            "epoch": epoch,
        }

    def snapshot_payload(self):
        """The ``snapshot`` wire op behind ``orion-tpu db backup``: the same
        consistent full-state dump replica resyncs ship (taken under the
        replication lock — no mutation interleaves), seq/epoch-stamped so
        the backup manifest records exactly which position it captured."""
        with self._repl_lock:
            return self._snapshot_payload_locked()

    def seq_info(self):
        """The ``seq`` wire op: applied/assigned position, role, epoch.
        A server awaiting a fork repair reports position 0 — it is neither
        electable nor a valid resume point for entry replay."""
        with self._repl_lock:
            return {
                "seq": 0 if self._resync_pending else self.seq,
                "replica": self._is_replica,
                "epoch": self.epoch,
                "resyncing": self._resync_pending,
                # The ack floor rides the probe so `db status` can render
                # each shard's write contract; pre-upgrade clients ignore
                # unknown keys — wire-compatible both ways.
                "quorum": self.quorum,
            }

    def read_stamp(self):
        """Applied seq to stamp on read replies — replicas only (plain and
        primary servers stamp reads with nothing; their answers are
        authoritative by construction)."""
        if not self._is_replica:
            return None
        with self._repl_lock:
            return 0 if self._resync_pending else self.seq

    def replication_status(self):
        """Operator view: position, role, epoch, and per-replica acked lag."""
        with self._repl_lock:
            status = {
                "seq": self.seq,
                "replica": self._is_replica,
                "epoch": self.epoch,
            }
        status["links"] = [
            {
                "address": f"{link.host}:{link.port}",
                "acked_seq": link.acked_seq,
            }
            for link in self._repl_links
        ]
        return status

    @property
    def _meta_db(self):
        """The UNWRAPPED store for replication bookkeeping (the seq doc,
        resync snapshots, stream replay): a chaos harness's FaultyDB wraps
        ``self.db`` to fault the COORDINATION protocol at the op boundary;
        replication internals fault through the protocol ops they serve,
        never independently — a fault injected into the seq upkeep would
        fail a client op AFTER it durably applied without the
        ``maybe_applied`` marking real wire losses carry."""
        return getattr(self.db, "inner", self.db)

    def _log_entry_locked(self, op, args, kwargs):
        self.seq += 1  # lint: disable=LCK002 -- caller holds _repl_lock (_locked contract)
        self._repl_log.append((self.seq, op, args, kwargs))
        self._persist_seq_locked()
        return self.seq

    def _persist_seq_locked(self):
        # The meta doc lives in the store so the sequence AND epoch ride
        # the same durability the data has (SQLite persist commits it;
        # snapshot mode pickles it with everything else).
        db = self._meta_db
        meta = {"seq": self.seq, "epoch": self.epoch}
        if not db.write("_replmeta", meta, query={"_id": "seq"}):
            db.write("_replmeta", dict(meta, _id="seq"))

    def _load_replmeta(self):
        """``(seq, epoch)`` from the persisted meta doc (0, 0 fresh)."""
        try:
            docs = self._meta_db.read("_replmeta", {"_id": "seq"})
        except Exception:  # pragma: no cover - a fresh store never raises
            return 0, 0
        if not docs:
            return 0, 0
        return int(docs[0].get("seq", 0)), int(docs[0].get("epoch", 0))

    def _snapshot_payload_locked(self):
        """Full-state resync payload from a consistent point (the caller
        holds the replication lock, so no mutation interleaves with the
        dump): every collection's raw documents plus the index specs."""
        db = self._meta_db
        collections = {}
        for name in db.collection_names():
            if name == "_replmeta":
                continue
            collections[name] = db.read(name, {})
        return {
            "seq": self.seq,
            "epoch": self.epoch,
            "collections": collections,
            "indexes": [list(spec) for spec in db.index_specs()],
        }

    def _apply_snapshot_locked(self, snapshot):
        db = self._meta_db
        for name in db.collection_names():
            db.remove(name, {})
        for col, keys, unique in snapshot.get("indexes") or []:
            db.ensure_index(col, keys, unique=unique)
        for name, docs in (snapshot.get("collections") or {}).items():
            if docs:
                db.write(name, docs)
        self.seq = int(snapshot.get("seq", 0))  # lint: disable=LCK002 -- caller holds _repl_lock (_locked contract)
        self.epoch = int(snapshot.get("epoch", self.epoch))  # lint: disable=LCK002 -- caller holds _repl_lock (_locked contract)
        self._persist_seq_locked()

    def _notify_links(self):
        for link in self._repl_links:
            link.notify()

    # --- distributed-trace adoption ------------------------------------------
    def adopt_begin(self, request):
        """``(t0, ctx)`` when this request carries a sampled trace context
        and telemetry is on — the handler's apply span window opens here;
        ``(None, None)`` otherwise (zero-cost beyond one dict get)."""
        if not TELEMETRY.enabled:
            return None, None
        wire = request.get("ctx")
        if wire is None:
            return None, None
        ctx = TraceContext.from_wire(wire)
        if ctx is None or not ctx.sampled:
            return None, None
        return time.perf_counter(), ctx

    def adopt_finish(self, op, t0, ctx):
        """Record the server-side ``netdb.apply`` span parented at the
        client's injected context, on this server's own trace track."""
        if t0 is None:
            return
        self._span_tel.record_span(
            "netdb.apply",
            start=t0,
            args={"op": op},
            parent_ctx=ctx,
            track=self._span_track,
        )
        self.flush_server_spans()

    def flush_server_spans(self, force=False):
        """Drain the private span ring into this server's own ``spans``
        collection under :data:`~orion_tpu.tracing.SERVER_EXPERIMENT`
        (rate-limited; the server has no experiment identity, so the merge
        joins these back by trace_id).  Never raises — observability must
        not break the wire."""
        now = time.monotonic()
        with self._span_flush_lock:
            TSAN.write("DBServer._span_flush", self)
            if not force and now - self._last_span_flush < self.SPAN_FLUSH_INTERVAL:
                return
            self._last_span_flush = now
        spans = self._span_tel.drain_spans()
        if not spans:
            return
        try:
            self.db.write(
                "spans",
                [
                    {"experiment": SERVER_EXPERIMENT, "worker": self._span_track, **s}
                    for s in spans
                ],
            )
            # Bounded retention (runs at most once per flush gate): prune
            # the oldest down to 90% of the cap, same hysteresis rationale
            # as DocumentStorage._prune_spans.
            query = {"experiment": SERVER_EXPERIMENT}
            if self.db.count("spans", query) > self.SERVER_SPANS_CAP:
                docs = self.db.read("spans", query)
                keep = max(1, int(self.SERVER_SPANS_CAP * 0.9))
                if len(docs) > keep:
                    docs.sort(key=lambda d: d.get("ts") or 0.0)
                    cutoff = docs[len(docs) - keep].get("ts") or 0.0
                    self.db.remove(
                        "spans",
                        {"experiment": SERVER_EXPERIMENT, "ts": {"$lt": cutoff}},
                    )
        except Exception:  # pragma: no cover - observability never breaks serving
            log.debug("could not flush server spans", exc_info=True)

    def persist_snapshot(self):
        """Mark the DB dirty; the flusher thread writes at most one snapshot
        per ``persist_interval`` — a per-mutation dump would hold the DB lock
        for an O(DB-size) pickle on every heartbeat at multi-worker scale."""
        self._dirty.set()

    def _flush_loop(self):
        while not self._stop_flusher.wait(self.persist_interval):
            self._flush_if_dirty()

    def _flush_if_dirty(self):
        if not (self._snapshotting and self._dirty.is_set()):
            return
        self._dirty.clear()
        t0 = time.perf_counter() if TELEMETRY.enabled else None
        # Snapshot the UNWRAPPED store: a chaos harness's FaultyDB wrapper
        # must never be pickled into the restart image (and faults never
        # fire on the flusher's internal dump).
        db = self._meta_db
        with self._persist_lock:
            # Hold the DB lock while pickling: handler threads mutate the
            # collections concurrently and pickle iterating a changing dict
            # raises mid-dump.  The static resolver cannot see this edge
            # (the lock lives on the attribute-held db object), so the
            # runtime sanitizer's cross-check anchors its LCK003 here:
            # the ordering is one-directional by construction — no MemoryDB
            # op calls back into the server, so persist_lock is always the
            # outer lock.  Pinned by tests/fixtures/lint/tsan_edge_cases.py.
            # lint: disable=LCK003 -- one-directional flusher edge; persist_lock always outer
            with db._lock:
                atomic_pickle_dump(self.persist, db)
        if t0 is not None:
            # The persist span rides the server track (no parent: the
            # flusher batches many requests' dirt into one dump).  Recorded
            # OUTSIDE the persist lock — span bookkeeping must never mint a
            # persist_lock -> registry-lock ordering edge.
            self._span_tel.record_span(
                "netdb.persist", start=t0, track=self._span_track
            )

    def serve_forever(self, *args, **kwargs):
        # Direct callers (the blocking `serve()` entry) mark the flag too.
        self._serving = True
        super().serve_forever(*args, **kwargs)

    def shutdown(self):
        self._stop_flusher.set()
        # BaseServer.shutdown() waits on a flag only serve_forever sets at
        # exit — calling it on a server that never served deadlocks
        # forever.  A constructed-but-never-served server still owns
        # sockets/links worth closing below.
        if getattr(self, "_serving", False):
            super().shutdown()
        self.close_connections()
        # Replica links drain after the accept loop stops (one best-effort
        # final push), so a clean primary shutdown leaves reachable
        # replicas caught up.
        for link in self._repl_links:
            link.stop(flush=True)
        # Span flush BEFORE the final snapshot so adopted spans recorded
        # since the last gate land in the persisted image too.
        if TELEMETRY.enabled:
            self.flush_server_spans(force=True)
        self._flush_if_dirty()  # final durable snapshot

    def serve_background(self):
        """Start serving on a daemon thread; returns (host, port).  The
        serving flag is set BEFORE the thread starts: a shutdown() racing
        the thread's entry into serve_forever must still run the real
        BaseServer.shutdown handshake, or the accept loop would start
        against a server its owner already believes stopped."""
        self._serving = True
        thread = threading.Thread(target=self.serve_forever, daemon=True)
        thread.start()
        return self.address


def serve(host="127.0.0.1", port=8765, persist=None, secret=None,
          replicate_to=None, replica=False, quorum=0):  # pragma: no cover - CLI
    """Blocking server entry point (`orion-tpu db serve`)."""
    server = DBServer(
        host=host, port=port, persist=persist, secret=secret,
        replicate_to=replicate_to, replica=replica, quorum=quorum,
    )
    log.info("serving orion-tpu DB on %s:%s", *server.address)
    auth = "shared-secret auth" if secret else "NO auth (open server)"
    role = ""
    if replicate_to:
        role = f", replicating to {len(list(replicate_to))} replica(s)"
    elif replica:
        role = ", read replica"
    if quorum:
        role += f", quorum={int(quorum)}"
    print(
        f"orion-tpu db server listening on "
        f"{server.address[0]}:{server.address[1]} ({auth}{role})"
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()


def _translate(response, raise_errors=True):
    """Wire response -> result, or the mapped exception (raised, or returned
    as an instance when ``raise_errors=False`` for pipelined batches)."""
    if response.get("ok"):
        return response.get("result")
    error = response.get("error")
    message = response.get("message", "")
    exc_cls = {
        "DuplicateKeyError": DuplicateKeyError,
        "KeyError": KeyError,
        "AuthenticationError": AuthenticationError,
    }.get(error)
    exc = exc_cls(message) if exc_cls else DatabaseError(f"{error}: {message}")
    if response.get("maybe_applied") and isinstance(exc, DatabaseError):
        exc.maybe_applied = True
    if response.get("not_primary") and isinstance(exc, DatabaseError):
        # The server refused a mutation because it is (now) a replica —
        # the epoch fence's wire form.  Nothing was applied; the sharded
        # router uses the marker to refresh its view of who the primary is
        # before the op-level retry re-runs.
        exc.not_primary = True
        exc.epoch = int(response.get("epoch", 0) or 0)
    if raise_errors:
        raise exc
    return exc


class NetworkDB:
    """AbstractDB-contract client for a :class:`DBServer`.

    Thread-safe: one socket guarded by a lock (requests are tiny; contention
    is on the server's DB lock anyway).  Idempotent reads reconnect and
    retry transparently across a server restart (``--persist``).  Mutations
    are never blindly re-sent; instead, a connection idle longer than
    ``idle_probe`` seconds is ping-probed (and re-established if dead)
    before a mutation uses it, so the common restart-while-idle case also
    succeeds.  Only a server death in the middle of an in-flight mutation
    surfaces as DatabaseError — the one case where applied-or-not is
    genuinely unknowable without server-side request ids.
    """

    #: A count is one small request/reply, vastly cheaper than shipping the
    #: full trial history over the wire (the producer's count-gated sync
    #: keys on this).
    cheap_counts = True

    def __init__(
        self, host="127.0.0.1", port=8765, timeout=60.0, idle_probe=1.0,
        secret=None, reconnect_jitter=0.1, jitter_seed=None,
    ):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.idle_probe = idle_probe
        self.secret = secret
        #: Herd control: a RE-connect (never the first connect) sleeps a
        #: full-jittered uniform draw in [0, reconnect_jitter) first, so N
        #: workers dropped by one server restart do not re-handshake in
        #: lockstep (op-level backoff was already jittered; the reconnect
        #: itself was not).  ``jitter_seed`` pins the stream for tests.
        self.reconnect_jitter = float(reconnect_jitter)
        self._jitter_rng = random.Random(jitter_seed)
        self._lock = threading.Lock()
        self._sock = None
        self._file = None
        self._last_used = 0.0
        #: Replication sequence stamped by the last response that carried
        #: one (mutations answered by a replicating primary; reads answered
        #: by a replica).  None until such a response arrives — plain
        #: servers never stamp.  Read via :meth:`seq_snapshot`.
        self.last_seq = None
        #: Replication epoch stamped next to the seq (promotion protocol);
        #: None until a stamped response arrives.
        self.last_epoch = None
        #: Socket send/receive cycles since construction (one per _call,
        #: one per pipeline/batch regardless of op count) — bench.py's
        #: storage breakdown reads this to prove a q-batch round costs O(1)
        #: wire round trips.
        self.round_trips = 0
        #: Request lines put on the wire: a pipeline of N ops writes N (the
        #: server runs N dispatch/persist cycles), the batch op writes 1.
        #: This is the per-round "wire operations" count the breakdown
        #: reports — the quantity the batch op takes from O(q) to O(1).
        self.wire_requests = 0
        #: Re-established connections (any _connect after the first):
        #: restarts, idle-probe failures, send-phase EPIPE resends.  A
        #: rising rate is THE first symptom of a flapping server/link —
        #: exported as the ``storage.network.reconnects`` telemetry counter.
        self.reconnects = 0
        self._ever_connected = False
        # Flipped when a server rejects the batch wire op (pre-batch
        # server); apply_batch then rides pipeline() instead.
        self._batch_unsupported = False

    # --- wire ----------------------------------------------------------------
    def _connect(self):
        TSAN.write("NetworkDB._conn", self)
        self._close()
        if self._ever_connected and self.reconnect_jitter > 0.0:
            # Full jitter BEFORE the dial: after a drop_all()-style restart
            # every client wakes at once, and without this spread they all
            # hit the listener (and redo the PBKDF2 handshake) in lockstep.
            time.sleep(self._jitter_rng.random() * self.reconnect_jitter)
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        if self._ever_connected:
            self.reconnects += 1
            # Reconnects are flight-recorder events (orion_tpu.health):
            # the first symptom of a flapping link belongs on the crash
            # timeline.  Guarded — no args allocation when disabled.
            if FLIGHT.enabled:
                FLIGHT.record(
                    "storage.reconnect",
                    args={"host": self.host, "port": self.port},
                )
        self._ever_connected = True
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._file = sock.makefile("rb")
        # lint: disable=LCK002 -- every caller of _connect holds _lock
        self._last_used = time.monotonic()
        if self.secret is not None:
            self._authenticate()

    def _authenticate(self):
        """Mutual HMAC handshake on a fresh connection (reconnects redo it):
        client proves first, then verifies the server proof released with
        the auth-ok reply — the shared :func:`perform_client_handshake`
        flow both wire surfaces use."""
        try:
            perform_client_handshake(
                self._exchange, self.secret, f"{self.host}:{self.port}"
            )
        except AuthenticationError:
            self._close()
            raise

    def _close(self):
        TSAN.write("NetworkDB._conn", self)
        for closer in (self._file, self._sock):
            if closer is not None:
                try:
                    closer.close()
                except OSError:  # pragma: no cover
                    pass
        self._sock = self._file = None

    def close(self):
        """Public teardown: ``_close`` is the internal caller-holds-_lock
        form — external owners (bench, tests, pools) must come through the
        lock or a concurrent request could race the socket teardown (the
        runtime sanitizer flags the bare form)."""
        with self._lock:
            self._close()

    def __getstate__(self):
        # Sockets don't cross fork/pickle; children reconnect lazily.
        return {
            "host": self.host,
            "port": self.port,
            "timeout": self.timeout,
            "secret": self.secret,
            "reconnect_jitter": self.reconnect_jitter,
        }

    def __setstate__(self, state):
        self.__init__(**state)

    # Ops safe to re-send after a dropped connection.  Mutating ops must NOT
    # be retried blindly: the server may have applied the request before the
    # reply was lost, and a re-send would double-apply it (a second trial
    # reserved, a spurious DuplicateKeyError on an insert that succeeded).
    # `snapshot` is a read; `promote` is idempotent by construction (a
    # resend at the same epoch reports the standing state, never re-flips).
    _IDEMPOTENT = frozenset(
        {"read", "count", "index_information", "ping", "seq", "snapshot",
         "promote"}
    )

    def _exchange(self, payload):
        """One request/response on the current socket; raises on any break.
        Round-trip latency feeds the ``storage.network.rtt`` telemetry
        histogram when the registry is enabled."""
        t0 = time.perf_counter() if TELEMETRY.enabled else None
        TSAN.write("NetworkDB._conn", self)
        self._sock.sendall(payload)
        response = _read_line(self._file)
        if response is None:
            raise ConnectionError("server closed the connection")
        self._last_used = time.monotonic()  # lint: disable=LCK002 -- caller holds _lock
        self.round_trips += 1  # lint: disable=LCK002 -- caller holds _lock
        self.wire_requests += 1  # lint: disable=LCK002 -- caller holds _lock
        self._note_seq(response)  # lint: disable=LCK002 -- caller holds _lock
        if t0 is not None:
            TELEMETRY.observe("storage.network.rtt", time.perf_counter() - t0)
        return response

    def _note_seq(self, response):
        """Track the replication sequence/epoch optionally stamped on a
        reply (see :attr:`last_seq`).  Callers hold ``_lock``."""
        if not isinstance(response, dict):
            return
        seq = response.get("seq")
        if seq is not None:
            self.last_seq = int(seq)  # lint: disable=LCK002 -- caller holds _lock
        epoch = response.get("epoch")
        if epoch is not None:
            self.last_epoch = int(epoch)  # lint: disable=LCK002 -- caller holds _lock

    def seq_snapshot(self):
        """Thread-safe read of :attr:`last_seq` (the sharded router compares
        a replica's read stamp against its primary's write stamp)."""
        with self._lock:
            return self.last_seq

    def stamp_snapshot(self):
        """Thread-safe ``(last_seq, last_epoch)`` — the router's fencing
        check reads both with one lock hold."""
        with self._lock:
            return self.last_seq, self.last_epoch

    def _probe_idle_connection(self):
        """Ping a connection that has sat idle so a mutation never rides a
        half-open socket from a restarted server."""
        if self._sock is None:
            return
        if time.monotonic() - self._last_used <= self.idle_probe:
            return
        try:
            self._exchange(_dumps({"op": "ping"}))
        except (OSError, ConnectionError, json.JSONDecodeError):
            self._close()  # mutation path will reconnect fresh

    @staticmethod
    def _wire_request(op, args, kwargs):
        """The request envelope, with the ambient TraceContext injected as
        the optional ``ctx`` field when telemetry is on — the server adopts
        it as the parent of its apply span.  Pre-upgrade servers ignore the
        key (wire-compatible), and a disabled registry pays one attribute
        check."""
        request = {"op": op, "args": list(args), "kwargs": kwargs}
        if TELEMETRY.enabled:
            ctx = current_trace_context()
            if ctx is not None and ctx.sampled:
                request["ctx"] = ctx.to_wire()
        return request

    def _call(self, op, *args, **kwargs):
        payload = _dumps(self._wire_request(op, args, kwargs))
        retriable = op in self._IDEMPOTENT
        with self._lock:
            for attempt in range(2):
                sent = False
                try:
                    if not retriable:
                        self._probe_idle_connection()
                    if self._sock is None:
                        self._connect()
                    response = self._exchange(payload)
                    break
                except (OSError, ConnectionError, json.JSONDecodeError) as exc:
                    sent = self._sock is not None
                    self._close()
                    if attempt or (sent and not retriable):
                        error = DatabaseError(
                            f"connection to {self.host}:{self.port} lost during "
                            f"{op!r}: {exc}"
                        )
                        # The request may have reached the server before the
                        # connection died: applied-or-not is unknowable, and
                        # the unified retry policy must not blindly re-send
                        # non-converging mutations (storage/retry.py).
                        error.maybe_applied = sent
                        raise error from exc
        return _translate(response)

    def pipeline(self, ops):
        """Execute ``[(op, args, kwargs), ...]`` over ONE round trip.

        All requests are written in a single send; the server's handler loop
        consumes them back-to-back off the stream (each op individually
        atomic, exactly as if sent one by one), and the responses are read in
        order afterwards.  This is what makes q-batch reservation affordable
        over the wire: q pipelined find-one-and-updates cost ~1 RTT instead
        of q serialized ones (the role MongoDB's wire batching plays for the
        reference, `mongodb.py:229-247`).

        Returns a list the same length as ``ops``: each element is the op's
        result, or an *exception instance* (DuplicateKeyError/KeyError/...)
        for that op — per-op failures must not abort the batch (a duplicate
        in slot 3 says nothing about slot 4).  A connection drop mid-batch
        raises DatabaseError: mutations may or may not have applied, same
        contract as a lost in-flight ``_call``.
        """
        if not ops:
            return []
        payload = b"".join(
            _dumps(self._wire_request(op, args, kwargs))
            for op, args, kwargs in ops
        )
        with self._lock:
            # Mirror _call's connect contract: nothing has been sent yet, so
            # one reconnect retry is safe, and a dead server surfaces as
            # DatabaseError (the type the CLI handles), never a raw OSError.
            try:
                self._probe_idle_connection()
                if self._sock is None:
                    self._connect()
            except (OSError, ConnectionError):
                self._close()
                try:
                    self._connect()
                except (OSError, ConnectionError) as exc:
                    # lint: disable=STO003 -- connect failed pre-send: nothing applied
                    raise DatabaseError(
                        f"cannot connect to {self.host}:{self.port} for "
                        f"pipeline of {len(ops)} ops: {exc}"
                    ) from exc
            # Responses are drained CONCURRENTLY with the send (reads and
            # writes ride opposite socket directions): a send-then-read
            # pipeline deadlocks once a big batch fills both kernel socket
            # buffers — the server blocks writing responses nobody reads,
            # stops consuming requests, and the client's sendall blocks too.
            rtt_t0 = time.perf_counter() if TELEMETRY.enabled else None
            responses, reader_error = [], []

            def _drain():
                try:
                    for _ in ops:
                        response = _read_line(self._file)
                        if response is None:
                            raise ConnectionError("server closed the connection")
                        responses.append(response)
                except Exception as exc:  # surfaced after join
                    reader_error.append(exc)

            reader = threading.Thread(target=_drain, daemon=True)
            reader.start()
            try:
                self._sock.sendall(payload)
            except OSError as exc:
                reader_error.append(exc)
            # No join deadline: the socket timeout already bounds each READ
            # (60s of silence = dead server, surfaced by the reader), so the
            # reader always terminates — while a big batch whose responses
            # are steadily streaming in may legitimately take longer than
            # any single-op timeout and must not be declared lost mid-flight.
            reader.join()
            if reader_error:
                exc = reader_error[0]
                self._close()
                error = DatabaseError(
                    f"connection to {self.host}:{self.port} lost during "
                    f"pipeline of {len(ops)} ops: {exc}"
                )
                # A prefix of the pipelined ops may have applied before the
                # connection died (the server dispatches line by line).
                error.maybe_applied = True
                raise error from exc
            self._last_used = time.monotonic()
            self.round_trips += 1
            self.wire_requests += len(ops)
            for r in responses:
                self._note_seq(r)
            if rtt_t0 is not None:
                # One histogram sample per socket round trip, same as
                # _exchange — the batch paths are the produce round's
                # dominant wire ops and must not be invisible in the rtt
                # signal.
                TELEMETRY.observe(
                    "storage.network.rtt", time.perf_counter() - rtt_t0
                )
        return [_translate(r, raise_errors=False) for r in responses]

    def apply_batch(self, ops):
        """Execute ``[(op, args, kwargs), ...]`` as ONE wire request/response.

        Tighter than :meth:`pipeline` (N request lines, N response lines,
        N server dispatch/persist cycles in ~1 RTT): the batch rides one
        request line, the server applies it as one atomic unit against the
        store — one lock hold, and in ``--persist x.sqlite`` mode ONE
        transaction/fsync for the whole q-batch — and answers with one
        response line of per-slot outcomes (results or exception
        instances, same contract as pipeline).

        The request reuses this instance's persistent socket.  A send-phase
        failure (EPIPE/ECONNRESET against a socket a restarted server
        closed) means the request line never fully reached the server, so
        nothing was applied and a reconnect + single resend is safe; only a
        failure AFTER the payload was handed off is genuinely unknowable
        and surfaces as DatabaseError.  Talking to a pre-batch server, the
        rejected op falls back to :meth:`pipeline` transparently (and stops
        re-trying the batch op on that instance)."""
        if not ops:
            return []
        if self._batch_unsupported:
            return self.pipeline(ops)
        # The batch's single RESPONSE line aggregates every sub-op result;
        # document-returning ops (read / read_and_write, e.g. a q-batch
        # reservation's claimed trial docs) at large op counts could push
        # it past the server's line cap — which the request-side guard
        # below cannot see.  Chunk those through pipeline's per-op
        # response lines (still ~1 RTT).
        if len(ops) > 512 and any(
            op in ("read", "read_and_write") for op, _, _ in ops
        ):
            return self.pipeline(ops)
        payload = _dumps(
            self._wire_request(
                "batch",
                [[[op, list(args), kwargs] for op, args, kwargs in ops]],
                {},
            )
        )
        if len(payload) > _MAX_LINE:
            # One line over the server's readline cap would be read as a
            # truncated request and silently dropped (surfacing as a
            # misleading "connection lost").  pipeline ships one line per
            # op, so an oversized batch rides it instead.
            return self.pipeline(ops)
        with self._lock:
            response = None
            for attempt in range(2):
                try:
                    # Shrink the applied-or-not window: a socket that sat
                    # idle across a server restart is ping-probed (and
                    # reconnected) before the batch rides it — sendall can
                    # succeed into the kernel buffer of a dead connection.
                    self._probe_idle_connection()
                    if self._sock is None:
                        self._connect()
                    rtt_t0 = time.perf_counter() if TELEMETRY.enabled else None
                    self._sock.sendall(payload)
                except (OSError, ConnectionError) as exc:
                    # Send phase: the request line was not fully delivered
                    # (a partial line is dropped by the server's readline),
                    # so retrying on a fresh connection cannot double-apply.
                    self._close()
                    if attempt:
                        # lint: disable=STO003 -- send-phase loss: nothing applied
                        raise DatabaseError(
                            f"cannot send batch of {len(ops)} ops to "
                            f"{self.host}:{self.port}: {exc}"
                        ) from exc
                    continue
                try:
                    response = _read_line(self._file)
                    if response is None:
                        raise ConnectionError("server closed the connection")
                except (OSError, ConnectionError, json.JSONDecodeError) as exc:
                    # Read phase: the server may or may not have applied the
                    # batch — same contract as a lost in-flight _call.
                    self._close()
                    error = DatabaseError(
                        f"connection to {self.host}:{self.port} lost during "
                        f"batch of {len(ops)} ops: {exc}"
                    )
                    error.maybe_applied = True
                    raise error from exc
                self._last_used = time.monotonic()
                self.round_trips += 1
                self.wire_requests += 1
                self._note_seq(response)
                if rtt_t0 is not None:
                    TELEMETRY.observe(
                        "storage.network.rtt", time.perf_counter() - rtt_t0
                    )
                break
        try:
            outcomes = _translate(response)
        except DatabaseError as exc:
            if "bad op 'batch'" in str(exc):
                # Pre-batch server: nothing was applied (the op was
                # rejected before dispatch) — downgrade to pipeline.
                self._batch_unsupported = True
                return self.pipeline(ops)
            raise
        return [_translate(r, raise_errors=False) for r in outcomes]

    # --- AbstractDB contract --------------------------------------------------
    def ping(self):
        return self._call("ping") == "pong"

    def ensure_index(self, collection, keys, unique=False):
        return self._call("ensure_index", collection, keys, unique=unique)

    def ensure_indexes(self, specs):
        return self._call("ensure_indexes", [list(s) for s in specs])

    def index_information(self, collection):
        return self._call("index_information", collection)

    def drop_index(self, collection, name):
        return self._call("drop_index", collection, name)

    def write(self, collection, data, query=None):
        return self._call("write", collection, data, query=query)

    def update_many(self, collection, pairs):
        """One pipelined round trip (see MemoryDB.update_many); the first
        per-op failure is raised after the whole batch has been drained."""
        results = self.pipeline(
            [("write", [collection, data], {"query": query})
             for query, data in pairs]
        )
        total = 0
        for result in results:
            if isinstance(result, Exception):
                raise result
            total += result
        return total

    def read(self, collection, query=None, projection=None):
        return self._call("read", collection, query=query, projection=projection)

    def read_and_write(self, collection, query, data):
        return self._call("read_and_write", collection, query, data)

    def count(self, collection, query=None):
        return self._call("count", collection, query=query)

    def remove(self, collection, query=None):
        return self._call("remove", collection, query=query)
