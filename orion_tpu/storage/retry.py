"""Unified retry/backoff policy for storage operations.

Before this module, fault handling was scattered and inconsistent: the
network driver hand-rolled its EPIPE reconnects, the pacemaker silently
swallowed every storage exception forever, and the other backends simply
let transient failures (a locked SQLite file, a contended flock, a
flapping server) crash the worker.  ``RetryPolicy`` is the ONE contract
all of them now share:

- **exponential backoff with jitter and a deadline** — attempt ``n``
  sleeps ``base_delay * multiplier**n`` (capped at ``max_delay``),
  jittered so a fleet of workers hammered by the same outage doesn't
  retry in lockstep, and bounded by both ``max_attempts`` and a wall
  clock ``deadline``;
- **transient-vs-fatal classification** shared by every caller:
  semantic outcomes (``DuplicateKeyError``, ``FailedUpdate``,
  ``AuthenticationError``, ``KeyError``) are *answers*, never retried;
  everything else in the ``DatabaseError`` family plus OS-level
  connection failures is presumed transient;
- **applied-or-not awareness**: an exception carrying
  ``maybe_applied=True`` (the network driver's lost-in-flight-mutation
  marker, ``exceptions.py``) is only retried for operations that
  *converge* under re-application — see the per-op contract table in
  ``docs/robustness.md``.  Non-converging ops give up immediately and
  surface the ambiguity to the caller;
- **telemetry**: every retry books a ``storage.retries`` counter tick +
  a ``storage.retry.backoff`` span (so retries are visible in a trace
  exactly where the round stalled), and every exhausted policy books
  ``storage.gave_up``.

``DocumentStorage`` applies a policy instance to every protocol op
(``storage/base.py``), so all four in-tree backends and any third-party
document backend get identical failure semantics; the worker loop and
pacemaker reuse the same classification for their coarser-grained
degradation (``core/worker.py``, ``core/pacemaker.py``).
"""

import random
import time

from orion_tpu.health import FLIGHT
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import (
    AuthenticationError,
    DatabaseError,
    DuplicateKeyError,
    FailedUpdate,
)

#: Exceptions that are semantic outcomes of the operation — retrying them
#: can only repeat the same answer (or worse, mask a real conflict).
FATAL_ERRORS = (DuplicateKeyError, FailedUpdate, AuthenticationError)

#: Retry modes — how an op behaves when the failed attempt MAY have been
#: durably applied (``exc.maybe_applied``):
#: - "always": the op converges under re-application (deterministic ids +
#:   unique indexes absorb a duplicate insert; absolute by-id updates are
#:   idempotent; an orphaned reservation is recovered by the lost-trial
#:   sweep) — retry any transient failure.
#: - "unapplied": the op does NOT converge (a was-guarded CAS re-applied
#:   after success reports a spurious conflict) — retry only failures
#:   that guarantee nothing was applied.
MODE_ALWAYS = "always"
MODE_UNAPPLIED = "unapplied"


def is_transient(exc):
    """True when ``exc`` is worth retrying: an infrastructure failure, not
    a semantic answer.  THE classification every retry loop (storage layer,
    worker loop, pacemaker) shares — two call sites disagreeing on what is
    transient is how silent retry-forever loops are born."""
    if isinstance(exc, FATAL_ERRORS):
        return False
    if isinstance(exc, DatabaseError):
        return True
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


class RetryPolicy:
    """Exponential backoff + jitter + deadline around a callable.

    Parameters mirror the ``storage.retry`` config section
    (docs/robustness.md): ``max_attempts`` total tries, delays growing as
    ``base_delay * multiplier**attempt`` capped at ``max_delay``, the
    whole affair bounded by ``deadline`` seconds of wall clock.
    ``jitter`` is the +/- fraction applied to each delay; ``seed`` pins
    the jitter stream for deterministic tests.  ``sleep`` is injectable
    for the same reason.
    """

    def __init__(
        self,
        max_attempts=4,
        base_delay=0.05,
        max_delay=2.0,
        multiplier=2.0,
        jitter=0.25,
        deadline=15.0,
        seed=None,
        sleep=time.sleep,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delay(self, attempt):
        """Backoff before retry number ``attempt`` (0-based), jittered."""
        # Exponent-capped: past ~2**64 the product is max_delay regardless,
        # and an unbounded float power overflows on long outages.
        raw = min(
            self.base_delay * self.multiplier ** min(attempt, 64), self.max_delay
        )
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        # Cap AFTER jitter: max_delay is a hard ceiling (at the cap, jitter
        # only shortens — fleets still de-synchronize on the way up).
        return max(0.0, min(raw, self.max_delay))

    def sleep(self, attempt, op="storage", span="storage.retry.backoff"):
        """Sleep one backoff step, booked as a span so stalls show up in
        traces where they happened.  ``span`` defaults to the storage
        layer's ``storage.retry.backoff``; non-storage reusers of the
        policy (producer duplicate backoff, worker reserve spacing) pass
        their own name so a healthy-but-contended run doesn't read as a
        struggling store in a trace."""
        duration = self.delay(attempt)
        if duration > 0.0:
            self._sleep(duration)
        # Guarded: the args dict must not be allocated when telemetry is
        # off — backoff sleeps sit inside the storage retry hot path.
        if TELEMETRY.enabled:
            TELEMETRY.record_span(
                span,
                duration=duration,
                args={"op": op, "attempt": attempt},
                histogram=False,
            )
        return duration

    def run(self, fn, op="storage", mode=MODE_ALWAYS):
        """Call ``fn()`` under this policy.

        Transient failures are retried with backoff until ``max_attempts``
        or ``deadline`` runs out; fatal failures raise immediately.  In
        ``mode="unapplied"`` a failure whose ``maybe_applied`` flag is set
        gives up at once (see MODE_UNAPPLIED above).  Gave-up failures
        re-raise the LAST exception after booking ``storage.gave_up``.
        """
        stop_at = (
            None if self.deadline is None else time.monotonic() + self.deadline
        )
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as exc:
                if not is_transient(exc):
                    raise
                if mode == MODE_UNAPPLIED and getattr(exc, "maybe_applied", False):
                    TELEMETRY.count("storage.gave_up")
                    raise
                attempt += 1
                out_of_budget = attempt >= self.max_attempts or (
                    stop_at is not None and time.monotonic() >= stop_at
                )
                if out_of_budget:
                    TELEMETRY.count("storage.gave_up")
                    # Guarded (TEL004): the args dict must not allocate on
                    # the disabled path — this sits inside the retry loop.
                    if FLIGHT.enabled:
                        FLIGHT.record(
                            "storage.gave_up",
                            args={"op": op, "attempts": attempt},
                        )
                    raise
                TELEMETRY.count("storage.retries")
                if FLIGHT.enabled:
                    FLIGHT.record(
                        "storage.retry", args={"op": op, "attempt": attempt}
                    )
                self.sleep(attempt - 1, op=op)


def create_retry_policy(config=None):
    """Build a policy from a ``storage.retry`` config section.

    ``None``/``{}`` -> the default policy; ``False`` -> no retries (the
    raw pre-policy behavior, for tests and callers that layer their own
    handling); a dict -> ``RetryPolicy(**dict)``; a ready policy instance
    passes through."""
    if config is False:
        return None
    if config is None or config == {}:
        return RetryPolicy()
    if isinstance(config, RetryPolicy):
        return config
    return RetryPolicy(**dict(config))
