"""Durable single-file backend with cross-process locking.

Capability parity: reference `src/orion/core/io/database/pickleddb.py` — every
operation takes an advisory file lock, unpickles the in-memory DB, applies the
op, and atomically rewrites the file (write-to-temp + rename).  The reference
uses the `filelock` package with a 60s timeout; here the lock is `fcntl.flock`
on a sidecar ``<path>.lock`` file (stdlib-only, correct across processes on
one node — the same guarantee the reference offers).
"""

import contextlib
import errno
import fcntl
import os
import pickle
import tempfile
import time

from orion_tpu.storage.documents import MemoryDB
from orion_tpu.utils.exceptions import DatabaseError

DEFAULT_LOCK_TIMEOUT = 60.0


class LockAcquisitionTimeout(DatabaseError):
    """Could not obtain the database file lock in time."""


def atomic_pickle_dump(path, obj):
    """Pickle ``obj`` to ``path`` atomically (tempfile in the target dir +
    rename) — shared by the pickled backend and the network server snapshot."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=dirname, prefix=".dbtmp-")
    try:
        with os.fdopen(fd, "wb") as handle:
            pickle.dump(obj, handle)
        os.replace(tmp, path)  # atomic on POSIX
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


@contextlib.contextmanager
def _file_lock(lock_path, timeout=DEFAULT_LOCK_TIMEOUT, poll=0.01):
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
    deadline = time.monotonic() + timeout
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as exc:
                if exc.errno not in (errno.EAGAIN, errno.EACCES, errno.EWOULDBLOCK):
                    raise  # real flock failure (e.g. ENOLCK) — don't mask as timeout
                if time.monotonic() >= deadline:
                    raise LockAcquisitionTimeout(
                        f"could not lock {lock_path} within {timeout}s"
                    )
                time.sleep(poll)
        yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


class PickledDB:
    """File-backed document DB; safe for many concurrent worker processes."""

    def __init__(self, path, lock_timeout=DEFAULT_LOCK_TIMEOUT):
        self.path = os.path.abspath(os.path.expanduser(path))
        self.lock_timeout = lock_timeout
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # Index definitions must survive reloads, so they are applied to the
        # pickled state itself on every ensure_index.

    @property
    def _lock_path(self):
        return self.path + ".lock"

    def _load(self):
        if not os.path.exists(self.path) or os.path.getsize(self.path) == 0:
            return MemoryDB()
        with open(self.path, "rb") as handle:
            return pickle.load(handle)

    def _dump(self, db):
        atomic_pickle_dump(self.path, db)

    @contextlib.contextmanager
    def _locked(self, write=True):
        with _file_lock(self._lock_path, timeout=self.lock_timeout):
            db = self._load()
            yield db
            if write:
                self._dump(db)

    # --- AbstractDB contract ------------------------------------------------
    def ensure_index(self, collection, keys, unique=False):
        with self._locked() as db:
            db.ensure_index(collection, keys, unique=unique)

    def ensure_indexes(self, specs):
        """All index definitions in ONE lock/load/dump cycle (worker startup
        happens per process; five separate cycles would rewrite the whole DB
        file five times under the shared lock)."""
        with self._locked() as db:
            db.ensure_indexes(specs)

    def index_information(self, collection):
        with self._locked(write=False) as db:
            return db.index_information(collection)

    def drop_index(self, collection, name):
        with self._locked() as db:
            db.drop_index(collection, name)

    def write(self, collection, data, query=None):
        with self._locked() as db:
            return db.write(collection, data, query)

    def update_many(self, collection, pairs):
        with self._locked() as db:
            return db.update_many(collection, pairs)

    def apply_batch(self, ops):
        """The whole batch in ONE lock/load/dump cycle (see
        MemoryDB.apply_batch for the outcome contract).  A q-batch
        registration otherwise pays q full unpickle+rewrite cycles — the
        dominant cost of this backend.  Successful slots persist even when
        a later slot fails (matching the sequential path: MemoryDB's
        insert checks uniqueness before mutating, so a failed slot leaves
        no partial state in the dumped snapshot)."""
        with self._locked() as db:
            return db.apply_batch(ops)

    def collection_names(self):
        """Enumeration surface shared by every backend (replication
        snapshots, `db dump`): one lock/load cycle over the inner store."""
        with self._locked(write=False) as db:
            return db.collection_names()

    def index_specs(self):
        with self._locked(write=False) as db:
            return db.index_specs()

    def read(self, collection, query=None, projection=None):
        with self._locked(write=False) as db:
            return db.read(collection, query, projection)

    def read_and_write(self, collection, query, data):
        with self._locked() as db:
            return db.read_and_write(collection, query, data)

    def count(self, collection, query=None):
        with self._locked(write=False) as db:
            return db.count(collection, query)

    def remove(self, collection, query=None):
        with self._locked() as db:
            return db.remove(collection, query)
